"""CPU denominators for the headline benchmark (VERDICT r2 item 3).

The north star (BASELINE.json) is ">=20x vs Spark local-mode" on the
AS-OF join + rolling-stats + EMA pipeline.  pyspark is not installed in
this image, so the denominator must be the strongest CPU implementation
of the same op set we can actually run.  This module measures EVERY
available oracle and reports the best; ``bench.py`` divides by the
strongest, not the friendliest.

Oracles (this image has ONE cpu — ``multiprocessing.cpu_count() == 1``
— so process-sharded pandas is pointless; duckdb/polars/numba are
absent, checked 2026-07-30):

* ``pandas`` — ``merge_asof(by=key)`` + groupby ``rolling('10s')``
  mean/std + groupby ``ewm(alpha).mean()``: the idiomatic single-node
  answer, and a *stronger* per-row baseline than Spark local-mode
  (argued in BASELINE.md).
* ``numpy`` — a hand-vectorised implementation of the same ops:
  searchsorted + last-valid-scan AS-OF (the reference's
  ``__getLastRightRow`` semantics), prefix-sum windowed mean/std with
  searchsorted range bounds, and the exact adjusted EWM via two
  ``scipy.signal.lfilter`` IIR recurrences.  Typically 3-6x faster
  per row than pandas; its outputs are asserted against pandas on
  every run, so the speed is not bought with wrong answers.

Run directly for one JSON line: {"oracles": {...rows/sec},
"strongest": name}.
"""

import json
import time

import numpy as np

WINDOW_SECS = 10.0
EWM_ALPHA = 0.2


# ----------------------------------------------------------------------
# pandas oracle
# ----------------------------------------------------------------------

def pandas_pipeline(left, right):
    import pandas as pd

    joined = pd.merge_asof(left, right, on="ts", by="key")
    g = joined.sort_values(["key", "ts"]).set_index("ts").groupby("key")["x"]
    roll = g.rolling("10s")
    mean = roll.mean()
    std = roll.std()
    ewm = joined.groupby("key")["x"].transform(
        lambda s: s.ewm(alpha=EWM_ALPHA).mean()
    )
    return joined, mean, std, ewm


# ----------------------------------------------------------------------
# numpy/scipy oracle — same ops, vectorised
# ----------------------------------------------------------------------

def numpy_pipeline(l_ts, l_x, l_key_starts, r_ts, r_vals, r_key_starts):
    """Per-key-sorted flat arrays in, joined cols + mean/std/ewm out.

    ``*_key_starts`` are [K+1] offsets of each key's row range; both
    sides are time-sorted within each key (the merge_asof precondition).
    """
    from scipy.signal import lfilter

    n = len(l_ts)
    K = len(l_key_starts) - 1
    joined = np.empty((len(r_vals), n))
    mean = np.empty(n)
    std = np.empty(n)
    ewm = np.empty(n)
    one_minus = 1.0 - EWM_ALPHA
    b, a = [1.0], [1.0, -one_minus]
    w_ns = np.int64(WINDOW_SECS * 1e9)

    for k in range(K):
        ls, le = l_key_starts[k], l_key_starts[k + 1]
        rs, re = r_key_starts[k], r_key_starts[k + 1]
        lt = l_ts[ls:le]
        lx = l_x[ls:le]
        # AS-OF: last right row at-or-before each left row.  Row-based
        # (nulls included), matching pandas merge_asof exactly — the
        # TPU pipeline additionally does per-column last-non-null
        # (skipNulls), so this denominator does no MORE work than the
        # numerator.
        pos = np.searchsorted(r_ts[rs:re], lt, side="right") - 1
        for c in range(len(r_vals)):
            rv = r_vals[c][rs:re]
            joined[c, ls:le] = np.where(
                pos >= 0, rv[np.maximum(pos, 0)], np.nan
            )
        # rolling mean/std over the trailing 10s range window:
        # prefix sums + searchsorted bounds.  pandas time-based rolling
        # is closed='right' — the window is (t-10s, t], excluding the
        # left edge (Spark's rangeBetween includes it; the denominator
        # follows the pandas oracle it is checked against)
        s = np.searchsorted(lt, lt - w_ns, side="right")
        c1 = np.concatenate([[0.0], np.cumsum(lx)])
        c2 = np.concatenate([[0.0], np.cumsum(lx * lx)])
        e = np.arange(1, le - ls + 1)
        cnt = e - s
        s1 = c1[e] - c1[s]
        s2 = c2[e] - c2[s]
        m = s1 / cnt
        mean[ls:le] = m
        var = (s2 - s1 * s1 / cnt) / np.maximum(cnt - 1, 1)
        std[ls:le] = np.where(cnt > 1, np.sqrt(np.maximum(var, 0.0)),
                              np.nan)
        # adjusted EWM y_t = num_t / den_t, both first-order IIRs
        num = lfilter(b, a, lx * EWM_ALPHA)
        den = lfilter(b, a, np.full(le - ls, EWM_ALPHA))
        ewm[ls:le] = num / den
    return joined, mean, std, ewm


# ----------------------------------------------------------------------
# harness
# ----------------------------------------------------------------------

def _frames(data, sub):
    import pandas as pd

    l_ts, l_secs, x, valid, r_ts, r_valids, r_values = data
    L = l_ts.shape[1]
    ks = np.repeat(np.arange(sub), L)
    left = pd.DataFrame({
        "key": ks,
        "ts": pd.to_datetime(l_ts[:sub].ravel()),
        "x": x[:sub].ravel().astype(np.float64),
    })
    C = r_valids.shape[0]
    rv = [np.where(r_valids[c, :sub], r_values[c, :sub], np.nan).ravel()
          for c in range(C)]
    right = pd.DataFrame({
        "key": ks,
        "ts": pd.to_datetime(r_ts[:sub].ravel()),
        **{f"v{c}": rv[c] for c in range(C)},
    })
    left = left.sort_values(["ts", "key"], kind="stable")
    right = right.sort_values(["ts", "key"], kind="stable")
    return left, right


def measure(data, sub=32, reps=3):
    """rows/sec of every oracle on a ``sub``-series slice; asserts the
    numpy oracle agrees with pandas before trusting its speed."""
    l_ts, l_secs, x, valid, r_ts, r_valids, r_values = data
    L = l_ts.shape[1]
    left, right = _frames(data, sub)
    n_rows = sub * L

    best_pd = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        pd_out = pandas_pipeline(left, right)
        best_pd = min(best_pd, time.perf_counter() - t0)

    # flat per-key-sorted inputs for the numpy oracle (layout prep is
    # not timed for either oracle: pandas gets pre-sorted frames too)
    starts = np.arange(sub + 1, dtype=np.int64) * L
    nl_ts = l_ts[:sub].ravel()
    nl_x = x[:sub].ravel().astype(np.float64)
    nr_ts = r_ts[:sub].ravel()
    nr_vals = [np.where(r_valids[c, :sub], r_values[c, :sub],
                        np.nan).ravel().astype(np.float64)
               for c in range(r_valids.shape[0])]

    best_np = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np_out = numpy_pipeline(nl_ts, nl_x, starts, nr_ts, nr_vals,
                                starts)
        best_np = min(best_np, time.perf_counter() - t0)

    _check_agreement(pd_out, np_out, sub, L)
    return {
        "pandas": n_rows / best_pd,
        "numpy_vectorized": n_rows / best_np,
    }


def _check_agreement(pd_out, np_out, sub, L):
    joined_pd, mean_pd, std_pd, ewm_pd = pd_out
    joined_np, mean_np, std_np, ewm_np = np_out
    # pandas frames are (ts, key)-sorted; numpy flat arrays are
    # (key, ts)-sorted — compare in (key, ts) order
    order = np.lexsort((joined_pd["ts"].to_numpy(),
                        joined_pd["key"].to_numpy()))
    for c in range(joined_np.shape[0]):
        np.testing.assert_allclose(
            joined_pd[f"v{c}"].to_numpy()[order], joined_np[c],
            rtol=1e-9, atol=1e-12, equal_nan=True,
        )
    np.testing.assert_allclose(mean_pd.to_numpy(), mean_np,
                               rtol=1e-9, atol=1e-12, equal_nan=True)
    np.testing.assert_allclose(std_pd.to_numpy(), std_np,
                               rtol=1e-9, atol=1e-9, equal_nan=True)
    np.testing.assert_allclose(ewm_pd.to_numpy()[order], ewm_np,
                               rtol=1e-9, atol=1e-12, equal_nan=True)


def strongest(data, sub=32):
    rates = measure(data, sub)
    name = max(rates, key=rates.get)
    return name, rates[name], rates


if __name__ == "__main__":
    import bench

    data = bench.make_data()
    name, rate, rates = strongest(data)
    print(json.dumps({
        "oracles": {k: round(v) for k, v in rates.items()},
        "strongest": name,
    }))
