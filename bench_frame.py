"""Frame-level end-to-end benchmark at quickstart scale (VERDICT r2
item 7).

Times what a user actually calls — pandas in -> ``TSDF.on_mesh`` ->
``asofJoin`` -> ``withRangeStats`` -> ``EMA`` -> ``collect`` — on an
HHAR-shaped workload (the reference quickstart's 13,062,475-row
phone<->watch accelerometer join, `Tempo QuickStart - Python.ipynb`
cell 3), reporting the three phases separately so the environment's
device<->host tunnel bound is quantified rather than asserted:

* ``t_pack``   — host packing + upload (``on_mesh`` + a forcing probe);
* ``t_device`` — the full op chain on-device, forced by fetching a
  data-dependent scalar (this backend materialises lazily — an
  un-fetched result may never execute, BASELINE.md round-2 notes);
* ``t_fetch``  — ``collect()``: ONE stacked device->host transfer plus
  host assembly back to pandas.

On this axon-tunnelled chip the transfer phases are bounded by the
~5-10 MB/s tunnel, three orders of magnitude below a TPU-VM host's
PCIe; ``rows_per_sec_device`` is the hardware-meaningful number,
``rows_per_sec_end_to_end`` is this environment's.  Scale with
TEMPO_BENCH_FRAME_ROWS (default the full 13M; CI smoke uses ~100k).

Prints ONE json line.
"""

import json
import os
import sys
import time

import numpy as np
import pandas as pd

import tempo_tpu  # noqa: F401
import jax
import jax.numpy as jnp

from tempo_tpu import TSDF
from tempo_tpu.parallel import make_mesh

N_ROWS = int(os.environ.get("TEMPO_BENCH_FRAME_ROWS", 13_062_475))
# 1024 integer partition keys (one 'user' column): ~12.8k rows/series
# keeps the merged join length inside the Pallas kernel's VMEM plan; at
# 128 keys the ~205k-lane XLA sort program OOM-killed the remote
# compile helper (measured 2026-07-30)
N_SERIES = 1024


def make_frames(n_rows=N_ROWS, n_series=N_SERIES, seed=0):
    """HHAR-shaped: n_series (user, device) keys, ~1-2 Hz accelerometer
    ticks, phone (left) joined against watch (right)."""
    rng = np.random.default_rng(seed)
    per = n_rows // n_series
    n = per * n_series
    keys = np.repeat(np.arange(n_series), per)
    gaps = rng.integers(1, 3, size=n).astype(np.int64)
    secs = np.concatenate(
        [np.cumsum(gaps[i * per: (i + 1) * per]) for i in range(n_series)]
    )
    ts = pd.to_datetime(secs * np.int64(1_000_000_000))
    left = pd.DataFrame({
        "user": keys, "event_ts": ts,
        "x": rng.standard_normal(n).astype(np.float64),
    })
    right = pd.DataFrame({
        "user": keys,
        "event_ts": pd.to_datetime(
            (secs - rng.integers(0, 3, size=n)) * np.int64(1_000_000_000)
        ),
        "wx": np.where(rng.random(n) > 0.05,
                       rng.standard_normal(n), np.nan),
    })
    return left, right, n


def main():
    left, right, n = make_frames()
    mesh = make_mesh({"series": len(jax.devices())})

    t0 = time.perf_counter()
    dl = TSDF(left, "event_ts", ["user"]).on_mesh(mesh)
    dr = TSDF(right, "event_ts", ["user"]).on_mesh(mesh)
    # force the uploads: a data-dependent scalar fetch (lazy backend)
    float(jnp.asarray(dl.ts).sum() + jnp.asarray(dr.ts).sum())
    t_pack = time.perf_counter() - t0

    def chain():
        t0 = time.perf_counter()
        out = (
            dl.asofJoin(dr)
            .withRangeStats(colsToSummarize=["x"], rangeBackWindowSecs=10)
            .EMA("x", exact=True)
        )
        # force the whole chain without fetching the planes
        float(jnp.nan_to_num(out.cols["EMA_x"].values).sum()
              + jnp.nan_to_num(out.cols["mean_x"].values).sum()
              + jnp.nan_to_num(out.cols["right_wx"].values).sum())
        return out, time.perf_counter() - t0

    out, t_device = chain()          # cold: includes jit compiles
    _, t_device_warm = chain()       # warm: compiled programs cached

    t0 = time.perf_counter()
    df = out.collect().df
    t_fetch = time.perf_counter() - t0
    assert len(df) == n, (len(df), n)

    fetched_mb = sum(
        df[c].to_numpy().nbytes for c in df.columns
    ) / 1e6
    print(json.dumps({
        "metric": "frame-level pandas->mesh->asofJoin+rangeStats+EMA->collect",
        "rows": n,
        "t_pack_s": round(t_pack, 2),
        "t_device_s": round(t_device, 2),
        "t_device_warm_s": round(t_device_warm, 2),
        "t_fetch_s": round(t_fetch, 2),
        "rows_per_sec_device": round(n / t_device_warm),
        "rows_per_sec_end_to_end": round(n / (t_pack + t_device + t_fetch)),
        "collect_mb": round(fetched_mb),
        "tunnel_note": "pack/fetch ride the axon tunnel (~5-10 MB/s); "
                       "on a TPU-VM host these phases are PCIe-bound",
    }))


if __name__ == "__main__":
    sys.exit(main())
