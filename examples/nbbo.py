"""Capital-markets flow: skewed NBBO quotes <-> trades AS-OF join.

Mirrors BASELINE.md configs 4-5 (the reference's capital-markets
reference architecture): a Zipf-skewed symbol universe where a handful
of tickers carry most of the volume — exactly the shape Spark needs the
``tsPartitionVal`` skew join for (reference tsdf.py:164-190).  Shows:

* the plain vs skew-partitioned asofJoin agreeing row-for-row,
* quote staleness audit via the joined quote timestamps,
* per-symbol VWAP bars on the trades.

Run: python examples/nbbo.py  (TPU or JAX_PLATFORMS=cpu)
"""

import os
import sys
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from tempo_tpu import TSDF

N_SYMBOLS = 50
N_QUOTES = 200_000
N_TRADES = 50_000


def make_tape(seed=7):
    rng = np.random.default_rng(seed)
    # Zipf-skewed symbol draw: symbol 0 carries ~100x symbol 49's flow
    weights = 1.0 / (np.arange(N_SYMBOLS) + 1.0)
    weights /= weights.sum()
    syms = np.array([f"SYM{i:03d}" for i in range(N_SYMBOLS)])

    def tape(n, cols):
        sym = rng.choice(N_SYMBOLS, size=n, p=weights)
        ts = (pd.Timestamp("2024-01-02 09:30").value
              + np.sort(rng.integers(0, 6.5 * 3600 * 1e9, size=n).astype(np.int64)))
        df = pd.DataFrame({"symbol": syms[sym],
                           "event_ts": pd.to_datetime(ts)})
        mid = 100.0 + sym * 2.0
        for c in cols:
            noise = rng.standard_normal(n)
            df[c] = mid + noise if c != "trade_qty" else rng.integers(1, 500, n)
        return df

    quotes = tape(N_QUOTES, ["bid_pr", "ask_pr"])
    trades = tape(N_TRADES, ["trade_pr", "trade_qty"])
    return quotes, trades


def main():
    quotes, trades = make_tape()
    q = TSDF(quotes, "event_ts", ["symbol"])
    t = TSDF(trades, "event_ts", ["symbol"])

    t0 = time.perf_counter()
    plain = t.asofJoin(q, right_prefix="quote")
    print(f"plain asofJoin: {len(plain.df)} rows in {time.perf_counter()-t0:.2f}s")

    t0 = time.perf_counter()
    skew = t.asofJoin(q, right_prefix="quote", tsPartitionVal=1800,
                      fraction=0.5, suppress_null_warning=True)
    print(f"skew  asofJoin: {len(skew.df)} rows in {time.perf_counter()-t0:.2f}s")

    both = plain.df.merge(skew.df, on=["symbol", "event_ts"], suffixes=("", "_skew"))
    same = (both["quote_bid_pr"].fillna(-1) == both["quote_bid_pr_skew"].fillna(-1)).all()
    print(f"plain == skew (where lookback covered): {bool(same)}")

    staleness = (plain.df["event_ts"] - plain.df["quote_event_ts"]).dt.total_seconds()
    print(f"median quote staleness at trade time: {staleness.median():.2f}s")

    vw = t.vwap(frequency="H", volume_col="trade_qty", price_col="trade_pr")
    print("hourly VWAP (head):")
    print(vw.df.head(5).to_string(index=False))


if __name__ == "__main__":
    main()
