"""tempo-tpu quickstart: the reference's HHAR phone<->watch flow.

Replicates the dbl-tempo README quickstart (reference
`Tempo QuickStart - Python.ipynb`: UCI HHAR accelerometer data, phone
readings AS-OF joined against watch readings, rolling stats, resample,
EMA, interpolation, columnar write) on synthetic accelerometer-like
data so it runs anywhere.

    JAX_PLATFORMS=cpu python examples/quickstart.py
"""

import os
import sys
import tempfile

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from tempo_tpu import TSDF, display  # noqa: E402


def synth_accel(n_users=5, n_per_user=2000, device="phone", seed=0):
    """Accelerometer-like stream: (user, ts, x, y, z) at ~50ms cadence
    with jitter, a few nulls, per-user drift."""
    rng = np.random.default_rng(seed + (0 if device == "phone" else 1))
    frames = []
    for u in range(n_users):
        gaps = rng.integers(30, 70, size=n_per_user).cumsum()
        ts = pd.Timestamp("2024-03-01") + pd.to_timedelta(gaps, unit="ms")
        walk = rng.standard_normal((n_per_user, 3)).cumsum(axis=0) * 0.02
        xyz = walk + rng.standard_normal((n_per_user, 3)) * 0.5
        df = pd.DataFrame({
            "User": f"user_{u}",
            "event_ts": ts,
            "x": xyz[:, 0], "y": xyz[:, 1], "z": xyz[:, 2],
        })
        df.loc[df.sample(frac=0.01, random_state=u).index, "z"] = np.nan
        frames.append(df)
    return pd.concat(frames, ignore_index=True)


def main():
    phone = synth_accel(device="phone")
    watch = synth_accel(device="watch")
    print(f"phone rows: {len(phone)}, watch rows: {len(watch)}")

    phone_tsdf = TSDF(phone, ts_col="event_ts", partition_cols=["User"])
    watch_tsdf = TSDF(watch, ts_col="event_ts", partition_cols=["User"])

    # 1. AS-OF join: each phone reading annotated with the latest watch
    #    reading at or before it (README quickstart's headline op)
    joined = phone_tsdf.asofJoin(watch_tsdf, right_prefix="watch_accel")
    print("\nAS-OF joined:")
    display(joined.limit(5))

    # 2. Rolling range stats over a 10-second lookback
    stats = phone_tsdf.withRangeStats(colsToSummarize=["z"], rangeBackWindowSecs=10)
    print("\n10s rolling stats on z:")
    display(stats.select("User", "event_ts", "mean_z", "stddev_z", "zscore_z").limit(5))

    # 3. Resample to 1-second bars (closest-record floor semantics)
    bars = phone_tsdf.resample(freq="sec", func="floor")
    print(f"\nresampled rows: {len(bars.df)}")

    # 4. EMA on z (reference-compat truncated-lag EMA)
    ema = phone_tsdf.EMA("z", window=30)
    print("\nEMA tail:")
    display(ema.select("User", "event_ts", "z", "EMA_z").limit(5))

    # 5. Gap-fill: resample to a 1s grid, linearly interpolate
    interp = phone_tsdf.interpolate(freq="sec", func="mean", method="linear")
    print(f"\ninterpolated rows: {len(interp.df)}")

    # 6. Columnar write (the Delta-writer analog)
    with tempfile.TemporaryDirectory() as d:
        joined.write(os.path.join(d, "phone_watch_joined"))
        written = [f for f in os.listdir(d)]
        print(f"\nwrote table dirs: {written}")

    print("\nquickstart OK")


if __name__ == "__main__":
    main()
