"""Distributed pipeline: the quickstart chain on a device mesh.

Shows the round-2 distribution surface (the analog of the reference's
implicit Spark distribution, SURVEY.md §2.3):

* ``TSDF.on_mesh(mesh, time_axis=...)`` — pack + shard once,
* a device-resident chain (asofJoin -> EMA -> withRangeStats ->
  resample -> interpolate) with ONE host fetch at the end,
* a mid-pipeline checkpoint resumed on a different mesh shape,
* the audit/warning surface for halo-truncated windows.

Run on any host:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/distributed.py
(on a real TPU pod slice, drop both env vars — the mesh axes map to
real chips and the collectives ride ICI.)
"""

import os
import sys
import tempfile
import time

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

from tempo_tpu import TSDF, checkpoint  # noqa: E402
from tempo_tpu.parallel import make_mesh  # noqa: E402

rng = np.random.default_rng(0)
N = 20_000
SYMS = [f"S{i:02d}" for i in range(12)]


def make_frame(value_col):
    n = N
    return TSDF(pd.DataFrame({
        "symbol": rng.choice(SYMS, n),
        "event_ts": pd.to_datetime(
            np.sort(rng.integers(0, 7200, n)) * 1_000_000_000),
        value_col: np.where(rng.random(n) > 0.05,
                            rng.standard_normal(n) + 100, np.nan),
        "venue": rng.choice(["NYS", "NSQ", "ARC"], n),
    }), "event_ts", ["symbol"])


def main():
    n_dev = len(jax.devices())
    n_time = 2 if n_dev % 2 == 0 and n_dev > 1 else 1
    mesh = make_mesh({"series": n_dev // n_time, "time": n_time})
    print(f"mesh: {dict(mesh.shape)} over {n_dev} {jax.devices()[0].platform} devices")

    trades = make_frame("price")
    quotes = make_frame("bid")

    t0 = time.perf_counter()
    dt = trades.on_mesh(mesh, time_axis="time" if n_time > 1 else None)
    dq = quotes.on_mesh(mesh, time_axis="time" if n_time > 1 else None)
    joined = (
        dt.asofJoin(dq)                       # quotes onto trades
        .EMA("price", exact=True)             # exact scan EMA
        .withRangeStats(colsToSummarize=["price"], rangeBackWindowSecs=600)
    )

    # snapshot mid-pipeline, resume on a series-only mesh (elastic
    # re-placement), then keep chaining
    ckpt = os.path.join(tempfile.mkdtemp(), "pipeline_ckpt")
    checkpoint.save(joined, ckpt)
    resumed = checkpoint.load(ckpt, mesh=make_mesh({"series": n_dev}))
    bars = resumed.resample("5 minutes", "mean") \
        .interpolate(method="linear", target_cols=["price"])

    out = bars.collect().df
    dt_s = time.perf_counter() - t0
    print(f"pipeline (join+EMA+stats -> checkpoint -> resample+interpolate) "
          f"in {dt_s:.1f}s; {len(out)} dense bars")
    print(out.head(8).to_string(index=False))


if __name__ == "__main__":
    main()
