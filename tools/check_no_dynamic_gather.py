#!/usr/bin/env python
"""Ban dynamic-gather ops in the Pallas kernel modules.

The regression this guards against: the prefix-scan + RMQ rolling path
was gather-bound for two rounds (~96 ms per ``take_along_axis`` level
at [1024, 8192], BENCH_r05 ``2b_range_stats_dense_50hz`` at 8.0M
rows/s — below one CPU core) because per-lane dynamic gathers are the
one data-movement primitive this hardware cannot do at speed, and
Mosaic cannot lower them inside kernels at all (it falls back to
scalar loops or rejects the op).  Every kernel in ``ops/pallas_*.py``
is built from the primitives that ARE fast — ``pltpu.roll``, sorts,
``broadcasted_iota`` masks — and this check keeps it that way: any
call to a gather/scatter-shaped jnp/lax op anywhere in those modules
fails the suite.

Flagged call names (as attribute or bare calls):
``take_along_axis``, ``take``, ``gather``, ``dynamic_slice``,
``dynamic_update_slice``, ``dynamic_index_in_dim``, ``searchsorted``,
``scatter``, ``scatter_add``, ``at[...]``-style ``.get``/``.set`` are
not detectable syntactically and are left to review.

A genuinely-needed exception (e.g. host-side plumbing in the same
file) is whitelisted by putting the marker comment
``# gather-ok: <reason>`` on the SAME line as the call.

Wired into the test run via tests/test_tooling.py; also runnable
standalone: ``python tools/check_no_dynamic_gather.py [paths...]``
(default: tempo_tpu/ops/pallas_*.py next to this script).  Exit code 1
when violations exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

Violation = Tuple[Path, int, str]

BANNED = {
    "take_along_axis",
    "take",
    "gather",
    "dynamic_slice",
    "dynamic_update_slice",
    "dynamic_index_in_dim",
    "searchsorted",
    "scatter",
    "scatter_add",
}

MARKER = "# gather-ok:"


def _call_name(node: ast.Call) -> str:
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def check_file(path: Path) -> List[Violation]:
    violations: List[Violation] = []
    try:
        text = path.read_text()
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    lines = text.splitlines()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name not in BANNED:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if MARKER in line:
            continue
        violations.append((
            path, node.lineno,
            f"dynamic-gather-shaped call '{name}' in a Pallas kernel "
            f"module (the pattern behind the dense-regime regression; "
            f"use roll/sort/iota primitives, or annotate the line with "
            f"'{MARKER} <reason>' if it provably never runs on-chip)",
        ))
    return violations


def default_paths() -> List[Path]:
    ops = Path(__file__).resolve().parent.parent / "tempo_tpu" / "ops"
    return sorted(ops.glob("pallas_*.py"))


def main(argv: List[str]) -> int:
    paths: List[Path] = []
    for arg in argv or [str(p) for p in default_paths()]:
        p = Path(arg)
        if p.is_dir():
            paths.extend(sorted(p.glob("pallas_*.py")))
        else:
            paths.append(p)
    violations: List[Violation] = []
    for p in paths:
        violations.extend(check_file(p))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} violation(s)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
