#!/usr/bin/env python
"""Ban dynamic-gather ops in the Pallas kernel modules — shim over the
analysis framework.

The actual rule lives in ``tools/analysis/rules/gather.py``
(``dynamic-gather``, part of ``python tools/analyze.py``) and now also
catches what this script's first revision punted on: aliased imports,
``getattr`` indirection, and the ``.at[...].get/.set`` forms.  This
wrapper keeps the historical CLI: ``python
tools/check_no_dynamic_gather.py [paths...]`` (default:
``tempo_tpu/ops/pallas_*.py`` plus — since the framework migration —
``tools/`` and ``tests/helpers.py``), exit code 1 when violations
exist.  The legacy same-line ``# gather-ok: <reason>`` marker still
suppresses, as does ``# lint-ok: dynamic-gather: <reason>``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Tuple

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.analysis import core  # noqa: E402
from tools.analysis.rules import DynamicGatherRule  # noqa: E402
from tools.analysis.rules.gather import BANNED  # noqa: E402,F401

Violation = Tuple[Path, int, str]

_RULE = DynamicGatherRule()

MARKER = "# gather-ok:"  # legacy suppression, still honoured


def check_file(path: Path) -> List[Violation]:
    mod = core.ModuleSource(path)
    if mod.parse_error is not None:
        e = mod.parse_error
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    return [(v.path, v.line, v.message) for v in _RULE.check(mod)]


def default_paths() -> List[Path]:
    ops = _REPO / "tempo_tpu" / "ops"
    return (sorted(ops.glob("pallas_*.py"))
            + core.iter_py_files([_REPO / "tools"])
            + [_REPO / "tests" / "helpers.py"])


def main(argv: List[str]) -> int:
    paths: List[Path] = []
    for arg in argv or [str(p) for p in default_paths()]:
        p = Path(arg)
        if p.is_dir():
            paths.extend(sorted(p.glob("pallas_*.py")))
        else:
            paths.append(p)
    violations: List[Violation] = []
    for p in paths:
        violations.extend(check_file(p))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} violation(s)")
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
