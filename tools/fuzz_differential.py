"""Differential fuzz: tempo_tpu ops vs pandas oracles, adversarial shapes.

Usage:
    JAX_PLATFORMS=cpu FUZZ_SEEDS=60 python tools/fuzz_differential.py   # exact f64
    FUZZ_SEEDS=6 FUZZ_ATOL=1e-4 python tools/fuzz_differential.py       # on TPU, f32

Adversarial modes per seed: plain, all-tied timestamps, sub-second
timestamps, all-null metric, shuffled input order.  Exits non-zero on
any divergence.  (Kept out of the default pytest run for time; CI runs
the fixed-fixture + property suites.)
"""
import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np
import pandas as pd

import tempo_tpu
from tempo_tpu import TSDF

ATOL = float(os.environ.get("FUZZ_ATOL", "1e-9"))
N_SEEDS = int(os.environ.get("FUZZ_SEEDS", "60"))

fails = []


def frame(rng, adversarial):
    n_keys = int(rng.integers(1, 6))
    n = int(rng.integers(1, 120))
    keys = rng.integers(0, n_keys, n)
    secs = rng.integers(-50, 200, n).astype(float)
    if adversarial == "allties":
        secs[:] = 42.0
    elif adversarial == "subsec":
        secs = secs + rng.random(n)
    ts = pd.Timestamp("2024-01-01") + pd.to_timedelta((secs * 1000).astype(int), unit="ms")
    v = rng.standard_normal(n)
    if adversarial == "allnull":
        v[:] = np.nan
    else:
        v[rng.random(n) < 0.2] = np.nan
    df = pd.DataFrame({"k": np.char.add("s", keys.astype(str)), "ts": ts, "v": v})
    if adversarial == "shuffled":
        df = df.sample(frac=1.0, random_state=int(rng.integers(1 << 30))).reset_index(drop=True)
    return df


def check(name, seed, adv, fn):
    try:
        fn()
    except Exception:
        fails.append((name, seed, adv, traceback.format_exc(limit=4)))


def oracle_asof(left, right):
    rows = []
    for (k, lts) in left[["k", "ts"]].itertuples(index=False):
        sub = right[(right.k == k) & (right.ts <= lts)]
        rv = sub.sort_values("ts", kind="stable")["v"].dropna()
        rows.append(rv.iloc[-1] if len(rv) else np.nan)
    return np.array(rows)


def t_asof(rng, adv):
    left, right = frame(rng, adv), frame(rng, adv)
    tl = TSDF(left, "ts", ["k"])
    tr = TSDF(right, "ts", ["k"])
    got = tl.asofJoin(tr).df.sort_values(["k", "ts"], kind="stable").reset_index(drop=True)
    ls = left.sort_values(["k", "ts"], kind="stable").reset_index(drop=True)
    want = oracle_asof(ls, right)
    np.testing.assert_allclose(got["right_v"].to_numpy(dtype=float), want,
                               atol=ATOL, rtol=1e-5, equal_nan=True)


def t_asof_sequence(rng, adv):
    """Merge-path AS-OF with a sequence tie-break.  Left rows carry a
    null sequence, which sorts NULLS FIRST (tsdf.py:117-121): at a tied
    timestamp the left row precedes every right row with a non-null
    sequence, so only strictly-earlier right rows are eligible."""
    left, right = frame(rng, adv), frame(rng, adv)
    right = right.assign(seq=rng.integers(0, 50, len(right)))

    tl = TSDF(left, "ts", ["k"])
    tr = TSDF(right, "ts", ["k"], sequence_col="seq")
    got = tl.asofJoin(tr).df.sort_values(["k", "ts"], kind="stable").reset_index(drop=True)

    rs = right.sort_values(["ts", "seq"], kind="stable")
    rows = []
    for (k, lts) in (
        left.sort_values(["k", "ts"], kind="stable")[["k", "ts"]]
        .itertuples(index=False)
    ):
        sub = rs[(rs.k == k) & (rs.ts < lts)]["v"].dropna()
        rows.append(sub.iloc[-1] if len(sub) else np.nan)
    np.testing.assert_allclose(got["right_v"].to_numpy(dtype=float),
                               np.array(rows), atol=ATOL, rtol=1e-5,
                               equal_nan=True)


def t_asof_max_lookback(rng, adv):
    """Scala maxLookback (asofJoin.scala:64-88): the lookback is a ROW
    cap on the merged left+right stream ordered by (ts, rec) with right
    rows before left rows at a tied timestamp."""
    left, right = frame(rng, adv), frame(rng, adv)
    cap = int(rng.integers(1, 6))
    tl = TSDF(left, "ts", ["k"])
    tr = TSDF(right, "ts", ["k"])
    got = (
        tl.asofJoin(tr, maxLookback=cap)
        .df.sort_values(["k", "ts"], kind="stable").reset_index(drop=True)
    )

    rows = []
    for k, lg in left.sort_values(["k", "ts"], kind="stable").groupby("k", sort=False):
        stream = []  # (ts, rec, is_right, v) in merged order
        for t, v in right[right.k == k].sort_values("ts", kind="stable")[["ts", "v"]].itertuples(index=False):
            stream.append((t, -1, True, v))
        for t in lg["ts"]:
            stream.append((t, 1, False, np.nan))
        stream.sort(key=lambda r: (r[0].value, r[1]))
        for p, (t, rec, is_right, _) in enumerate(stream):
            if is_right:
                continue
            lo = max(0, p - cap)
            vals = [v for (tt, rr, ir, v) in stream[lo:p + 1]
                    if ir and not (isinstance(v, float) and np.isnan(v))]
            rows.append((k, t, vals[-1] if vals else np.nan))
    want = pd.DataFrame(rows, columns=["k", "ts", "want"]).sort_values(
        ["k", "ts"], kind="stable").reset_index(drop=True)
    np.testing.assert_allclose(got["right_v"].to_numpy(dtype=float),
                               want["want"].to_numpy(), atol=ATOL,
                               rtol=1e-5, equal_nan=True)


def t_rangestats(rng, adv):
    df = frame(rng, adv)
    W = int(rng.integers(1, 30))
    got = TSDF(df, "ts", ["k"]).withRangeStats(colsToSummarize=["v"],
                                               rangeBackWindowSecs=W).df
    for i, (k, ts) in enumerate(got[["k", "ts"]].itertuples(index=False)):
        tl = df.ts.astype("datetime64[ns]").astype("int64") // 10**9
        me = ts.value // 10**9
        sub = df[(df.k == k) & (tl >= me - W) & (tl <= me)]
        vv = sub["v"].dropna()
        want_cnt = len(vv)
        assert int(got["count_v"].iloc[i]) == want_cnt, (i, k, ts)
        if want_cnt:
            np.testing.assert_allclose(got["mean_v"].iloc[i], vv.mean(),
                                       atol=ATOL, rtol=1e-5)
            np.testing.assert_allclose(got["min_v"].iloc[i], vv.min(),
                                       atol=ATOL, rtol=1e-5)
            np.testing.assert_allclose(got["max_v"].iloc[i], vv.max(),
                                       atol=ATOL, rtol=1e-5)


def t_resample_interp(rng, adv):
    df = frame(rng, adv)
    r = TSDF(df, "ts", ["k"]).resample("min", "mean")
    assert len(r.df) >= 1 or len(df) == 0
    out = r.interpolate(method="ffill")
    assert len(out.df) >= len(r.df)


def t_grouped_ema_vwap(rng, adv):
    df = frame(rng, adv)
    t = TSDF(df, "ts", ["k"])
    t.withGroupedStats(metricCols=["v"], freq="1 minute")
    t.EMA("v", window=5)
    t.EMA("v", exact=True)
    df2 = df.rename(columns={"v": "price"}).assign(volume=np.abs(rng.standard_normal(len(df))) + 0.1)
    TSDF(df2, "ts", ["k"]).vwap(frequency="m")
    t.describe()
    if len(df) > 2:
        t.autocorr("v", 1)


def t_fourier_lookback(rng, adv):
    df = frame(rng, adv)
    t = TSDF(df, "ts", ["k"])
    t.fourier_transform(1.0, "v")
    t.withLookbackFeatures(["v"], 3, exactSize=False)


def main():
    ADVS = [None, "allties", "subsec", "allnull", "shuffled"]
    TESTS = [t_asof, t_asof_sequence, t_asof_max_lookback, t_rangestats, t_resample_interp, t_grouped_ema_vwap, t_fourier_lookback]

    for seed in range(N_SEEDS):
        for adv in ADVS:
            rng = np.random.default_rng(seed * 37 + hash(adv or "x") % 1000)
            for fn in TESTS:
                check(fn.__name__, seed, adv, lambda: fn(np.random.default_rng(seed * 101 + 7), adv))
        # every shape fuzzed is a fresh compile; holding thousands of
        # executables live exhausts the process mmap budget
        # (vm.max_map_count) — LLVM then fails allocation and jaxlib
        # segfaults (observed ~30 seeds in).  Same mitigation as the
        # test suite's per-module fixture.
        if seed % 3 == 2:
            jax.clear_caches()

    print(f"fuzz done: {len(fails)} failures")
    for name, seed, adv, tb in fails[:6]:
        print("=" * 70)
        print(name, "seed", seed, "adv", adv)
        print(tb)
    sys.exit(1 if fails else 0)


if __name__ == "__main__":
    main()
