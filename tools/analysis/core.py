"""Core of the kernel-safety static analyzer.

One engine replaces the per-bug-class scripts that accreted in
``tools/`` (``check_no_bare_except.py``, ``check_no_dynamic_gather.py``
— both now thin shims over this framework): every rule shares one
parse per file, one suppression syntax, and one reporting/exit-code
contract, so adding the next bug-class check is a ~50-line rule module
instead of another standalone script.

Contract
--------

* :class:`ModuleSource` — a lazily parsed source file (text, split
  lines, ``ast`` tree) shared by every rule; a file that does not
  parse yields a single ``parse-error`` violation instead of crashing
  the run.
* :class:`Rule` — subclasses define ``name`` (the kebab-case id used
  in suppressions and the CLI), ``code`` (a distinct power-of-two exit
  bit), ``applies(path)`` (the file filter), and ``check(mod)``;
  whole-tree consistency rules additionally implement
  ``check_project(root, files)``, which runs once per invocation.
* Suppression — ``# lint-ok: <rule>: <reason>`` on the flagged line
  silences that rule there; the reason is mandatory (a bare marker
  does not suppress).  Rules may also declare ``legacy_markers``
  (e.g. ``# gather-ok:``) kept for pre-framework annotations.
* Dead-suppression audit — a ``# lint-ok:`` comment whose rule never
  fires on that line is itself a violation
  (:data:`DEAD_SUPPRESSION_CODE`): the code it once excused has moved
  or been fixed, and a stale marker left in place would silently
  swallow the next *real* finding on that line.  Only actual comment
  tokens count (docstrings and string literals that merely *mention*
  the marker syntax are ignored), and the audit runs only when the
  full battery does — a ``--rule``-filtered run cannot know whether an
  unselected rule would have used a marker.
* Exit codes — :func:`run` returns the bitwise OR of the ``code`` of
  every rule that fired, so a CI log's exit status alone names the
  failing rule families (``parse-error`` contributes
  :data:`PARSE_ERROR_CODE`).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

#: Exit bit for files that fail to parse (or read) at all.  The low 7
#: bits (1..64) stayed under the shell's 128+signal convention
#: (130 = SIGINT, 137 = SIGKILL) so a bare exit status alone named the
#: failing families; with the 8th rule family (plan-registry, bit 128)
#: that nicety no longer fully holds — a status >= 128 here is always
#: accompanied by the per-rule summary on stderr, which remains the
#: authoritative breakdown (signal deaths print no summary).
PARSE_ERROR_CODE = 64

#: Exit bit of the dead-suppression audit.  NOTE: past bit 7 the
#: 8-bit process exit status can no longer carry the raw OR —
#: ``tools/analyze.py`` folds it (nonzero-preserving) and the stderr
#: per-rule summary remains the authoritative breakdown; the full
#: integer is still what :func:`run` returns to in-process callers.
DEAD_SUPPRESSION_CODE = 256

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache", ".hypothesis"}

#: The audit's marker pattern mirrors :meth:`ModuleSource.suppressed`
#: exactly — '#'-anchored and reason-required — so prose that merely
#: mentions the syntax ("consider adding a lint-ok: ...") and
#: reasonless markers (which suppress nothing; their rule still
#: fires) are never reported as dead suppressions.
_LINT_OK_RE = re.compile(r"#\s*lint-ok:\s*([A-Za-z0-9_-]+)\s*:\s*\S")


@dataclass(frozen=True)
class Violation:
    path: Path
    line: int
    rule: str
    message: str

    def render(self, with_rule: bool = True) -> str:
        tag = f"[{self.rule}] " if with_rule else ""
        return f"{self.path}:{self.line}: {tag}{self.message}"


class ModuleSource:
    """One parsed source file, shared across rules."""

    def __init__(self, path: Path, text: Optional[str] = None):
        self.path = Path(path)
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        #: (lineno, rule name) pairs whose ``# lint-ok:`` marker
        #: actually silenced a would-be violation this run — the
        #: evidence the dead-suppression audit checks against.
        self.suppression_hits: set = set()
        try:
            self.text = self.path.read_text() if text is None else text
        except (OSError, UnicodeDecodeError) as e:
            # unreadable files report like syntax errors instead of
            # crashing the run (the exit status must stay rule-shaped)
            self.text = ""
            self.lines = []
            self.parse_error = SyntaxError(f"unreadable: {e}")
            return
        self.lines = self.text.splitlines()
        try:
            self.tree = ast.parse(self.text, filename=str(self.path))
        except SyntaxError as e:
            self.parse_error = e

    def line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def suppressed(self, lineno: int, rule: "Rule") -> bool:
        """``# lint-ok: <rule>: <reason>`` (reason mandatory) on the
        flagged line, or one of the rule's grandfathered markers."""
        text = self.line(lineno)
        if re.search(rf"#\s*lint-ok:\s*{re.escape(rule.name)}\s*:\s*\S",
                     text):
            self.suppression_hits.add((lineno, rule.name))
            return True
        return any(marker in text for marker in rule.legacy_markers)

    def lint_ok_comments(self) -> List[Tuple[int, str]]:
        """(lineno, rule name) of every ``lint-ok:`` marker appearing
        in an actual COMMENT token — docstrings/string literals that
        merely mention the syntax do not count.  Multi-line comments
        attribute each marker to its own physical line."""
        out: List[Tuple[int, str]] = []
        try:
            tokens = tokenize.generate_tokens(
                io.StringIO(self.text).readline)
            for tok in tokens:
                if tok.type != tokenize.COMMENT:
                    continue
                for m in _LINT_OK_RE.finditer(tok.string):
                    out.append((tok.start[0], m.group(1)))
        except (tokenize.TokenError, IndentationError, SyntaxError):
            return []  # untokenizable: skip the audit for this file
        return out


class Rule:
    """Base class: one decidable bug class."""

    #: kebab-case id — the suppression token and CLI name.
    name: str = ""
    #: distinct power-of-two exit bit.
    code: int = 0
    #: one-line description shown by ``analyze.py --list-rules``.
    doc: str = ""
    #: pre-framework same-line markers that still suppress this rule.
    legacy_markers: Tuple[str, ...] = ()

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py"

    def check(self, mod: ModuleSource) -> List[Violation]:
        return []

    def check_project(self, root: Path,
                      files: Sequence[ModuleSource]) -> List[Violation]:
        """Whole-tree consistency pass; runs once per invocation."""
        return []

    # -- helpers for subclasses ----------------------------------------

    def violation(self, mod: ModuleSource, lineno: int,
                  message: str) -> Optional[Violation]:
        """A violation at ``lineno``, honouring suppressions."""
        if mod.suppressed(lineno, self):
            return None
        return Violation(mod.path, lineno, self.name, message)


def iter_py_files(paths: Iterable[Path]) -> List[Path]:
    """Expand files/directories into a sorted, de-duplicated .py file
    list, never descending into bytecode/VCS dirs."""
    out = []
    seen = set()
    for p in paths:
        p = Path(p)
        if p.is_dir():
            found = sorted(
                f for f in p.rglob("*.py")
                if not (_SKIP_DIRS & set(f.parts))
            )
        else:
            found = [p]
        for f in found:
            key = f.resolve()
            if key not in seen:
                seen.add(key)
                out.append(f)
    return out


def load_sources(paths: Iterable[Path]) -> List[ModuleSource]:
    return [ModuleSource(p) for p in iter_py_files(paths)]


class _DeadSuppressionProbe(Rule):
    """Identity the audit presents to ``ModuleSource.suppressed`` so a
    dead-suppression finding can itself be silenced the usual way
    (``# lint-ok: dead-suppression: <reason>``)."""

    name = "dead-suppression"
    code = DEAD_SUPPRESSION_CODE
    doc = ("# lint-ok: markers whose rule never fires on that line "
           "(stale suppressions rot in place)")


def all_tier_rule_names() -> set:
    """Every rule name across ALL analyzer tiers (AST, compiled,
    concurrency) plus the engine-level pseudo-rules — the universe a
    ``# lint-ok:`` marker may legitimately name.  Imported lazily so
    a broken tier degrades to 'its names look unknown' instead of
    taking the other tiers down."""
    names = {"parse-error", "dead-suppression", "build-error"}
    try:
        from tools.analysis.rules import ALL_RULES
        names |= {r.name for r in ALL_RULES}
    except ImportError:
        pass
    try:
        from tools.analysis.compiled.rules import COMPILED_RULES
        names |= {r.name for r in COMPILED_RULES}
    except ImportError:
        pass
    try:
        from tools.analysis.concurrency.rules import CONCURRENCY_RULES
        names |= {r.name for r in CONCURRENCY_RULES}
    except ImportError:
        pass
    return names


def audit_suppressions(rules: Sequence[Rule],
                       files: Sequence[ModuleSource]) -> List[Violation]:
    """Dead-suppression audit: every ``# lint-ok: <rule>: ...`` comment
    must have silenced a real would-be finding of ``<rule>`` on its
    line during this run (``ModuleSource.suppression_hits``).  Markers
    naming a rule outside the battery are reported as unknown — a typo
    in the rule name suppresses nothing and rots just the same.  Run
    only with the FULL battery: under ``--rule`` filtering an unused
    marker may belong to an unselected rule."""
    probe = _DeadSuppressionProbe()
    known = {r.name for r in rules}
    # markers naming ANOTHER tier's rule belong to that tier: not
    # unknown, and their liveness is judged by that tier's own audit
    # over its own sweep/artifacts — skip them here.  (The AST tier
    # sweeps files carrying concurrency-tier markers and vice versa;
    # compiled-tier markers sit at contracts.py @register sites.)
    other_tier = all_tier_rule_names() - known
    out: List[Violation] = []
    for mod in files:
        if mod.parse_error is not None:
            continue
        for lineno, rname in mod.lint_ok_comments():
            if rname == probe.name or rname in other_tier:
                continue  # self-markers / the compiled tier's markers
            if rname not in known:
                v = probe.violation(
                    mod, lineno,
                    f"suppression names unknown rule {rname!r} — it "
                    f"silences nothing (see analyze.py --list-rules); "
                    f"fix the name or delete the marker")
                if v is not None:
                    out.append(v)
            elif (lineno, rname) not in mod.suppression_hits:
                v = probe.violation(
                    mod, lineno,
                    f"dead suppression: rule '{rname}' no longer fires "
                    f"on this line — the finding it excused has moved "
                    f"or been fixed; delete the marker (a stale one "
                    f"would silently swallow the next real finding "
                    f"here)")
                if v is not None:
                    out.append(v)
    return out


def run(rules: Sequence[Rule], files: Sequence[ModuleSource],
        root: Optional[Path] = None,
        audit: bool = True) -> Tuple[List[Violation], int]:
    """Run every rule over every applicable file (plus each rule's
    project pass), then the dead-suppression audit (``audit=False``
    for ``--rule``-filtered runs).  Returns (violations, exit code)
    where the exit code ORs the bits of the rules that fired."""
    violations: List[Violation] = []
    exit_code = 0
    for mod in files:
        if mod.parse_error is not None:
            e = mod.parse_error
            violations.append(Violation(
                mod.path, e.lineno or 0, "parse-error",
                f"unparseable: {e.msg}"))
            exit_code |= PARSE_ERROR_CODE
            continue
        for rule in rules:
            if not rule.applies(mod.path):
                continue
            found = rule.check(mod)
            violations.extend(found)
            if found:
                exit_code |= rule.code
    if root is not None:
        for rule in rules:
            found = rule.check_project(Path(root), files)
            violations.extend(found)
            if found:
                exit_code |= rule.code
    if audit:
        # must run LAST: it needs every rule's suppression_hits
        found = audit_suppressions(rules, files)
        violations.extend(found)
        if found:
            exit_code |= DEAD_SUPPRESSION_CODE
    violations.sort(key=lambda v: (str(v.path), v.line))
    return violations, exit_code
