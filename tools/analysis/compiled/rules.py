"""Rule battery of the compiled-contract tier.

Each rule checks one compiled-artifact guarantee against the contract
declared next to the program (``tempo_tpu/plan/contracts.py``).  Exit
bits live in the compiled tier's own space (the tier is its own
``tools/analyze.py --compiled`` invocation):

==================== ====  ============================================
no-f64-leak             1  non-scalar f64 ops in a compiled artifact
                           that declares the f32 policy
no-host-transfer        2  infeed/outfeed/send/recv/python-callback
                           custom-calls outside a declared barrier
collective-inventory    4  compiled collectives vs the declared model
                           (per-kind bytes within the shared tolerance;
                           no unmodeled kinds; no vanished kinds)
donation-applied        8  declared donate_argnums must appear as
                           input-output aliases in the executable
stage-sharding-match   16  chained stage N out-sharding == stage N+1
                           in-sharding (no implicit resharding)
recompile-coverage     32  every parameter of a PLANNED_METHODS op
                           feeds the recorded plan node (cache keys can
                           never replay a stale executable)
build-error            64  registry programs that fail to build
==================== ====  ============================================
"""

from __future__ import annotations

import ast
import inspect
import re
import textwrap
from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from dataclasses import dataclass

from tools.analysis.compiled.core import CompiledRule, Finding


@dataclass(frozen=True)
class _Site:
    """A suppressible finding anchor that is not a CompiledProgram:
    registry-level findings point at the offending METHOD's def line,
    so the standard ``# lint-ok: <rule>: <reason>`` works there too."""

    name: str
    source_file: str
    source_line: int

#: non-scalar f64 shapes in HLO text: ``f64[`` followed by a digit.
#: Scalar ``f64[]`` constants are tolerated — they fold at compile
#: time (a weak python float cast to a typed scalar), while an ARRAY
#: of f64 means real double-precision compute the TPU cannot run
#: (silent f32 demotion = cross-backend bitwise drift).
_F64_ARRAY_RE = re.compile(r"\bf64\[\d")


class NoF64LeakRule(CompiledRule):
    name = "no-f64-leak"
    code = 1
    doc = ("no non-scalar f64 ops in compiled artifacts built under "
           "the f32 compute policy")

    def check_program(self, program) -> List[Finding]:
        if program.contract.allow_f64:
            return []
        text = program.hlo_text()
        hits = []
        for line in text.splitlines():
            m = _F64_ARRAY_RE.search(line)
            if m:
                hits.append(line.strip()[:120])
        if not hits:
            return []
        f = self.finding(
            program,
            f"{len(hits)} non-scalar f64 op(s) in the compiled HLO of "
            f"an f32-policy program (weak python floats / dtype-less "
            f"asarray re-traced f64 — the 22-test interpret regression "
            f"class; TPU would silently demote and drift bitwise).  "
            f"First: {hits[0]}")
        return [f] if f else []


class NoHostTransferRule(CompiledRule):
    name = "no-host-transfer"
    code = 2
    doc = ("no infeed/outfeed/send/recv/python-callback ops outside a "
           "declared materialization barrier")

    def check_program(self, program) -> List[Finding]:
        if program.contract.host_transfer_ok is not None:
            return []
        from tempo_tpu import profiling

        hits = profiling.host_transfers_from_compiled(
            program.compiled, text=program.hlo_text())
        if not hits:
            return []
        f = self.finding(
            program,
            f"{len(hits)} host-transfer op(s) compiled into a program "
            f"declared device-resident (declare the barrier in the "
            f"contract if it is intentional).  First: {hits[0]}")
        return [f] if f else []


class CollectiveInventoryRule(CompiledRule):
    name = "collective-inventory"
    code = 4
    doc = ("compiled collectives match the declared per-kind byte "
           "model within the shared tolerance; no unmodeled kinds")

    def check_program(self, program) -> List[Finding]:
        from tempo_tpu import profiling

        contract = program.contract
        measured = profiling.comm_bytes_from_compiled(
            program.compiled, text=program.hlo_text())
        out: List[Optional[Finding]] = []
        for kind, model in sorted(contract.collectives.items()):
            got = measured.get(kind, 0)
            tol = contract.tolerances.get(
                kind, profiling.COLLECTIVE_TOLERANCE.get(kind, 1.25))
            if got == 0:
                out.append(self.finding(
                    program,
                    f"declared collective '{kind}' ({model} B/shard "
                    f"modeled) is ABSENT from the compiled HLO — the "
                    f"comm the model budgets for no longer happens "
                    f"(or was renamed/fused); re-derive the model"))
            elif not (model <= got <= tol * model):
                out.append(self.finding(
                    program,
                    f"collective '{kind}' moved {got} B/shard vs the "
                    f"modeled {model} B/shard (outside [1x, {tol}x] — "
                    f"an extra collective, a wrong halo width, or XLA "
                    f"padding past the shared tolerance)"))
        for kind, got in sorted(measured.items()):
            if kind in contract.collectives:
                continue
            ceiling = contract.incidental.get(kind)
            if ceiling is None:
                out.append(self.finding(
                    program,
                    f"UNMODELED collective '{kind}' ({got} B/shard) in "
                    f"the compiled HLO — declare a model (or an "
                    f"incidental ceiling for audit scalars) so the "
                    f"comm-bytes budget stays honest"))
            elif got > ceiling:
                out.append(self.finding(
                    program,
                    f"incidental collective '{kind}' moved {got} "
                    f"B/shard, over its declared {ceiling} B ceiling"))
        return [f for f in out if f is not None]


class DonationAppliedRule(CompiledRule):
    name = "donation-applied"
    code = 8
    doc = ("declared donate_argnums appear as input-output aliases in "
           "the compiled executable (no silently dropped donation)")

    def check_program(self, program) -> List[Finding]:
        from tempo_tpu import profiling

        declared = set(program.contract.donate_argnums)
        applied = profiling.donated_params_from_compiled(
            program.compiled, text=program.hlo_text())
        out: List[Optional[Finding]] = []
        dropped = sorted(declared - applied)
        if dropped:
            out.append(self.finding(
                program,
                f"declared donation of parameter(s) {dropped} was NOT "
                f"applied (no input_output_alias in the executable): "
                f"XLA found no shape/dtype-matching output — the donated "
                f"buffers are silently kept live and the program's HBM "
                f"working set doubles"))
        undeclared = sorted(applied - declared)
        if undeclared:
            out.append(self.finding(
                program,
                f"executable aliases parameter(s) {undeclared} that the "
                f"contract does not declare — the jit's donate_argnums "
                f"and the contract drifted apart (both must read one "
                f"source of truth)"))
        return [f for f in out if f is not None]


def _flat_shardings(compiled):
    import jax

    ins = compiled.input_shardings
    if isinstance(ins, tuple) and len(ins) == 2 and isinstance(
            ins[1], dict):
        ins = ins[0]
    return (list(jax.tree_util.tree_leaves(ins)),
            list(jax.tree_util.tree_leaves(compiled.output_shardings)))


def _spec_tuple(sharding) -> Optional[Tuple]:
    spec = getattr(sharding, "spec", None)
    if spec is None:
        return None
    return tuple(spec)


def _strip(spec: Tuple) -> Tuple:
    spec = tuple(spec)
    while spec and spec[-1] is None:
        spec = spec[:-1]
    return spec


class StageShardingMatchRule(CompiledRule):
    name = "stage-sharding-match"
    code = 16
    doc = ("declared chain links: producer out-sharding equals "
           "consumer in-sharding (no implicit resharding between "
           "chained programs)")

    def check_chains(self, programs: Sequence, chains: Sequence
                     ) -> List[Finding]:
        by_name = {p.name: p for p in programs}
        out: List[Optional[Finding]] = []
        for chain in chains:
            for link in chain.links:
                out.append(self._check_link(chain, link, by_name))
        return [f for f in out if f is not None]

    def _check_link(self, chain, link, by_name) -> Optional[Finding]:
        prod = by_name.get(link.producer)
        cons = by_name.get(link.consumer)
        if prod is None or cons is None:
            return self.finding(
                chain,
                f"chain link {link.producer}[{link.out_idx}] -> "
                f"{link.consumer}[{link.in_idx}] names a program that "
                f"did not build")
        _, outs = _flat_shardings(prod.compiled)
        ins, _ = _flat_shardings(cons.compiled)
        if link.out_idx >= len(outs) or link.in_idx >= len(ins):
            return self.finding(
                chain,
                f"chain link {link.producer}[{link.out_idx}] -> "
                f"{link.consumer}[{link.in_idx}] is out of range "
                f"({len(outs)} outputs / {len(ins)} inputs)")
        p_spec = _spec_tuple(outs[link.out_idx])
        c_spec = _spec_tuple(ins[link.in_idx])
        if p_spec is None or c_spec is None:
            return self.finding(
                chain,
                f"chain link {link.producer}[{link.out_idx}] -> "
                f"{link.consumer}[{link.in_idx}]: sharding carries no "
                f"named spec (unverifiable — jit the stage with "
                f"NamedShardings)")
        if link.drop_leading:
            dropped = p_spec[:link.drop_leading]
            if any(d is not None for d in dropped):
                return self.finding(
                    chain.name,
                    f"chain link {link.producer}[{link.out_idx}]: the "
                    f"{link.drop_leading} host-sliced leading axis(es) "
                    f"are SHARDED ({dropped}) — slicing them changes "
                    f"device ownership in flight")
            p_spec = p_spec[link.drop_leading:]
        if _strip(p_spec) != _strip(c_spec):
            return self.finding(
                chain,
                f"stage-boundary sharding mismatch at "
                f"{link.producer}[{link.out_idx}] -> "
                f"{link.consumer}[{link.in_idx}]: producer writes "
                f"{p_spec}, consumer expects {c_spec} — chaining these "
                f"programs inserts an implicit reshard (ROADMAP item "
                f"2's precondition is an exact match)")
        return None


class RecompileCoverageRule(CompiledRule):
    name = "recompile-coverage"
    code = 32
    doc = ("every parameter of a PLANNED_METHODS op method feeds the "
           "recorded plan node (params dict or frame operands) — cache "
           "hits can never replay a stale executable")

    def check_registry(self, root: Path) -> List[Finding]:
        from tempo_tpu import dist as dist_mod
        from tempo_tpu import frame as frame_mod
        from tempo_tpu.plan import ir

        classes = {"TSDF": frame_mod.TSDF,
                   "DistributedTSDF": dist_mod.DistributedTSDF}
        out: List[Optional[Finding]] = []
        for cls_name, methods in ir.PLANNED_METHODS.items():
            cls = classes.get(cls_name)
            if cls is None:
                out.append(self.finding(
                    f"registry:{cls_name}",
                    f"PLANNED_METHODS class {cls_name!r} not found"))
                continue
            for m in methods:
                out.append(self._check_method(cls_name, cls, m))
        return [f for f in out if f is not None]

    def _check_method(self, cls_name: str, cls, method: str
                      ) -> Optional[Finding]:
        site = f"registry:{cls_name}.{method}"
        fn = getattr(cls, method, None)
        if fn is None:
            return self.finding(
                site, "method missing (PLANNED_METHODS drift — the "
                      "plan-registry AST rule should have caught this)")
        try:
            sig = inspect.signature(fn)
            src = textwrap.dedent(inspect.getsource(fn))
            # anchor the finding at the method's def so a same-site
            # ``# lint-ok: recompile-coverage: <reason>`` suppresses,
            # like every other compiled finding
            site = _Site(site, inspect.getsourcefile(fn) or "",
                         inspect.getsourcelines(fn)[1])
        except (OSError, TypeError, ValueError) as e:
            return self.finding(site, f"source unavailable: {e}")
        recorded, operands = self._recorded_names(src)
        if recorded is None:
            return self.finding(
                site, "no _plan_record call found in the method body")
        missing = []
        for p in sig.parameters.values():
            if p.name in ("self", "cls"):
                continue
            if p.kind is inspect.Parameter.VAR_KEYWORD:
                continue
            if p.name not in recorded and p.name not in operands:
                missing.append(p.name)
        if missing:
            return self.finding(
                site,
                f"parameter(s) {missing} are NOT recorded into the "
                f"plan node (neither a params key nor a frame "
                f"operand): two calls differing only there share a "
                f"plan signature, so a cache hit would replay a STALE "
                f"executable built for the other value")
        return None

    @staticmethod
    def _recorded_names(src: str):
        """(params-dict keys, operand Name ids) of the method's
        ``_plan_record(op, others, params, objs)`` call, or
        (None, None) when no call is found."""
        try:
            tree = ast.parse(src)
        except SyntaxError:
            return None, None
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_plan_record"):
                continue
            others = node.args[1] if len(node.args) > 1 else None
            params = node.args[2] if len(node.args) > 2 else None
            for kw in node.keywords:
                if kw.arg == "others":
                    others = kw.value
                elif kw.arg == "params":
                    params = kw.value
            keys = set()
            if isinstance(params, ast.Call):        # dict(colName=...)
                keys |= {kw.arg for kw in params.keywords if kw.arg}
            elif isinstance(params, ast.Dict):      # {"colName": ...}
                keys |= {k.value for k in params.keys
                         if isinstance(k, ast.Constant)
                         and isinstance(k.value, str)}
            operands = set()
            if isinstance(others, (ast.Tuple, ast.List)):
                for elt in others.elts:
                    for sub in ast.walk(elt):
                        if isinstance(sub, ast.Name):
                            operands.add(sub.id)
            return keys, operands
        return None, None


COMPILED_RULES: Tuple[CompiledRule, ...] = (
    NoF64LeakRule(),
    NoHostTransferRule(),
    CollectiveInventoryRule(),
    DonationAppliedRule(),
    StageShardingMatchRule(),
    RecompileCoverageRule(),
)
