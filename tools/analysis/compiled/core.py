"""Engine of the compiled-contract analyzer tier.

Mirrors ``tools/analysis/core.py`` one level up the stack: where the
AST tier's unit is a parsed source file, this tier's unit is a
**compiled artifact** — a production program from the registry in
``tempo_tpu/plan/contracts.py``, lowered and compiled at small
representative shapes, checked against the contract declared next to
it.

Conventions shared with the AST tier:

* every rule owns a power-of-two exit bit — but in a SEPARATE bit
  space (the two tiers are separate ``tools/analyze.py`` invocations,
  so their statuses never mix);
* a registry entry that fails to *build* reports as ``build-error``
  (:data:`BUILD_ERROR_CODE`) instead of crashing the run — the moral
  twin of the AST tier's ``parse-error``;
* one finding is silenced by a ``# lint-ok: <rule>: <reason>`` comment
  on (or immediately around) the program builder's ``@register`` line.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

#: Exit bit for registry programs that fail to build/compile at all.
BUILD_ERROR_CODE = 64


@dataclass(frozen=True)
class Finding:
    program: str            # registry program (or chain) name
    rule: str
    message: str

    def render(self) -> str:
        return f"compiled:{self.program}: [{self.rule}] {self.message}"


class CompiledRule:
    """Base class: one decidable compiled-artifact bug class."""

    #: kebab-case id — the suppression token and CLI name.
    name: str = ""
    #: distinct power-of-two exit bit (compiled-tier space).
    code: int = 0
    #: one-line description shown by ``analyze.py --list-rules``.
    doc: str = ""

    def check_program(self, program) -> List[Finding]:
        """Findings for one ``contracts.CompiledProgram``."""
        return []

    def check_chains(self, programs: Sequence, chains: Sequence
                     ) -> List[Finding]:
        """Findings over the declared stage chains (runs once)."""
        return []

    def check_registry(self, root: Path) -> List[Finding]:
        """Registry-level consistency pass needing no artifacts
        (runs once)."""
        return []

    # -- helpers -------------------------------------------------------

    def finding(self, program, message: str) -> Optional[Finding]:
        """A finding against ``program``, honouring a same-site
        ``# lint-ok: <rule>: <reason>`` suppression."""
        if _suppressed(program, self.name):
            return None
        name = program if isinstance(program, str) else program.name
        return Finding(name, self.name, message)


def _suppressed(program, rule_name: str) -> bool:
    """True when the builder's ``@register`` site carries
    ``# lint-ok: <rule>: <reason>`` (the decorator lines and the def
    line — the same convention as the AST tier, anchored to where the
    program is declared)."""
    src = getattr(program, "source_file", "")
    line = getattr(program, "source_line", 0)
    if not src or not line:
        return False
    try:
        lines = Path(src).read_text().splitlines()
    except OSError:
        return False
    pat = re.compile(rf"#\s*lint-ok:\s*{re.escape(rule_name)}\s*:\s*\S")
    lo = max(0, line - 4)
    hi = min(len(lines), line + 2)
    return any(pat.search(lines[i]) for i in range(lo, hi))


def run_compiled(rules: Sequence[CompiledRule], programs: Sequence,
                 chains: Sequence, errors: Dict[str, str],
                 root: Optional[Path] = None
                 ) -> Tuple[List[Finding], int]:
    """Run every compiled rule over every built artifact (+ the chain
    and registry passes).  ``errors`` (builder name -> message) become
    ``build-error`` findings.  Returns (findings, exit code)."""
    findings: List[Finding] = []
    exit_code = 0
    for name, msg in sorted(errors.items()):
        findings.append(Finding(
            name, "build-error",
            f"registry program failed to build/compile: {msg}"))
        exit_code |= BUILD_ERROR_CODE
    for rule in rules:
        fired = False
        for program in programs:
            found = rule.check_program(program)
            findings.extend(found)
            fired = fired or bool(found)
        found = rule.check_chains(programs, chains)
        findings.extend(found)
        fired = fired or bool(found)
        if root is not None:
            found = rule.check_registry(Path(root))
            findings.extend(found)
            fired = fired or bool(found)
        if fired:
            exit_code |= rule.code
    findings.sort(key=lambda f: (f.program, f.rule))
    return findings, exit_code
