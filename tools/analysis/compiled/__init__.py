"""Compiled-contract analyzer tier (``python tools/analyze.py
--compiled``).

Second static-analysis tier of the project: where ``tools/analysis``
checks *source*, this tier checks the **compiled artifacts** of the
production-program registry (``tempo_tpu/plan/contracts.py``) against
the contracts declared next to the programs — sharding, donation,
collectives, dtype and host-transfer guarantees that only exist in
what XLA actually compiled.  See ``core.py`` (engine), ``rules.py``
(the battery), and BUILDING.md "Compiled contracts".
"""

from __future__ import annotations

import os
import sys
from pathlib import Path
from typing import Optional, Sequence

from tools.analysis.compiled.core import (  # noqa: F401
    BUILD_ERROR_CODE,
    CompiledRule,
    Finding,
    run_compiled,
)
from tools.analysis.compiled.rules import COMPILED_RULES  # noqa: F401

_REPO = Path(__file__).resolve().parent.parent.parent.parent


def _prepare_environment() -> None:
    """Arrange the dryrun-style build environment BEFORE jax
    initialises: the f32 TPU compute policy + sort-kernel forms (the
    artifacts under contract are the production TPU shapes, not the
    f64 golden-parity shapes) and the virtual multi-device mesh when
    no accelerator is attached.  No-ops when the caller (conftest.py,
    a TPU image) already arranged them."""
    os.environ.setdefault("TEMPO_TPU_COMPUTE_DTYPE", "float32")
    os.environ.setdefault("TEMPO_TPU_SORT_KERNELS", "1")
    if "jax" not in sys.modules:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()


def main(programs: Optional[Sequence[str]] = None,
         rules: Optional[Sequence[str]] = None) -> int:
    """Build the registry (or the named subset), run the battery,
    print findings, return the compiled tier's exit-bit OR."""
    _prepare_environment()

    from tempo_tpu.plan import contracts

    battery = list(COMPILED_RULES)
    if rules:
        known = {r.name: r for r in COMPILED_RULES}
        unknown = [n for n in rules if n not in known]
        if unknown:
            # a CLI usage error, NOT a build-error finding: exit 2,
            # the same status argparse uses for the AST tier's
            # malformed invocations (the bit table stays honest)
            print(f"unknown compiled rule(s): {', '.join(unknown)} "
                  f"(see analyze.py --list-rules)", file=sys.stderr)
            return 2
        battery = [known[n] for n in rules]

    try:
        built, chains, skipped, errors = contracts.build_all(
            only=programs)
    except (RuntimeError, KeyError) as e:
        # environment-precondition / unknown-program failures are
        # USAGE errors (exit 2, argparse's status), not findings —
        # exiting 1 would read as the no-f64-leak bit to CI
        print(f"compiled tier cannot run: {e}", file=sys.stderr)
        return 2
    for name, why in sorted(skipped.items()):
        print(f"compiled:{name}: skipped ({why})", file=sys.stderr)

    findings, exit_code = run_compiled(battery, built, chains, errors,
                                       root=_REPO)
    for f in findings:
        print(f.render())
    summary = (f"{len(built)} program(s), {len(chains)} chain(s), "
               f"{len(skipped)} skipped")
    if findings:
        by_rule = {}
        for f in findings:
            by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
        detail = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
        print(f"{len(findings)} compiled-contract finding(s) ({detail}) "
              f"over {summary}; exit code {exit_code}", file=sys.stderr)
    else:
        print(f"compiled contracts clean over {summary}",
              file=sys.stderr)
    return exit_code
