"""Kernel-safety static analyzer for the tempo-tpu tree.

``python tools/analyze.py`` runs the whole battery; see
``tools/analysis/core.py`` for the framework contract and
``tools/analysis/rules/`` for the bug classes.
"""

from tools.analysis.core import (  # noqa: F401
    ModuleSource,
    PARSE_ERROR_CODE,
    Rule,
    Violation,
    iter_py_files,
    load_sources,
    run,
)
