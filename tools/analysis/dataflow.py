"""Shared AST dataflow helpers: constant folding, import/alias
resolution, and scope utilities.

The rules need three things the raw AST does not give directly:

* *constant folding* over straight-line assignments — BlockSpec shapes
  are written as ``(bk, L)`` with ``bk``/``L`` bound a few lines up;
  :func:`fold` resolves such names through the local then module
  assignment environment, evaluating the arithmetic the kernels
  actually use (``*``, ``//``, ``<<``, ``**``, unary ``-``) and
  returning :data:`UNKNOWN` the moment anything runtime-dependent
  (function args, ``.shape`` reads) enters;
* *origin resolution* — ``from jax.numpy import take_along_axis as g``
  and ``h = jnp.take`` both alias a banned gather;
  :func:`build_aliases` maps every local name to its dotted origin so
  call checks see through the rename;
* *scope walks* — :func:`enclosing_function_map` ties every node to
  its innermost function so rules can build per-function environments.

Everything here is intentionally flow-insensitive (last assignment
wins): the kernel dispatch wrappers this analyzes are straight-line,
and a wrong ``UNKNOWN`` only widens a check, never silences it.
"""

from __future__ import annotations

import ast
from typing import Any, Dict, List, Optional, Union


class _Unknown:
    """Sentinel: not statically resolvable."""

    def __repr__(self):
        return "UNKNOWN"

    def __bool__(self):
        return False


UNKNOWN = _Unknown()

Env = Dict[str, ast.expr]


def assignment_env(body: List[ast.stmt]) -> Env:
    """name -> last straight-line assigned expression, from the given
    statement list only (no descent into nested functions: their
    bindings are a different scope)."""
    env: Env = {}

    def visit(stmts):
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        env[tgt.id] = stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                if isinstance(stmt.target, ast.Name):
                    env[stmt.target.id] = stmt.value
            elif isinstance(stmt, (ast.If, ast.For, ast.While, ast.With,
                                   ast.Try)):
                for field in ("body", "orelse", "finalbody", "handlers"):
                    sub = getattr(stmt, field, None)
                    if not sub:
                        continue
                    if field == "handlers":
                        for h in sub:
                            visit(h.body)
                    else:
                        visit(sub)

    visit(body)
    return env


def fold(node: Optional[ast.expr], env: Env,
         fallback: Optional[Env] = None, _depth: int = 0) -> Any:
    """Evaluate ``node`` to a python value, or :data:`UNKNOWN`.

    Handles int/float/str/bool constants, name lookups through ``env``
    then ``fallback`` (module scope), tuples/lists (element-wise —
    a partially known tuple folds to a tuple containing UNKNOWN
    elements), the int arithmetic the kernel planners use, and
    ``len()`` of resolvable sequences."""
    if node is None or _depth > 32:
        return UNKNOWN
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name):
        for scope in (env, fallback or {}):
            if node.id in scope:
                return fold(scope[node.id], env, fallback, _depth + 1)
        return UNKNOWN
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(fold(e, env, fallback, _depth + 1) for e in node.elts)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        v = fold(node.operand, env, fallback, _depth + 1)
        return -v if isinstance(v, (int, float)) else UNKNOWN
    if isinstance(node, ast.BinOp):
        lhs = fold(node.left, env, fallback, _depth + 1)
        rhs = fold(node.right, env, fallback, _depth + 1)
        if isinstance(lhs, (int, float)) and isinstance(rhs, (int, float)):
            try:
                if isinstance(node.op, ast.Add):
                    return lhs + rhs
                if isinstance(node.op, ast.Sub):
                    return lhs - rhs
                if isinstance(node.op, ast.Mult):
                    return lhs * rhs
                if isinstance(node.op, ast.FloorDiv):
                    return lhs // rhs
                if isinstance(node.op, ast.Mod):
                    return lhs % rhs
                if isinstance(node.op, ast.Pow):
                    return lhs ** rhs
                if isinstance(node.op, ast.LShift):
                    return lhs << rhs
                if isinstance(node.op, ast.RShift):
                    return lhs >> rhs
            except (ZeroDivisionError, ValueError, OverflowError):
                return UNKNOWN
        return UNKNOWN
    if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id == "len" and len(node.args) == 1):
        seq = fold(node.args[0], env, fallback, _depth + 1)
        return len(seq) if isinstance(seq, tuple) else UNKNOWN
    return UNKNOWN


def dotted_name(node: ast.expr,
                aliases: Optional[Dict[str, str]] = None) -> Optional[str]:
    """``jnp.take`` -> 'jax.numpy.take' (through ``aliases``), plain
    names through the alias map, else None for non-name expressions."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    head = node.id
    if aliases and head in aliases:
        head = aliases[head]
    parts.append(head)
    return ".".join(reversed(parts))


def build_aliases(tree: ast.Module) -> Dict[str, str]:
    """Local name -> dotted origin, covering ``import x.y as z``,
    ``from x import y as z``, and first-order assignment aliases
    (``g = jnp.take``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = (
                    a.name if a.asname else a.name.split(".")[0])
        elif isinstance(node, ast.ImportFrom) and node.module:
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    # assignment aliases resolve through the import map built above
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, (ast.Attribute, ast.Name)):
            origin = dotted_name(node.value, aliases)
            if origin:
                aliases[node.targets[0].id] = origin
    return aliases


def terminal_name(node: ast.expr) -> str:
    """Rightmost identifier of a call target: ``pl.pallas_call`` ->
    'pallas_call', ``take`` -> 'take', anything else -> ''."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""


FuncNode = Union[ast.FunctionDef, ast.AsyncFunctionDef]


def enclosing_function_map(tree: ast.Module) -> Dict[ast.AST, FuncNode]:
    """node -> innermost enclosing FunctionDef (nodes at module level
    are absent)."""
    out: Dict[ast.AST, FuncNode] = {}

    def visit(node: ast.AST, current: Optional[FuncNode]):
        for child in ast.iter_child_nodes(node):
            if current is not None:
                out[child] = current
            nxt = child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef)) else current
            visit(child, nxt)

    visit(tree, None)
    return out
