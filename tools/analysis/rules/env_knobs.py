"""env-knobs: every ``TEMPO_TPU_*`` knob is declared once and
documented once.

The bug class: silent engine fallbacks are governed by env knobs that
accreted per-module, and by PR 3 two of them (``TEMPO_TPU_WAREHOUSE``,
``TEMPO_TPU_BINPACK``) were read in code but absent from BUILDING.md —
an operator reading the docs could not know the fallbacks existed.
``tempo_tpu/config.py`` is now the registry (name, type, default,
owning module, one-line contract); this rule keeps the three copies of
the truth — registry, code, docs — from drifting again:

* module pass — ``os.environ`` / ``os.getenv`` anywhere under
  ``tempo_tpu/`` outside ``config.py`` is flagged: knob reads go
  through ``config.get``/``get_bool``/``get_int``; foreign vars
  (``JAX_PLATFORMS``...) through ``config.env_external``;
* project pass — every ``TEMPO_TPU_*`` token mentioned anywhere in
  package sources (string literals, comments, docstrings — mentions of
  ghosts are exactly the drift) and in ``__graft_entry__.py`` must be
  declared in the registry; every registry knob must appear in
  BUILDING.md's knob documentation; every ``TEMPO_TPU_*`` token in
  BUILDING.md must be a declared knob (else it documents a dead knob).
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Optional, Sequence

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df

_KNOB_RE = re.compile(r"TEMPO_TPU_[A-Z0-9_]+")

#: basenames whose knob mentions are definitional, not drift.
_REGISTRY_FILE = "config.py"


def _in_package(path: Path) -> bool:
    return "tempo_tpu" in path.parts


class EnvKnobRule(Rule):
    name = "env-knobs"
    code = 16
    doc = ("os.environ access outside tempo_tpu/config.py banned; "
           "registry / code / BUILDING.md knob tables must agree")

    # -- module pass ---------------------------------------------------

    def applies(self, path: Path) -> bool:
        # __graft_entry__.py imports tempo_tpu before jax, so it can
        # (and must) read its knob through config too
        return (path.suffix == ".py"
                and (_in_package(path) or path.name == "__graft_entry__.py")
                and path.name != _REGISTRY_FILE)

    def check(self, mod: ModuleSource) -> List[Violation]:
        aliases = df.build_aliases(mod.tree)
        out: List[Optional[Violation]] = []
        for node in ast.walk(mod.tree):
            origin = None
            if isinstance(node, ast.Attribute):
                origin = df.dotted_name(node, aliases)
            elif isinstance(node, ast.Name):
                origin = aliases.get(node.id) if node.id in aliases else None
            if origin in ("os.environ", "os.getenv", "os.putenv",
                          "os.unsetenv"):
                out.append(self.violation(
                    mod, node.lineno,
                    f"direct '{origin}' access outside the knob registry "
                    f"— read TEMPO_TPU_* knobs via tempo_tpu.config.get/"
                    f"get_bool/get_int and foreign vars via "
                    f"config.env_external (declare new names in "
                    f"config.KNOBS / config.EXTERNAL_VARS)"))
        return [v for v in out if v is not None]

    # -- project pass --------------------------------------------------

    def check_project(self, root: Path,
                      files: Sequence[ModuleSource]) -> List[Violation]:
        registry = self._load_registry(files, root)
        if registry is None:
            return []  # no registry in this tree (fixture runs)
        reg_mod, knobs = registry
        out: List[Optional[Violation]] = []

        # every knob token mentioned in package code is declared
        for mod in files:
            if not (_in_package(mod.path)
                    or mod.path.name == "__graft_entry__.py"):
                continue
            if mod.path.name == _REGISTRY_FILE:
                continue
            for i, line in enumerate(mod.lines, start=1):
                for token in _KNOB_RE.findall(line):
                    if token not in knobs:
                        out.append(self.violation(
                            mod, i,
                            f"'{token}' is not declared in "
                            f"tempo_tpu.config.KNOBS — declare it (and "
                            f"document it in BUILDING.md) or delete the "
                            f"ghost reference"))

        # registry <-> BUILDING.md
        building = root / "BUILDING.md"
        if building.exists():
            doc_text = building.read_text()
            doc_lines = doc_text.splitlines()
            documented = set(_KNOB_RE.findall(doc_text))
            for name, lineno in knobs.items():
                if name not in documented:
                    out.append(self.violation(
                        reg_mod, lineno,
                        f"knob '{name}' is declared but undocumented — "
                        f"add it to BUILDING.md's knob table"))
            for i, line in enumerate(doc_lines, start=1):
                for token in _KNOB_RE.findall(line):
                    if token not in knobs:
                        out.append(Violation(
                            building, i, self.name,
                            f"BUILDING.md documents '{token}' but no such "
                            f"knob is declared in tempo_tpu.config.KNOBS "
                            f"— dead documentation or an undeclared "
                            f"read"))
        return [v for v in out if v is not None]

    def _load_registry(self, files: Sequence[ModuleSource], root: Path):
        """(registry ModuleSource, {knob name -> decl line}) from
        tempo_tpu/config.py, parsed statically (Knob(...) calls)."""
        reg = None
        for mod in files:
            if _in_package(mod.path) and mod.path.name == _REGISTRY_FILE:
                reg = mod
                break
        if reg is None:
            cand = root / "tempo_tpu" / _REGISTRY_FILE
            if cand.exists():
                reg = ModuleSource(cand)
        if reg is None or reg.tree is None:
            return None
        knobs = {}
        for node in ast.walk(reg.tree):
            if isinstance(node, ast.Call) \
                    and df.terminal_name(node.func) == "Knob" \
                    and node.args \
                    and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                knobs[node.args[0].value] = node.lineno
        return (reg, knobs) if knobs else None
