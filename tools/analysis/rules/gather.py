"""dynamic-gather + grid-carry: data-movement discipline in the Pallas
kernel modules.

``dynamic-gather`` supersedes ``tools/check_no_dynamic_gather.py``
(now a shim): per-lane dynamic gathers are the one data-movement
primitive this hardware cannot do at speed (the ~96 ms
``take_along_axis`` levels behind the BENCH_r05 dense-regime loss) and
Mosaic cannot lower them in-kernel at all.  The legacy script matched
call *names* only; this rule adds the dataflow it punted on:

* aliased imports — ``from jax.numpy import take_along_axis as g`` /
  ``h = jnp.take`` are resolved through the module alias map;
* ``getattr`` indirection — ``getattr(jnp, "take")(...)`` flags like
  the direct call, and ``getattr(jnp, name)(...)`` with a
  non-constant attr on an array library flags as unauditable;
* ``x.at[idx].get()`` / ``.set()`` / ``.add()`` — the indexed-update
  forms the legacy tool explicitly left to review.

``grid-carry``: a scratch ref on a *sequential* grid axis
(``dimension_semantics`` containing ``"arbitrary"``) is a carry — the
only state that survives between grid steps.  A kernel whose first
unguarded access to such a ref is a WRITE destroys the previous step's
carry before reading it (cross-chunk forward-fill state, PR 3's
correctness linchpin); initialisation writes belong under a
``@pl.when(step == 0)`` guard.  Refs bound as ``*refs`` varargs are
not attributable and are skipped.

Suppressions: ``# lint-ok: dynamic-gather: <reason>`` (the legacy
``# gather-ok: <reason>`` marker is still honoured) and
``# lint-ok: grid-carry: <reason>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df

BANNED = {
    "take_along_axis",
    "take",
    "gather",
    "dynamic_slice",
    "dynamic_update_slice",
    "dynamic_index_in_dim",
    "searchsorted",
    "scatter",
    "scatter_add",
}

#: dotted-origin prefixes that count as "an array library" for the
#: getattr-indirection check.
_ARRAY_LIBS = ("jax.numpy", "jax.lax", "numpy", "jax")

_AT_METHODS = {"get", "set", "add", "mul", "min", "max", "apply"}


def _kernel_module(path: Path) -> bool:
    """The files under kernel discipline: the Pallas op modules plus
    the tool/test helpers the analyzer sweeps."""
    return (
        path.name.startswith("pallas_")
        or "tools" in path.parts
        or path.name == "helpers.py"
    )


class DynamicGatherRule(Rule):
    name = "dynamic-gather"
    code = 4
    doc = ("no gather/scatter-shaped calls (incl. aliases, getattr "
           "indirection, .at[...] forms) in Pallas kernel modules")
    legacy_markers = ("# gather-ok:",)

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py" and _kernel_module(path)

    def check(self, mod: ModuleSource) -> List[Violation]:
        aliases = df.build_aliases(mod.tree)
        out: List[Optional[Violation]] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            out.append(self._direct_or_aliased(mod, node, aliases))
            out.append(self._getattr_form(mod, node, aliases))
            out.append(self._at_form(mod, node))
        return [v for v in out if v is not None]

    def _flag(self, mod, lineno, name, how) -> Optional[Violation]:
        return self.violation(
            mod, lineno,
            f"dynamic-gather-shaped call '{name}' {how} in a Pallas "
            f"kernel module (the pattern behind the dense-regime "
            f"regression; use roll/sort/iota primitives, or annotate "
            f"the line with '# lint-ok: {self.name}: <reason>' if it "
            f"provably never runs on-chip)")

    def _direct_or_aliased(self, mod, node: ast.Call,
                           aliases) -> Optional[Violation]:
        name = df.terminal_name(node.func)
        if name in BANNED:
            return self._flag(mod, node.lineno, name, "")
        # a renamed import / assignment alias of a banned op
        if isinstance(node.func, ast.Name):
            origin = aliases.get(node.func.id, "")
            terminal = origin.rsplit(".", 1)[-1]
            if terminal in BANNED and terminal != node.func.id:
                return self._flag(mod, node.lineno, origin,
                                  f"(aliased as '{node.func.id}')")
        return None

    def _getattr_form(self, mod, node: ast.Call,
                      aliases) -> Optional[Violation]:
        fn = node.func
        if not (isinstance(fn, ast.Call)
                and df.terminal_name(fn.func) == "getattr"
                and len(fn.args) >= 2):
            return None
        obj, attr = fn.args[0], fn.args[1]
        origin = df.dotted_name(obj, aliases) or ""
        on_array_lib = any(
            origin == lib or origin.startswith(lib + ".")
            for lib in _ARRAY_LIBS)
        if isinstance(attr, ast.Constant) and isinstance(attr.value, str):
            if attr.value in BANNED:
                return self._flag(mod, node.lineno, attr.value,
                                  "(through getattr)")
            return None
        if on_array_lib:
            return self._flag(
                mod, node.lineno, f"getattr({origin}, <dynamic>)",
                "(unauditable dynamic attribute on an array library)")
        return None

    def _at_form(self, mod, node: ast.Call) -> Optional[Violation]:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in _AT_METHODS
                and isinstance(fn.value, ast.Subscript)
                and isinstance(fn.value.value, ast.Attribute)
                and fn.value.value.attr == "at"):
            return self._flag(mod, node.lineno,
                              f".at[...].{fn.attr}", "(indexed update)")
        return None


class GridCarryRule(Rule):
    name = "grid-carry"
    code = 8
    doc = ("scratch refs on sequential grid axes must be read before "
           "any unguarded write within a step")

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py" and _kernel_module(path)

    def check(self, mod: ModuleSource) -> List[Violation]:
        if "pallas_call" not in mod.text:
            return []
        tree = mod.tree
        module_env = df.assignment_env(tree.body)
        func_of = df.enclosing_function_map(tree)
        defs = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, node)
        out: List[Optional[Violation]] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and df.terminal_name(node.func) == "pallas_call"):
                continue
            enclosing = func_of.get(node)
            env = (df.assignment_env(enclosing.body)
                   if enclosing is not None else module_env)
            fallback = module_env if enclosing is not None else None
            out.extend(self._check_site(mod, node, env, fallback, defs))
        return [v for v in out if v is not None]

    def _check_site(self, mod, call, env, fallback, defs):
        if not self._sequential(call, env, fallback):
            return []
        n_scratch = self._scratch_count(call, env, fallback)
        if not n_scratch:
            return []
        kernel = self._resolve_kernel(call, env, fallback, defs)
        if kernel is None or kernel.args.vararg is not None:
            return []  # factory-built or *refs kernels: not attributable
        params = [a.arg for a in kernel.args.args]
        if len(params) < n_scratch:
            return []
        out = []
        for ref in params[len(params) - n_scratch:]:
            first_write = self._first_unguarded_write_before_read(
                kernel, ref)
            if first_write is not None:
                out.append(self.violation(
                    mod, first_write,
                    f"scratch ref '{ref}' rides a sequential grid axis "
                    f"(dimension_semantics 'arbitrary') but is written "
                    f"before it is read within the step — the previous "
                    f"grid step's carry is destroyed; read it first, or "
                    f"guard initialisation with @pl.when(step == 0)"))
        return out

    def _sequential(self, call, env, fallback) -> bool:
        for kw in call.keywords:
            if kw.arg != "compiler_params":
                continue
            if isinstance(kw.value, ast.Call):
                for inner in kw.value.keywords:
                    if inner.arg == "dimension_semantics":
                        sem = df.fold(inner.value, env, fallback)
                        if isinstance(sem, tuple) and "arbitrary" in sem:
                            return True
        return False

    def _scratch_count(self, call, env, fallback) -> int:
        for kw in call.keywords:
            if kw.arg == "scratch_shapes":
                node = kw.value
                if isinstance(node, ast.Name):
                    for scope in (env, fallback or {}):
                        if node.id in scope:
                            node = scope[node.id]
                            break
                if isinstance(node, (ast.List, ast.Tuple)):
                    return len(node.elts)
                return 0
        return 0

    def _resolve_kernel(self, call, env, fallback, defs):
        if not call.args:
            return None
        fn = call.args[0]
        if isinstance(fn, ast.Name):
            kernel = defs.get(fn.id)
            if kernel is not None:
                return kernel
            for scope in (env, fallback or {}):
                if fn.id in scope and isinstance(scope[fn.id], ast.Lambda):
                    return None
        if isinstance(fn, ast.FunctionDef):
            return fn
        # factory call: _make_x_kernel(...) returning an inner def —
        # follow one level to the FunctionDef the factory returns
        if isinstance(fn, ast.Call):
            factory = defs.get(df.terminal_name(fn.func))
            if factory is not None:
                inner = {n.name: n for n in ast.walk(factory)
                         if isinstance(n, ast.FunctionDef)
                         and n is not factory}
                for node in ast.walk(factory):
                    if isinstance(node, ast.Return) \
                            and isinstance(node.value, ast.Name) \
                            and node.value.id in inner:
                        return inner[node.value.id]
        return None

    def _first_unguarded_write_before_read(self, kernel: ast.FunctionDef,
                                           ref: str) -> Optional[int]:
        """Line of the first unguarded write to ``ref[...]`` that
        precedes any read, else None.  Accesses inside a
        ``@pl.when(...)``-decorated inner def are guarded — they run
        conditionally (the init-at-step-0 idiom) and do not order."""
        state = {"read": False, "write_line": None}

        def visit(node: ast.AST):
            if state["read"] or state["write_line"] is not None:
                return
            if isinstance(node, ast.FunctionDef) and any(
                    isinstance(d, ast.Call)
                    and df.terminal_name(d.func) == "when"
                    for d in node.decorator_list):
                return  # guarded block
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                # reads on the RHS happen before the store
                visit_expr(node.value)
                if state["read"]:
                    return
                for tgt in targets:
                    if self._is_ref_access(tgt, ref):
                        state["write_line"] = tgt.lineno
                        return
                    visit_expr(tgt)  # subscript indices may read the ref
                return
            if isinstance(node, ast.expr):
                visit_expr(node)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)
                if state["read"] or state["write_line"] is not None:
                    return

        def visit_expr(node: ast.AST):
            for sub in ast.walk(node):
                if self._is_ref_access(sub, ref) or (
                        isinstance(sub, ast.Name) and sub.id == ref
                        and isinstance(sub.ctx, ast.Load)):
                    state["read"] = True
                    return

        for stmt in kernel.body:
            visit(stmt)
            if state["read"] or state["write_line"] is not None:
                break
        return state["write_line"]

    @staticmethod
    def _is_ref_access(node: ast.AST, ref: str) -> bool:
        return (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == ref)
