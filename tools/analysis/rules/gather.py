"""dynamic-gather: data-movement discipline in the Pallas kernel
modules.

Supersedes ``tools/check_no_dynamic_gather.py`` (now a shim): per-lane
dynamic gathers are the one data-movement primitive this hardware
cannot do at speed (the ~96 ms ``take_along_axis`` levels behind the
BENCH_r05 dense-regime loss) and Mosaic cannot lower them in-kernel at
all.  The legacy script matched call *names* only; this rule adds the
dataflow it punted on:

* aliased imports — ``from jax.numpy import take_along_axis as g`` /
  ``h = jnp.take`` are resolved through the module alias map;
* ``getattr`` indirection — ``getattr(jnp, "take")(...)`` flags like
  the direct call, and ``getattr(jnp, name)(...)`` with a
  non-constant attr on an array library flags as unauditable;
* ``x.at[idx].get()`` / ``.set()`` / ``.add()`` — the indexed-update
  forms the legacy tool explicitly left to review.

Suppression: ``# lint-ok: dynamic-gather: <reason>`` (the legacy
``# gather-ok: <reason>`` marker is still honoured).  The grid-carry
rule that used to share this module lives in
``tools/analysis/rules/grid_carry.py`` since round 8 (same rule name,
exit bit and suppression token; re-exported here for compatibility).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df
from tools.analysis.rules.grid_carry import (  # noqa: F401  (compat re-export)
    GridCarryRule,
    _kernel_module,
)

BANNED = {
    "take_along_axis",
    "take",
    "gather",
    "dynamic_slice",
    "dynamic_update_slice",
    "dynamic_index_in_dim",
    "searchsorted",
    "scatter",
    "scatter_add",
}

#: dotted-origin prefixes that count as "an array library" for the
#: getattr-indirection check.
_ARRAY_LIBS = ("jax.numpy", "jax.lax", "numpy", "jax")

_AT_METHODS = {"get", "set", "add", "mul", "min", "max", "apply"}


class DynamicGatherRule(Rule):
    name = "dynamic-gather"
    code = 4
    doc = ("no gather/scatter-shaped calls (incl. aliases, getattr "
           "indirection, .at[...] forms) in Pallas kernel modules")
    legacy_markers = ("# gather-ok:",)

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py" and _kernel_module(path)

    def check(self, mod: ModuleSource) -> List[Violation]:
        aliases = df.build_aliases(mod.tree)
        out: List[Optional[Violation]] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            out.append(self._direct_or_aliased(mod, node, aliases))
            out.append(self._getattr_form(mod, node, aliases))
            out.append(self._at_form(mod, node))
        return [v for v in out if v is not None]

    def _flag(self, mod, lineno, name, how) -> Optional[Violation]:
        return self.violation(
            mod, lineno,
            f"dynamic-gather-shaped call '{name}' {how} in a Pallas "
            f"kernel module (the pattern behind the dense-regime "
            f"regression; use roll/sort/iota primitives, or annotate "
            f"the line with '# lint-ok: {self.name}: <reason>' if it "
            f"provably never runs on-chip)")

    def _direct_or_aliased(self, mod, node: ast.Call,
                           aliases) -> Optional[Violation]:
        name = df.terminal_name(node.func)
        if name in BANNED:
            return self._flag(mod, node.lineno, name, "")
        # a renamed import / assignment alias of a banned op
        if isinstance(node.func, ast.Name):
            origin = aliases.get(node.func.id, "")
            terminal = origin.rsplit(".", 1)[-1]
            if terminal in BANNED and terminal != node.func.id:
                return self._flag(mod, node.lineno, origin,
                                  f"(aliased as '{node.func.id}')")
        return None

    def _getattr_form(self, mod, node: ast.Call,
                      aliases) -> Optional[Violation]:
        fn = node.func
        if not (isinstance(fn, ast.Call)
                and df.terminal_name(fn.func) == "getattr"
                and len(fn.args) >= 2):
            return None
        obj, attr = fn.args[0], fn.args[1]
        origin = df.dotted_name(obj, aliases) or ""
        on_array_lib = any(
            origin == lib or origin.startswith(lib + ".")
            for lib in _ARRAY_LIBS)
        if isinstance(attr, ast.Constant) and isinstance(attr.value, str):
            if attr.value in BANNED:
                return self._flag(mod, node.lineno, attr.value,
                                  "(through getattr)")
            return None
        if on_array_lib:
            return self._flag(
                mod, node.lineno, f"getattr({origin}, <dynamic>)",
                "(unauditable dynamic attribute on an array library)")
        return None

    def _at_form(self, mod, node: ast.Call) -> Optional[Violation]:
        fn = node.func
        if (isinstance(fn, ast.Attribute) and fn.attr in _AT_METHODS
                and isinstance(fn.value, ast.Subscript)
                and isinstance(fn.value.value, ast.Attribute)
                and fn.value.value.attr == "at"):
            return self._flag(mod, node.lineno,
                              f".at[...].{fn.attr}", "(indexed update)")
        return None
