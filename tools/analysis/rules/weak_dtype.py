"""weak-dtype: no bare Python float constants inside kernel bodies or
SMEM scalar operands.

The bug class (PR 3): a bare ``0.0``/``1.0`` in a kernel traces as a
*weak* type and follows the caller's config — under the library's
global x64 mode it re-traces as f64, which Mosaic's lowering rejects
(22 interpret-mode kernel tests broke at HEAD on this image).  The fix
shape is mechanical and local — ``jnp.float32(0.0)`` — so the rule
demands it everywhere a float literal can flow into traced kernel
math:

* inside any kernel body (a function named ``kernel``/``*_kernel`` or
  passed as the first argument to ``pl.pallas_call``), every float
  literal must sit under an explicit dtype constructor
  (``jnp.float32(...)``-style) or a call carrying a ``dtype``
  argument.  Int literals stay legal: loop bounds, rotate amounts and
  iota comparisons are python-level control, and integer weak-type
  promotion against i32 operands is value-preserving.
* at a ``pl.pallas_call(...)(operands)`` invocation, an operand built
  with ``jnp.asarray``/``jnp.array``/``jnp.full`` and *no* dtype is
  flagged: that is exactly the SMEM-scalar shape that re-traced f64
  (``jnp.asarray([alpha])`` vs ``jnp.asarray([alpha], jnp.float32)``).

Suppress a deliberate weak constant with
``# lint-ok: weak-dtype: <why the promotion is safe>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional, Set

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df

#: Calls that make the element dtype explicit for every literal below
#: them.
_DTYPE_CONSTRUCTORS = {
    "float32", "float64", "float16", "bfloat16",
    "int32", "int64", "int16", "int8",
    "uint32", "uint64", "uint16", "uint8",
    "bool_", "astype", "ShapeDtypeStruct",
}

#: Array constructors whose *positional* second argument is a dtype.
_POSITIONAL_DTYPE_CTORS = {"asarray", "array", "full"}


def _has_dtype_kw(call: ast.Call) -> bool:
    return any(kw.arg == "dtype" for kw in call.keywords)


class WeakDtypeRule(Rule):
    name = "weak-dtype"
    code = 2
    doc = ("bare Python float constants in kernel bodies / SMEM scalar "
           "operands must carry an explicit dtype")

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py"

    def check(self, mod: ModuleSource) -> List[Violation]:
        tree = mod.tree
        out: List[Violation] = []
        kernels = self._kernel_defs(tree)
        for fn in kernels:
            out.extend(self._check_kernel(mod, fn))
        if "pallas_call" in mod.text:
            out.extend(self._check_operands(mod, tree))
        return [v for v in out if v is not None]

    # -- kernel discovery ----------------------------------------------

    def _kernel_defs(self, tree: ast.Module) -> List[ast.FunctionDef]:
        by_name = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                by_name.setdefault(node.name, node)
        kernels: Set[ast.FunctionDef] = set()
        for name, fn in by_name.items():
            if name == "kernel" or name.endswith("_kernel"):
                kernels.add(fn)
        # functions handed to pallas_call by name
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) \
                    and df.terminal_name(node.func) == "pallas_call" \
                    and node.args and isinstance(node.args[0], ast.Name):
                fn = by_name.get(node.args[0].id)
                if fn is not None:
                    kernels.add(fn)
        # a kernel nested in another kernel (factory named *_kernel with
        # an inner ``kernel``) is already covered by the outer walk
        nested = {
            inner
            for outer in kernels
            for inner in ast.walk(outer)
            if isinstance(inner, ast.FunctionDef) and inner is not outer
        }
        return sorted(kernels - nested, key=lambda f: f.lineno)

    # -- rule bodies ---------------------------------------------------

    def _check_kernel(self, mod: ModuleSource,
                      fn: ast.FunctionDef) -> List[Optional[Violation]]:
        out = []

        def visit(node: ast.AST, dtyped: bool):
            for child in ast.iter_child_nodes(node):
                child_dtyped = dtyped
                if isinstance(child, ast.Call):
                    name = df.terminal_name(child.func)
                    if name in _DTYPE_CONSTRUCTORS or _has_dtype_kw(child):
                        child_dtyped = True
                    elif name in _POSITIONAL_DTYPE_CTORS \
                            and len(child.args) >= 2:
                        child_dtyped = True
                if isinstance(child, ast.Constant) \
                        and isinstance(child.value, float) and not dtyped:
                    out.append(self.violation(
                        mod, child.lineno,
                        f"bare float constant {child.value!r} in kernel "
                        f"'{fn.name}' traces as a weak type and re-traces "
                        f"f64 under the library's global x64 mode (the "
                        f"22-test interpret regression class) — wrap it: "
                        f"jnp.float32({child.value!r})"))
                visit(child, child_dtyped)

        for stmt in fn.body:
            visit(stmt, False)
        return out

    def _check_operands(self, mod: ModuleSource,
                        tree: ast.Module) -> List[Optional[Violation]]:
        out = []
        for node in ast.walk(tree):
            # pl.pallas_call(...)(operand, ...) — outer Call whose func
            # is itself the pallas_call Call
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Call)
                    and df.terminal_name(node.func.func) == "pallas_call"):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Starred):
                    continue
                call = arg
                # unwrap trailing .reshape(...)/.ravel() chains
                while isinstance(call, ast.Call) \
                        and isinstance(call.func, ast.Attribute) \
                        and call.func.attr in ("reshape", "ravel"):
                    call = call.func.value
                if not isinstance(call, ast.Call):
                    continue
                name = df.terminal_name(call.func)
                if name in _POSITIONAL_DTYPE_CTORS \
                        and len(call.args) < 2 and not _has_dtype_kw(call):
                    out.append(self.violation(
                        mod, call.lineno,
                        f"'{name}' operand of a pallas_call carries no "
                        f"explicit dtype — a weak scalar here re-traces "
                        f"f64 under global x64; pass one "
                        f"(e.g. jnp.{name}(x, jnp.float32))"))
        return out
