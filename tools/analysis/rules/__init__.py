"""Rule suite of the kernel-safety analyzer.

Each module holds one decidable-bug-class family; ``ALL_RULES`` is the
engine's default battery, in exit-bit order."""

from tools.analysis.rules.vmem import VmemBudgetRule
from tools.analysis.rules.weak_dtype import WeakDtypeRule
from tools.analysis.rules.gather import DynamicGatherRule
from tools.analysis.rules.grid_carry import GridCarryRule
from tools.analysis.rules.env_knobs import EnvKnobRule
from tools.analysis.rules.excepts import BareExceptRule
from tools.analysis.rules.plan_registry import PlanRegistryRule

ALL_RULES = (
    VmemBudgetRule(),
    WeakDtypeRule(),
    DynamicGatherRule(),
    GridCarryRule(),
    EnvKnobRule(),
    BareExceptRule(),
    PlanRegistryRule(),
)

__all__ = [
    "ALL_RULES",
    "VmemBudgetRule",
    "WeakDtypeRule",
    "DynamicGatherRule",
    "GridCarryRule",
    "EnvKnobRule",
    "BareExceptRule",
    "PlanRegistryRule",
]
