"""plan-registry: every TSDF / DistributedTSDF op method that mutates
data either records a plan node or is explicitly classified eager-only.

The bug class: the lazy planner (tempo_tpu/plan/) only sees what the
op methods record.  A new frame-returning method added without a
``_plan_record`` preamble silently punches a hole in every plan that
uses it — chains break at an op nobody marked as a boundary, and the
optimizer's rewrites/pruning reason over an incomplete registry.  Like
the env-knobs rule, the registry
(``tempo_tpu.plan.ir.PLANNED_METHODS``) is the single source of truth
and this rule keeps it and the code in lockstep both ways:

* every method named in the registry must exist on its class and call
  ``_plan_record`` in its body (registry -> code);
* every *other* public frame-returning method of a registered class
  (heuristic: a ``TSDF``/``DistributedTSDF`` return annotation, or a
  ``return`` of a ``TSDF(...)`` / ``DistributedTSDF(...)`` /
  ``self._with...(...)`` call) must carry an explicit
  ``# plan-ok: eager-only`` marker on its ``def`` line (code ->
  registry): eager-only is a decision someone made, not an accident;
* a method that calls ``_plan_record`` without being declared in the
  registry is flagged too — the registry must name every recorder.
"""

from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple

from tools.analysis import dataflow as df
from tools.analysis.core import ModuleSource, Rule, Violation

_REGISTRY_REL = Path("tempo_tpu") / "plan" / "ir.py"
_MARKER_RE = re.compile(r"#\s*plan-ok:\s*eager-only")
_FRAME_CTORS = {"TSDF", "DistributedTSDF"}
_SELF_CTORS = {"_with", "_with_df"}


def _in_package(path: Path) -> bool:
    return "tempo_tpu" in path.parts


class PlanRegistryRule(Rule):
    name = "plan-registry"
    code = 128
    doc = ("TSDF/DistributedTSDF op methods must record a plan node "
           "(tempo_tpu.plan.ir.PLANNED_METHODS) or carry "
           "'# plan-ok: eager-only'")

    def applies(self, path: Path) -> bool:
        # per-file pass unused; the whole check is project-level
        return False

    # -- project pass --------------------------------------------------

    def check_project(self, root: Path,
                      files: Sequence[ModuleSource]) -> List[Violation]:
        registry = self._load_registry(files, root)
        if registry is None:
            return []  # no plan package in this tree (fixture runs)
        reg_mod, methods = registry
        out: List[Optional[Violation]] = []
        found: Dict[Tuple[str, str], bool] = {}

        for mod in files:
            if not _in_package(mod.path) or mod.tree is None:
                continue
            if "plan" in mod.path.parts:
                continue  # the lazy wrappers themselves do not re-record
            for cls in ast.walk(mod.tree):
                if not (isinstance(cls, ast.ClassDef)
                        and cls.name in methods):
                    continue
                declared = set(methods[cls.name])
                for fn in cls.body:
                    if not isinstance(fn, ast.FunctionDef):
                        continue
                    if fn.name.startswith("_") or _decorated_out(fn):
                        continue
                    records = _calls_plan_record(fn)
                    if fn.name in declared:
                        found[(cls.name, fn.name)] = True
                        if not records:
                            out.append(self.violation(
                                mod, fn.lineno,
                                f"{cls.name}.{fn.name} is declared in "
                                f"plan.ir.PLANNED_METHODS but never "
                                f"calls _plan_record — record the op "
                                f"or remove it from the registry"))
                        continue
                    if records:
                        out.append(self.violation(
                            mod, fn.lineno,
                            f"{cls.name}.{fn.name} calls _plan_record "
                            f"but is not declared in "
                            f"plan.ir.PLANNED_METHODS — declare it so "
                            f"the optimizer knows the op exists"))
                        continue
                    if _returns_frame(fn) and not _marked(mod, fn):
                        out.append(self.violation(
                            mod, fn.lineno,
                            f"{cls.name}.{fn.name} returns a frame but "
                            f"neither records a plan node nor carries "
                            f"'# plan-ok: eager-only' — classify it: "
                            f"add a _plan_record preamble (and declare "
                            f"it in plan.ir.PLANNED_METHODS) or mark "
                            f"the def line eager-only"))
        for cls_name, names in methods.items():
            for m in names:
                if not found.get((cls_name, m)):
                    out.append(self.violation(
                        reg_mod, methods_line(reg_mod, m),
                        f"plan.ir.PLANNED_METHODS declares "
                        f"{cls_name}.{m} but no such method exists on "
                        f"a scanned {cls_name} class — dead registry "
                        f"entry"))
        return [v for v in out if v is not None]

    # -- registry loading ----------------------------------------------

    def _load_registry(self, files: Sequence[ModuleSource], root: Path):
        reg = None
        for mod in files:
            if mod.path.parts[-3:] == ("tempo_tpu", "plan", "ir.py"):
                reg = mod
                break
        if reg is None:
            cand = root / _REGISTRY_REL
            if cand.exists():
                reg = ModuleSource(cand)
        if reg is None or reg.tree is None:
            return None
        for node in ast.walk(reg.tree):
            if (isinstance(node, ast.Assign) and node.targets
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == "PLANNED_METHODS"):
                try:
                    val = ast.literal_eval(node.value)
                except (ValueError, SyntaxError):
                    return None
                if isinstance(val, dict):
                    return reg, {str(k): tuple(v) for k, v in val.items()}
        return None


def methods_line(reg_mod: ModuleSource, method: str) -> int:
    for i, line in enumerate(reg_mod.lines, start=1):
        if f'"{method}"' in line or f"'{method}'" in line:
            return i
    return 1


def _decorated_out(fn: ast.FunctionDef) -> bool:
    """Skip properties / classmethods / staticmethods: they construct
    or describe frames, they are not chainable op methods."""
    for dec in fn.decorator_list:
        name = df.terminal_name(dec) if not isinstance(dec, ast.Call) \
            else df.terminal_name(dec.func)
        if name in ("property", "classmethod", "staticmethod",
                    "cached_property"):
            return True
    return False


def _calls_plan_record(fn: ast.FunctionDef) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) \
                and df.terminal_name(node.func) == "_plan_record":
            return True
    return False


def _returns_frame(fn: ast.FunctionDef) -> bool:
    """Frame-returning heuristic: a TSDF-ish return annotation, or a
    return of a frame-constructor call."""
    ann = fn.returns
    if ann is not None:
        text = ann.value if isinstance(ann, ast.Constant) else \
            df.terminal_name(ann)
        if isinstance(text, str) and "TSDF" in text:
            return True
    for node in ast.walk(fn):
        if isinstance(node, ast.Return) and isinstance(node.value,
                                                       ast.Call):
            name = df.terminal_name(node.value.func)
            if name in _FRAME_CTORS or name in _SELF_CTORS:
                return True
    return False


def _marked(mod: ModuleSource, fn: ast.FunctionDef) -> bool:
    """``# plan-ok: eager-only`` anywhere on the (possibly multi-line)
    def header — from the ``def`` line through the line the signature
    closes on."""
    for lineno in range(fn.lineno, fn.body[0].lineno + 1):
        if _MARKER_RE.search(mod.line(lineno)):
            return True
    return False
