"""bare-except: no silent failure-swallowing.

Framework port of ``tools/check_no_bare_except.py`` (now a shim), same
two anti-patterns — both defeat the resilience layer's failure
*detection* (an exception that vanishes can be neither classified nor
retried nor surfaced — ``tempo_tpu/resilience.py``):

* bare ``except:`` — catches everything including SystemExit /
  KeyboardInterrupt / SimulatedKill; always wrong;
* ``except Exception:`` (or ``BaseException``) whose body is only
  ``pass``/``...`` — a broad catch is fine, silently discarding the
  exception is not: log it or narrow the type.

Scope grew with the migration: ``tools/`` and ``tests/helpers.py``
are swept alongside ``tempo_tpu/`` (the analyzer's default path set).
Suppress with ``# lint-ok: bare-except: <reason>`` on the ``except``
line.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from tools.analysis.core import ModuleSource, Rule, Violation


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """Body is only pass / bare ellipsis — the exception is discarded."""
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in handler.body
    )


def _catches_broad(node: ast.expr) -> bool:
    """The handler type names Exception or BaseException (possibly
    inside a tuple)."""
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    for e in elts:
        name = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None)
        if name in ("Exception", "BaseException"):
            return True
    return False


class BareExceptRule(Rule):
    name = "bare-except"
    code = 32
    doc = ("no bare 'except:' and no silent 'except Exception: pass' "
           "anywhere in the swept tree")

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py"

    def check(self, mod: ModuleSource) -> List[Violation]:
        out: List[Optional[Violation]] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                out.append(self.violation(
                    mod, node.lineno,
                    "bare 'except:' catches BaseException (incl. "
                    "KeyboardInterrupt/SimulatedKill) — name the "
                    "exception types"))
            elif _catches_broad(node.type) and _is_silent(node):
                out.append(self.violation(
                    mod, node.lineno,
                    "'except Exception: pass' silently swallows failures "
                    "— log the exception or narrow the type"))
        return [v for v in out if v is not None]
