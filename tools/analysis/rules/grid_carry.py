"""grid-carry: sequential-grid scratch carries must be read before
they are overwritten.

A scratch ref on a *sequential* grid axis (``dimension_semantics``
containing ``"arbitrary"``, or a declared carry axis of
``pallas_stream.grid_semantics``) is a carry — the only state that
survives between grid steps.  A kernel whose first unguarded access to
such a ref is a WRITE destroys the previous step's carry before
reading it (cross-chunk forward-fill state, PR 3's correctness
linchpin); initialisation writes belong under a ``@pl.when(step == 0)``
guard.

Resolution (round 8): ``dimension_semantics`` built by the PR-6
``pallas_stream.grid_semantics(n_axes, carry_axes=...)`` factory is
understood without folding — a non-empty ``carry_axes`` declares a
sequential carry axis by construction (and an unfoldable carry_axes
argument is treated as sequential, conservatively).  Kernels are
resolved through a direct factory call (``_make_x_kernel(...)``) AND
through a name bound to a factory call a few lines up (the
``pallas_stream.ring_call`` idiom: ``kernel = _make_ring_kernel(...)``
then ``pl.pallas_call(kernel, ...)``).  Refs bound as ``*refs``
varargs remain unattributable and are skipped.

Suppression: ``# lint-ok: grid-carry: <reason>``.  Split out of
``rules/gather.py`` in round 8; the rule name, exit bit (8) and
suppression token are unchanged.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Optional

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df


def _kernel_module(path: Path) -> bool:
    """The files under kernel discipline: the Pallas op modules plus
    the tool/test helpers the analyzer sweeps."""
    return (
        path.name.startswith("pallas_")
        or "tools" in path.parts
        or path.name == "helpers.py"
    )


class GridCarryRule(Rule):
    name = "grid-carry"
    code = 8
    doc = ("scratch refs on sequential grid axes must be read before "
           "any unguarded write within a step")

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py" and _kernel_module(path)

    def check(self, mod: ModuleSource) -> List[Violation]:
        if "pallas_call" not in mod.text:
            return []
        tree = mod.tree
        module_env = df.assignment_env(tree.body)
        func_of = df.enclosing_function_map(tree)
        aliases = df.build_aliases(tree)
        defs = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.FunctionDef):
                defs.setdefault(node.name, node)
        out: List[Optional[Violation]] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and df.terminal_name(node.func) == "pallas_call"):
                continue
            enclosing = func_of.get(node)
            env = (df.assignment_env(enclosing.body)
                   if enclosing is not None else module_env)
            fallback = module_env if enclosing is not None else None
            out.extend(self._check_site(mod, node, env, fallback, defs,
                                        aliases))
        return [v for v in out if v is not None]

    def _check_site(self, mod, call, env, fallback, defs, aliases):
        if not self._sequential(call, env, fallback, aliases):
            return []
        n_scratch = self._scratch_count(call, env, fallback)
        if not n_scratch:
            return []
        kernel = self._resolve_kernel(call, env, fallback, defs)
        if kernel is None or kernel.args.vararg is not None:
            return []  # factory-built or *refs kernels: not attributable
        params = [a.arg for a in kernel.args.args]
        if len(params) < n_scratch:
            return []
        out = []
        for ref in params[len(params) - n_scratch:]:
            first_write = self._first_unguarded_write_before_read(
                kernel, ref)
            if first_write is not None:
                out.append(self.violation(
                    mod, first_write,
                    f"scratch ref '{ref}' rides a sequential grid axis "
                    f"(dimension_semantics 'arbitrary') but is written "
                    f"before it is read within the step — the previous "
                    f"grid step's carry is destroyed; read it first, or "
                    f"guard initialisation with @pl.when(step == 0)"))
        return out

    def _sequential(self, call, env, fallback, aliases) -> bool:
        for kw in call.keywords:
            if kw.arg != "compiler_params":
                continue
            if isinstance(kw.value, ast.Call):
                for inner in kw.value.keywords:
                    if inner.arg == "dimension_semantics":
                        return self._semantics_sequential(
                            inner.value, env, fallback, aliases)
        return False

    @staticmethod
    def _is_grid_semantics(func, aliases) -> bool:
        """The call target is pallas_stream.grid_semantics, resolved
        through the module alias map (``from ... import grid_semantics
        as gs`` must not bypass the carry check — the same aliased-
        import gap dynamic-gather closes)."""
        origin = df.dotted_name(func, aliases) or df.terminal_name(func)
        return origin.split(".")[-1] == "grid_semantics"

    def _semantics_sequential(self, node, env, fallback, aliases) -> bool:
        """True when a ``dimension_semantics`` value declares (or may
        declare) a sequential axis: a foldable tuple containing
        ``"arbitrary"``, or a ``grid_semantics(n, carry_axes=...)``
        factory call whose ``carry_axes`` is non-empty (a declared
        carry IS the sequential contract; the megacore knob only
        widens the remaining axes, never a carry axis).  A name bound
        to either form (``sems = grid_semantics(...)`` then
        ``dimension_semantics=sems``) resolves the same way."""
        if isinstance(node, ast.Name):
            for scope in (env, fallback or {}):
                if node.id in scope:
                    node = scope[node.id]
                    break
        sem = df.fold(node, env, fallback)
        if isinstance(sem, tuple):
            return "arbitrary" in sem
        if (isinstance(node, ast.Call)
                and self._is_grid_semantics(node.func, aliases)):
            carry = None
            for kw in node.keywords:
                if kw.arg == "carry_axes":
                    carry = kw.value
            if carry is None and len(node.args) >= 2:
                carry = node.args[1]
            if carry is None:
                return False  # no declared carry axes: parallel-or-knob
            folded = df.fold(carry, env, fallback)
            if isinstance(folded, tuple):
                return len(folded) > 0
            return True  # unfoldable carry declaration: assume carry
        return False

    def _scratch_count(self, call, env, fallback) -> int:
        for kw in call.keywords:
            if kw.arg == "scratch_shapes":
                node = kw.value
                if isinstance(node, ast.Name):
                    for scope in (env, fallback or {}):
                        if node.id in scope:
                            node = scope[node.id]
                            break
                if isinstance(node, (ast.List, ast.Tuple)):
                    return len(node.elts)
                return 0
        return 0

    def _resolve_kernel(self, call, env, fallback, defs):
        if not call.args:
            return None
        fn = call.args[0]
        if isinstance(fn, ast.Name):
            kernel = defs.get(fn.id)
            if kernel is not None:
                return kernel
            for scope in (env, fallback or {}):
                if fn.id in scope:
                    bound = scope[fn.id]
                    if isinstance(bound, ast.Lambda):
                        return None
                    # ``kernel = _make_ring_kernel(...)`` then
                    # ``pallas_call(kernel, ...)`` — the ring_call
                    # idiom: follow the bound factory call
                    if isinstance(bound, ast.Call):
                        resolved = self._from_factory(bound, defs)
                        if resolved is not None:
                            return resolved
                    break
        if isinstance(fn, ast.FunctionDef):
            return fn
        # factory call: _make_x_kernel(...) returning an inner def —
        # follow one level to the FunctionDef the factory returns
        if isinstance(fn, ast.Call):
            return self._from_factory(fn, defs)
        return None

    @staticmethod
    def _from_factory(fn: ast.Call, defs):
        factory = defs.get(df.terminal_name(fn.func))
        if factory is None:
            return None
        inner = {n.name: n for n in ast.walk(factory)
                 if isinstance(n, ast.FunctionDef)
                 and n is not factory}
        for node in ast.walk(factory):
            if isinstance(node, ast.Return) \
                    and isinstance(node.value, ast.Name) \
                    and node.value.id in inner:
                return inner[node.value.id]
        return None

    def _first_unguarded_write_before_read(self, kernel: ast.FunctionDef,
                                           ref: str) -> Optional[int]:
        """Line of the first unguarded write to ``ref[...]`` that
        precedes any read, else None.  Accesses inside a
        ``@pl.when(...)``-decorated inner def are guarded — they run
        conditionally (the init-at-step-0 idiom) and do not order."""
        state = {"read": False, "write_line": None}

        def visit(node: ast.AST):
            if state["read"] or state["write_line"] is not None:
                return
            if isinstance(node, ast.FunctionDef) and any(
                    isinstance(d, ast.Call)
                    and df.terminal_name(d.func) == "when"
                    for d in node.decorator_list):
                return  # guarded block
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                # reads on the RHS happen before the store
                visit_expr(node.value)
                if state["read"]:
                    return
                for tgt in targets:
                    if self._is_ref_access(tgt, ref):
                        state["write_line"] = tgt.lineno
                        return
                    visit_expr(tgt)  # subscript indices may read the ref
                return
            if isinstance(node, ast.expr):
                visit_expr(node)
                return
            for child in ast.iter_child_nodes(node):
                visit(child)
                if state["read"] or state["write_line"] is not None:
                    return

        def visit_expr(node: ast.AST):
            for sub in ast.walk(node):
                if self._is_ref_access(sub, ref) or (
                        isinstance(sub, ast.Name) and sub.id == ref
                        and isinstance(sub.ctx, ast.Load)):
                    state["read"] = True
                    return

        for stmt in kernel.body:
            visit(stmt)
            if state["read"] or state["write_line"] is not None:
                break
        return state["write_line"]

    @staticmethod
    def _is_ref_access(node: ast.AST, ref: str) -> bool:
        return (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)
                and node.value.id == ref)
