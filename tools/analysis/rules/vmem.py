"""vmem-budget: every ``pl.pallas_call`` site must provably fit VMEM.

The bug class (PR 3, BASELINE r3): a kernel whose per-step buffers are
sized from data-dependent extents compiles fine on small inputs and
OOMs the compiler/chip at scale — the ~205K-merged-lane XLA cliff, and
the measured [32, 16384] f32 block that blew the 16M scoped-VMEM cap
at 23.5M.  The dynamic twin of this check is ``packing.asof_chunk_plan``
/ ``pallas_kernels._plan``; this rule is the static one, run at lint
time over every call site:

* Block shapes (BlockSpec), ``out_shape`` dtypes, and
  ``scratch_shapes`` are folded to constants where the source allows.
  A fully resolved site whose worst-case per-step bytes — VMEM-blocked
  inputs and outputs double-buffered (Mosaic pipelines I/O: the
  implicit 2x multi-buffering), scratch at its FULL declared shape —
  exceed the budget (``vmem_limit_bytes`` from ``compiler_params``
  when given, else the 16 MiB scoped default) is a violation outright.
  An explicit N-deep DMA ring (ops/pallas_stream.py) declares its
  buffering as the ring scratch's leading dim, so the N-fold cost is
  counted through the same shape folding; its ``memory_space=ANY``
  operands stay in HBM and count ZERO VMEM (the ring scratch IS their
  on-chip footprint), and DMA semaphores live in semaphore memory.
* A site with *unresolvable* extents (runtime ``K``/``L``) must sit in
  a function that consults a chunking/feasibility planner (a call
  whose name mentions plan/feasible/supported/chunk — ``_plan``,
  ``_plan_merge``, ``merge_join_supported``, ``asof_chunk_plan``
  ...); otherwise nothing bounds the bytes and the site is flagged.

The model counts *declared* buffers only — Mosaic's own network
temporaries are the planner's job (its ``arrays``/plane multipliers);
a static rule that guessed them would bless or damn sites on fiction.
Suppress a site whose guard lives in its callers with
``# lint-ok: vmem-budget: <where the plan is>``.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Any, List, Optional

from tools.analysis.core import ModuleSource, Rule, Violation
from tools.analysis import dataflow as df
from tools.analysis.dataflow import UNKNOWN

DEFAULT_BUDGET = 16 * 2**20  # Mosaic's default scoped-VMEM cap

_DTYPE_BYTES = {
    "float64": 8, "int64": 8, "uint64": 8,
    "float32": 4, "int32": 4, "uint32": 4,
    "float16": 2, "bfloat16": 2, "int16": 2, "uint16": 2,
    "int8": 1, "uint8": 1, "bool_": 1, "bool": 1,
}

#: a call counts as a chunking/feasibility guard when one of its
#: name's underscore-separated segments IS one of these tokens
#: (substring matching blessed 'explain'/'log_chunks'-style names).
_GUARD_HINTS = ("plan", "plans", "feasible", "supported", "chunk")


class _Spec:
    """One resolved BlockSpec: byte size per block, or UNKNOWN."""

    def __init__(self, bytes_per_block: Any, memory_space: str):
        self.bytes_per_block = bytes_per_block
        self.memory_space = memory_space


def _dtype_bytes(node: Optional[ast.expr]) -> Any:
    """jnp.float32 / np.int8 / 'float32' -> element size."""
    if node is None:
        return 4  # operand dtypes are invisible statically; assume word
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return _DTYPE_BYTES.get(node.value, UNKNOWN)
    name = df.terminal_name(node)
    return _DTYPE_BYTES.get(name, UNKNOWN)


def _shape_bytes(shape: Any, elem: Any) -> Any:
    if shape is UNKNOWN or elem is UNKNOWN:
        return UNKNOWN
    if not isinstance(shape, tuple):
        return UNKNOWN
    total = elem
    for dim in shape:
        if not isinstance(dim, int):
            return UNKNOWN
        total *= dim
    return total


def _kw(call: ast.Call, name: str) -> Optional[ast.expr]:
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


class VmemBudgetRule(Rule):
    name = "vmem-budget"
    code = 1
    doc = ("pallas_call sites must statically fit the VMEM budget or "
           "sit behind a chunking/feasibility planner")

    def applies(self, path: Path) -> bool:
        return path.suffix == ".py"

    def check(self, mod: ModuleSource) -> List[Violation]:
        if "pallas_call" not in mod.text:
            return []
        tree = mod.tree
        module_env = df.assignment_env(tree.body)
        func_of = df.enclosing_function_map(tree)
        out: List[Violation] = []
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and df.terminal_name(node.func) == "pallas_call"):
                continue
            enclosing = func_of.get(node)
            scope = (df.assignment_env(enclosing.body)
                     if enclosing is not None else module_env)
            fallback = module_env if enclosing is not None else None
            v = self._check_site(mod, node, enclosing, scope, fallback)
            if v is not None:
                out.append(v)
        return out

    # -- site analysis -------------------------------------------------

    def _check_site(self, mod, call, enclosing, env, fallback):
        budget = self._budget(call, env, fallback)
        total = 0
        # an unfoldable vmem_limit_bytes means the real cap is unknown:
        # the site must be guarded like any other unresolvable extent
        unresolved = budget is UNKNOWN

        for kw_name, pipelined in (("in_specs", True), ("out_specs", True)):
            specs = self._spec_list(_kw(call, kw_name), env, fallback)
            if specs is UNKNOWN:
                unresolved = True
                continue
            for spec in specs:
                if spec.memory_space in ("SMEM", "ANY"):
                    # scalar prefetch lives outside VMEM; ANY operands
                    # stay in HBM — a manual-DMA kernel's on-chip bytes
                    # are its declared ring/stage scratch
                    continue
                if spec.bytes_per_block is UNKNOWN:
                    unresolved = True
                else:
                    total += spec.bytes_per_block * (2 if pipelined else 1)

        scratch = self._scratch_bytes(_kw(call, "scratch_shapes"),
                                      env, fallback)
        if scratch is UNKNOWN:
            unresolved = True
        else:
            total += scratch

        if not unresolved and isinstance(budget, int) and total > budget:
            return self.violation(
                mod, call.lineno,
                f"pallas_call declares ~{total} bytes of per-step VMEM "
                f"(I/O blocks double-buffered + scratch) against a "
                f"{budget}-byte budget — shrink the blocks or grid over "
                f"the long axis (cf. packing.asof_chunk_plan / "
                f"pallas_kernels._plan)")
        if unresolved and not self._guarded(enclosing, mod):
            return self.violation(
                mod, call.lineno,
                "pallas_call block/scratch extents are not statically "
                "resolvable and no chunking guard (a *plan*/*feasible*/"
                "*supported* planner call) bounds them in the enclosing "
                "function — unbounded shapes re-create the ~205K-lane "
                "compiler-OOM class; add a VMEM plan or suppress with "
                "'# lint-ok: vmem-budget: <where the plan lives>'")
        return None

    def _budget(self, call, env, fallback) -> Any:
        cp = _kw(call, "compiler_params")
        if isinstance(cp, ast.Name):
            # params object built a few lines up: follow the assignment
            for scope in (env, fallback or {}):
                if cp.id in scope:
                    cp = scope[cp.id]
                    break
        if cp is None:
            return DEFAULT_BUDGET
        if isinstance(cp, ast.Call):
            limit = _kw(cp, "vmem_limit_bytes")
            if limit is not None:
                folded = df.fold(limit, env, fallback)
                return folded if isinstance(folded, int) else UNKNOWN
            return DEFAULT_BUDGET
        # unrecognized params expression: the raised-cap case cannot be
        # ruled out, nor confirmed — treat as unknown (guard required)
        return UNKNOWN

    def _spec_list(self, node, env, fallback) -> Any:
        """Resolve an in_specs/out_specs expression to a list of
        _Spec, or UNKNOWN.  Handles literals, ``[spec] * n``,
        list concatenation, and names bound to either."""
        if node is None:
            # defaulted specs block over the whole operand — sized by
            # runtime shapes, so statically unbounded
            return UNKNOWN
        if isinstance(node, ast.Name):
            for scope in (env, fallback or {}):
                if node.id in scope:
                    return self._spec_list(scope[node.id], env, fallback)
            return UNKNOWN
        if isinstance(node, (ast.List, ast.Tuple)):
            specs = []
            for elt in node.elts:
                sub = self._spec_list(elt, env, fallback)
                if sub is UNKNOWN:
                    return UNKNOWN
                specs.extend(sub)
            return specs
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Mult):
            for seq, count in ((node.left, node.right),
                               (node.right, node.left)):
                sub = self._spec_list(seq, env, fallback)
                if sub is UNKNOWN:
                    continue
                n = df.fold(count, env, fallback)
                if isinstance(n, int):
                    return sub * n
            return UNKNOWN
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            lhs = self._spec_list(node.left, env, fallback)
            rhs = self._spec_list(node.right, env, fallback)
            if lhs is UNKNOWN or rhs is UNKNOWN:
                return UNKNOWN
            return lhs + rhs
        if isinstance(node, ast.Call):
            name = df.terminal_name(node.func)
            if name == "BlockSpec":
                return [self._block_spec(node, env, fallback)]
        return UNKNOWN

    def _block_spec(self, call: ast.Call, env, fallback) -> "_Spec":
        space = "VMEM"
        ms = _kw(call, "memory_space")
        if ms is not None:
            space = df.terminal_name(ms) or "VMEM"
        shape_node = call.args[0] if call.args else _kw(call, "block_shape")
        if shape_node is None:
            # whole-operand block: sized by the runtime operand (0 for
            # the non-VMEM spaces — SMEM scalars, HBM-resident ANY)
            return _Spec(0 if space in ("SMEM", "ANY") else UNKNOWN,
                         space)
        shape = df.fold(shape_node, env, fallback)
        return _Spec(_shape_bytes(shape, 4), space)

    def _scratch_bytes(self, node, env, fallback) -> Any:
        if node is None:
            return 0
        if isinstance(node, ast.Name):
            for scope in (env, fallback or {}):
                if node.id in scope:
                    return self._scratch_bytes(scope[node.id], env, fallback)
            return UNKNOWN
        if isinstance(node, (ast.List, ast.Tuple)):
            total = 0
            for elt in node.elts:
                sub = self._scratch_bytes(elt, env, fallback)
                if sub is UNKNOWN:
                    return UNKNOWN
                total += sub
            return total
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            lhs = self._scratch_bytes(node.left, env, fallback)
            rhs = self._scratch_bytes(node.right, env, fallback)
            if lhs is UNKNOWN or rhs is UNKNOWN:
                return UNKNOWN
            return lhs + rhs
        if isinstance(node, ast.Call):
            name = df.terminal_name(node.func)
            if name in ("SMEM", "SemaphoreType", "DMA", "REGULAR",
                        "BARRIER"):
                # SMEM scalars and semaphores (pltpu.SemaphoreType.DMA
                # calls resolve to their rightmost attr) are not VMEM
                return 0
            if name == "VMEM":
                shape = df.fold(call_arg(node, 0), env, fallback)
                elem = _dtype_bytes(call_arg(node, 1))
                return _shape_bytes(shape, elem)
        return UNKNOWN

    def _guarded(self, enclosing, mod: ModuleSource) -> bool:
        scope = enclosing if enclosing is not None else mod.tree
        for node in ast.walk(scope):
            if isinstance(node, ast.Call):
                name = df.terminal_name(node.func).lower()
                segments = [s for s in name.split("_") if s]
                if any(s in _GUARD_HINTS for s in segments):
                    return True
        return False


def call_arg(call: ast.Call, i: int) -> Optional[ast.expr]:
    return call.args[i] if len(call.args) > i else None
