"""Concurrency-discipline analyzer tier (``python tools/analyze.py
--threads``).

Third static-analysis tier of the project: where ``tools/analysis``
checks kernel-safety *source* patterns and ``tools/analysis/compiled``
checks **compiled artifacts**, this tier checks the **threaded host
runtime** — the plane where every concurrency bug PRs 8-14 caught by
manual review actually lived.  It builds a thread-entry graph and a
lock-site map over the swept files (``threadmodel.py``), then runs
five rules (``rules.py``): guarded-attr, wait-loop, lock-order,
blocking-under-lock, ticket-resolution.  Reuses the core engine
(shared parses, ``# lint-ok: <rule>: <reason>`` suppressions,
power-of-two exit bits in this tier's OWN bit space, the
dead-suppression audit).  See BUILDING.md "Concurrency discipline".
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Optional, Sequence

from tools.analysis import core
from tools.analysis.concurrency.rules import CONCURRENCY_RULES  # noqa: F401

_REPO = Path(__file__).resolve().parent.parent.parent.parent


def default_paths() -> List[Path]:
    """The enforced sweep: the runtime package and the dryrun entry
    point (its stderr-filter pump thread).  Unlike the AST tier,
    ``tools/`` and test helpers are NOT swept — they spawn no
    threads; the threaded plane is the package itself."""
    return [_REPO / "tempo_tpu", _REPO / "__graft_entry__.py"]


def main(paths: Optional[Sequence[Path]] = None,
         rules: Optional[Sequence[str]] = None) -> int:
    """Run the battery, print findings, return the tier's exit-bit OR
    (``analyze.py`` folds it into the 8-bit process status)."""
    battery = list(CONCURRENCY_RULES)
    if rules:
        known = {r.name: r for r in CONCURRENCY_RULES}
        unknown = [n for n in rules if n not in known]
        if unknown:
            # a CLI usage error, NOT a finding: exit 2, matching the
            # compiled tier's convention (the bit table stays honest)
            print(f"unknown concurrency rule(s): {', '.join(unknown)} "
                  f"(see analyze.py --list-rules)", file=sys.stderr)
            return 2
        battery = [known[n] for n in rules]

    swept = [Path(p) for p in paths] if paths else \
        [p for p in default_paths() if p.exists()]
    files = core.load_sources(swept)
    # the dead-suppression audit needs the WHOLE battery's hits to
    # judge a marker dead — a --rule-filtered run skips it
    violations, exit_code = core.run(battery, files, root=_REPO,
                                     audit=rules is None)
    for v in violations:
        print(v.render())
    if violations:
        by_rule = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        detail = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
        print(f"{len(violations)} concurrency finding(s) ({detail}) "
              f"over {len(files)} file(s); exit code {exit_code}",
              file=sys.stderr)
    else:
        print(f"concurrency discipline clean over {len(files)} file(s)",
              file=sys.stderr)
    return exit_code
