"""The concurrency-discipline rule battery (``analyze.py --threads``).

Five decidable bug classes over the threaded host runtime, each one a
direct descendant of a bug CHANGES.md records being caught by manual
review (PRs 8-14): guarded-attr (the close-sentinel TOCTOU), wait-loop
(the lost-query deque race and the spurious ``queue.Full``),
lock-order (nested-acquisition cycles), blocking-under-lock (the
dispatch-stall family), ticket-resolution (forever-blocked tickets on
``close()``).  The tier owns its exit-bit space — see
``tools/analyze.py --list-rules``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis import core
from tools.analysis import dataflow
from tools.analysis.concurrency import threadmodel as tm


def _own_nodes(func_node: tm.FuncNode) -> List[ast.AST]:
    """Nodes of a function body excluding nested function/lambda
    subtrees (a nested def runs in whatever context CALLS it)."""
    out: List[ast.AST] = []

    def visit(n: ast.AST) -> None:
        for c in ast.iter_child_nodes(n):
            if isinstance(c, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.Lambda)):
                continue
            out.append(c)
            visit(c)

    visit(func_node)
    return out


def _wait_recv(project: tm.ProjectModel, mm: tm.ModuleModel,
               fi: Optional[tm.FuncInfo],
               call: ast.Call) -> Optional[Tuple[str, tm.LockKey, str]]:
    """Classify ``X.wait(...)`` / ``X.wait_for(...)`` receivers:
    ('condition'|'event', key, attr-or-name) or None for unknown."""
    if not isinstance(call.func, ast.Attribute):
        return None
    recv = call.func.value
    got = tm.resolve_lock_expr(project, mm, fi, recv)
    if got is not None and got[1] == "condition":
        return ("condition", got[0], tm.render_key(got[0]))
    chain = tm.attr_chain(recv)
    if not chain:
        return None
    cls = fi.cls if fi is not None else None
    if chain[0] == "self" and cls is not None and len(chain) == 2:
        flat = project.flattened(cls)
        if chain[1] in flat.event_attrs:
            key = ("cls", cls.name, chain[1])
            return ("event", key, tm.render_key(key))
    if len(chain) == 1 and fi is not None:
        cur: Optional[tm.FuncInfo] = fi
        while cur is not None:
            if chain[0] in cur.local_events:
                key = ("fn", f"{mm.key}:{cur.qualname}", chain[0])
                return ("event", key, chain[0])
            cur = tm._enclosing_funcinfo(mm, cur.node)
    return None


class ConcurrencyRule(core.Rule):
    """Base: concurrency rules are whole-project passes sharing one
    :class:`threadmodel.ProjectModel` per invocation."""

    def applies(self, path) -> bool:  # project-pass only
        return False

    def check_project(self, root, files) -> List[core.Violation]:
        model = tm.get_model(files)
        out: List[core.Violation] = []
        for mm in model.modules:
            out.extend(self.check_module(model, mm))
        out.extend(self.finish(model))
        return out

    def check_module(self, project: tm.ProjectModel,
                     mm: tm.ModuleModel) -> List[core.Violation]:
        return []

    def finish(self, project: tm.ProjectModel) -> List[core.Violation]:
        return []


# ---------------------------------------------------------------------


class GuardedAttrRule(ConcurrencyRule):
    name = "guarded-attr"
    code = 1
    doc = ("shared attributes written from >=2 thread contexts must "
           "declare '# guarded-by: <lock>' and every access must hold "
           "it (checked both ways; '# thread-shared' classes count "
           "callers as concurrent)")

    def check_project(self, root, files):
        #: (id(owner cls), attr) -> [mm, decl_ln, cls name, hits]
        self._decls: Dict[Tuple[int, str], list] = {}
        return super().check_project(root, files)

    def check_module(self, project, mm):
        out: List[core.Violation] = []
        seen: Set[Tuple[int, str]] = set()
        for cls in mm.classes.values():
            out.extend(self._check_class(project, mm, cls, seen))
        out.extend(self._check_globals(project, mm))
        out.extend(self._check_closures(project, mm))
        out.extend(self._check_cross_object(project, mm))
        return out

    def finish(self, project):
        # a declaration is stale only if NO class in the hierarchy
        # (base or subclass, any module) accesses the attribute
        out: List[core.Violation] = []
        for (_oid, attr), (mm, decl_ln, cname, hits) in sorted(
                self._decls.items(),
                key=lambda kv: (str(kv[1][0].mod.path), kv[1][1])):
            if hits:
                continue
            v = self.violation(
                mm.mod, decl_ln,
                f"stale '# guarded-by' on {cname}.{attr}: the "
                f"attribute is never accessed outside __init__ — "
                f"delete the annotation or the attribute")
            if v is not None:
                out.append(v)
        return out

    # -- instance attributes ------------------------------------------

    def _check_class(self, project, mm, cls, seen):
        out: List[core.Violation] = []
        flat = project.flattened(cls)
        accesses = [a for a in tm.collect_self_accesses(flat)
                    if a.method not in ("__init__", "__del__")]
        ctxs = flat.contexts()
        by_attr: Dict[str, List[tm.AttrAccess]] = {}
        for a in accesses:
            by_attr.setdefault(a.attr, []).append(a)

        for attr, (spec, decl_ln, owner) in sorted(
                flat.guarded_attrs.items()):
            got = self._resolve_spec(project, mm, flat, spec)
            if got is None:
                if owner is cls:  # decl-site checks: defining class only
                    v = self.violation(
                        mm.mod, decl_ln,
                        f"'# guarded-by: {spec}' on {cls.name}.{attr} "
                        f"names no known lock site — declare the lock "
                        f"(threading.Lock/RLock/Condition) or fix the "
                        f"spec")
                    if v is not None:
                        out.append(v)
                continue
            key, _kind = got
            acc = by_attr.get(attr, [])
            rec = self._decls.setdefault(
                (id(owner), attr),
                [self._mod_of(project, owner) or mm, decl_ln,
                 owner.name, 0])
            rec[3] += len(acc)
            if not acc:
                continue
            for a in acc:
                if (a.lineno, attr) in seen:
                    continue
                held = tm.locks_held(project, mm, a.node)
                if key not in held:
                    seen.add((a.lineno, attr))
                    kind = "write to" if a.is_write else "read of"
                    v = self.violation(
                        mm.mod, a.lineno,
                        f"{kind} {cls.name}.{attr} (declared "
                        f"# guarded-by: {spec}) without holding "
                        f"{tm.render_key(key)} — take the lock or "
                        f"annotate the enclosing def "
                        f"'# guarded-by: {spec}' if callers hold it")
                    if v is not None:
                        out.append(v)

        # undeclared attrs written from >= 2 contexts
        for attr, acc in sorted(by_attr.items()):
            if attr in flat.guarded_attrs:
                continue
            writes = [a for a in acc if a.is_write]
            if not writes:
                continue
            labels: Set[str] = set()
            for a in writes:
                labels |= ctxs.get(a.method, set())
            if flat.context_weight(labels) < 2:
                continue
            first = min(writes, key=lambda a: a.lineno)
            if (first.lineno, attr) in seen:
                continue
            seen.add((first.lineno, attr))
            pretty = ", ".join(sorted(labels))
            v = self.violation(
                mm.mod, first.lineno,
                f"{cls.name}.{attr} is written from multiple thread "
                f"contexts ({pretty}) with no '# guarded-by: <lock>' "
                f"declaration — declare the guarding lock on its "
                f"__init__ binding line (and hold it at every access), "
                f"or suppress with a reason if it is provably safe")
            if v is not None:
                out.append(v)
        return out

    def _resolve_spec(self, project, mm, flat, spec):
        try:
            expr = ast.parse(spec, mode="eval").body
        except SyntaxError:
            return None
        chain = tm.attr_chain(expr)
        if not chain:
            return None
        if chain[0] == "self":
            rest = chain[1:]
            if len(rest) == 1:
                return flat.lock_key(rest[0])
            if len(rest) == 2 and rest[0] in flat.attr_class:
                other = project.class_index.get(flat.attr_class[rest[0]])
                if other is not None:
                    return project.flattened(other).lock_key(rest[1])
            return None
        if len(chain) == 1 and chain[0] in mm.module_locks:
            return ("mod", mm.key, chain[0]), mm.module_locks[chain[0]]
        return None

    def _mod_of(self, project, cls):
        for m in project.modules:
            if cls.name in m.classes and m.classes[cls.name] is cls:
                return m
        return None

    # -- module globals (opt-in via annotation) -----------------------

    def _check_globals(self, project, mm):
        out: List[core.Violation] = []
        for gname, (spec, decl_ln) in sorted(mm.module_guarded.items()):
            got = tm.resolve_lock_spec(project, mm, None, spec)
            if got is None:
                v = self.violation(
                    mm.mod, decl_ln,
                    f"'# guarded-by: {spec}' on module global {gname!r} "
                    f"names no known lock site in this module")
                if v is not None:
                    out.append(v)
                continue
            key, _kind = got
            hit = False
            for fnode, fi in mm.funcs.items():
                bound = {a.arg for a in fnode.args.args}
                bound |= {a.arg for a in fnode.args.kwonlyargs}
                owns = list(_own_nodes(fnode))
                for n in owns:
                    if isinstance(n, ast.Assign):
                        for t in n.targets:
                            if isinstance(t, ast.Name):
                                bound.add(t.id)
                has_global = any(
                    isinstance(n, ast.Global) and gname in n.names
                    for n in owns)
                if gname in bound and not has_global:
                    continue  # shadowed: a different, local name
                for n in owns:
                    if not (isinstance(n, ast.Name) and n.id == gname):
                        continue
                    hit = True
                    held = tm.locks_held(project, mm, n)
                    if key not in held:
                        v = self.violation(
                            mm.mod, n.lineno,
                            f"access to module global {gname!r} "
                            f"(declared # guarded-by: {spec}) without "
                            f"holding {tm.render_key(key)}")
                        if v is not None:
                            out.append(v)
            if not hit:
                v = self.violation(
                    mm.mod, decl_ln,
                    f"stale '# guarded-by' on module global {gname!r}: "
                    f"no function accesses it — delete the annotation")
                if v is not None:
                    out.append(v)
        return out

    # -- closure-shared locals (the sweep_slabs pattern) --------------

    def _check_closures(self, project, mm):
        out: List[core.Violation] = []
        containers: Dict[tm.FuncNode, List[tm.ThreadEntry]] = {}
        for e in mm.entries:
            if e.target is None or e.target.cls is not None:
                continue
            host = tm._enclosing_funcinfo(mm, e.target.node)
            if host is not None:
                containers.setdefault(host.node, []).append(e)
        for host_node, entries in containers.items():
            host_fi = mm.funcs[host_node]
            publish_ln = min(e.lineno for e in entries)
            targets = {e.target.node: e for e in entries}
            own = _own_nodes(host_node)
            host_bound = {a.arg for a in host_node.args.args}
            for n in own:
                if isinstance(n, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    tgts = (n.targets if isinstance(n, ast.Assign)
                            else [n.target])
                    for t in tgts:
                        if isinstance(t, ast.Name):
                            host_bound.add(t.id)
            # free-variable uses/writes per nested thread target
            shared: Dict[str, Dict[str, object]] = {}

            def note(name, lineno, label, write):
                if name not in host_bound:
                    return
                if name in host_fi.local_locks \
                        or name in host_fi.local_queues \
                        or name in host_fi.local_events \
                        or name in host_fi.local_threads:
                    return
                rec = shared.setdefault(
                    name, {"labels": set(), "writes": [], "reads": []})
                rec["labels"].add(label) if write else None
                (rec["writes"] if write else rec["reads"]).append(
                    (lineno, label))

            for tnode, entry in targets.items():
                label = f"thread:{mm.funcs[tnode].name}"
                if entry.multi:
                    label += "[xN]"
                tbound = {a.arg for a in tnode.args.args}
                nonlocals: Set[str] = set()
                for n in ast.walk(tnode):
                    if isinstance(n, ast.Nonlocal):
                        nonlocals |= set(n.names)
                for n in ast.walk(tnode):
                    if isinstance(n, (ast.Assign, ast.AugAssign)):
                        tgts = (n.targets if isinstance(n, ast.Assign)
                                else [n.target])
                        for t in tgts:
                            if isinstance(t, ast.Name) \
                                    and t.id in nonlocals:
                                note(t.id, n.lineno, label, True)
                            elif isinstance(t, ast.Subscript) \
                                    and isinstance(t.value, ast.Name) \
                                    and t.value.id not in tbound:
                                note(t.value.id, n.lineno, label, True)
                    elif isinstance(n, ast.Call) \
                            and isinstance(n.func, ast.Attribute) \
                            and isinstance(n.func.value, ast.Name) \
                            and n.func.attr in tm.MUTATORS \
                            and n.func.value.id not in tbound:
                        note(n.func.value.id, n.lineno, label, True)
            # host-body writes after thread publication
            for n in own:
                if getattr(n, "lineno", 0) <= publish_ln:
                    continue
                if isinstance(n, ast.Call) \
                        and isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.attr in tm.MUTATORS:
                    note(n.func.value.id, n.lineno, tm.CALLER, True)
                elif isinstance(n, (ast.Assign, ast.AugAssign)):
                    tgts = (n.targets if isinstance(n, ast.Assign)
                            else [n.target])
                    for t in tgts:
                        if isinstance(t, ast.Subscript) \
                                and isinstance(t.value, ast.Name):
                            note(t.value.id, n.lineno, tm.CALLER, True)
            for name, rec in sorted(shared.items()):
                writes = rec["writes"]
                labels = {lab for _, lab in writes}
                weight = sum(2 if lab.endswith("[xN]") else 1
                             for lab in labels)
                if weight < 2 or not writes:
                    continue
                first = min(ln for ln, _ in writes)
                pretty = ", ".join(sorted(labels))
                v = self.violation(
                    mm.mod, first,
                    f"closure variable {name!r} of "
                    f"{host_fi.qualname}() is written from multiple "
                    f"thread contexts ({pretty}) with no lock — guard "
                    f"it with a function-local threading.Lock or "
                    f"suppress with a reason if the interleaving is "
                    f"provably safe")
                if v is not None:
                    out.append(v)
        return out

    # -- cross-object accesses (self.breaker._st) ---------------------

    def _check_cross_object(self, project, mm):
        out: List[core.Violation] = []
        for node in ast.walk(mm.mod.tree):
            chain = None
            if isinstance(node, ast.Attribute):
                chain = tm.attr_chain(node)
            if not chain or len(chain) < 3 or chain[0] != "self":
                continue
            fi = tm._enclosing_funcinfo(mm, node)
            if fi is None or fi.cls is None:
                continue
            flat = project.flattened(fi.cls)
            other_name = flat.attr_class.get(chain[1])
            if other_name is None:
                continue
            other = project.class_index.get(other_name)
            if other is None:
                continue
            oflat = project.flattened(other)
            guarded = oflat.guarded_attrs.get(chain[2])
            if guarded is None:
                continue
            spec, _ln, _owner = guarded
            got = self._resolve_spec(project, mm, oflat, spec)
            if got is None:
                continue
            key, _kind = got
            held = tm.locks_held(project, mm, node)
            if key not in held:
                v = self.violation(
                    mm.mod, node.lineno,
                    f"access to {other_name}.{chain[2]} through "
                    f"self.{chain[1]} (declared # guarded-by: {spec}) "
                    f"without holding {tm.render_key(key)}")
                if v is not None:
                    out.append(v)
        return out


# ---------------------------------------------------------------------


class WaitLoopRule(ConcurrencyRule):
    name = "wait-loop"
    code = 2
    doc = ("Condition.wait must sit in a while-predicate loop, a timed "
           "wait's False result must not directly gate a raise "
           "(re-check the predicate — the spurious queue.Full class), "
           "and locals aliasing shared state before a wait must be "
           "re-resolved after the wake (the lost-query deque race)")

    def check_module(self, project, mm):
        out: List[core.Violation] = []
        for fnode, fi in mm.funcs.items():
            out.extend(self._check_func(project, mm, fi))
        return out

    def _check_func(self, project, mm, fi):
        out: List[core.Violation] = []
        own = _own_nodes(fi.node)
        parents = mm.parents
        waits = []  # (call, kind 'wait'|'wait_for', recv info)
        for n in own:
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and n.func.attr in ("wait", "wait_for"):
                recv = _wait_recv(project, mm, fi, n)
                if recv is not None and recv[0] == "condition":
                    waits.append((n, n.func.attr, recv))
        if not waits:
            return out

        assigned_names = {}
        for n in own:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name):
                assigned_names.setdefault(
                    n.targets[0].id, []).append(n)

        for call, meth, (_, _key, pretty) in waits:
            enclosing_whiles = []
            cur = parents.get(call)
            while cur is not None and cur is not fi.node:
                if isinstance(cur, ast.While):
                    enclosing_whiles.append(cur)
                cur = parents.get(cur)
            # w1: bare wait outside any while loop
            if meth == "wait" and not enclosing_whiles:
                v = self.violation(
                    mm.mod, call.lineno,
                    f"{pretty}.wait() outside a while-predicate loop — "
                    f"a wake is a hint, not a guarantee (spurious "
                    f"wakeups, stolen predicates); loop on the "
                    f"predicate or use wait_for")
                if v is not None:
                    out.append(v)
            # w2: timed wait result gating a raise
            if meth == "wait" and (call.args or call.keywords):
                out.extend(self._check_timed_gate(
                    mm, fi, call, pretty, assigned_names, parents))
            # w3: stale aliases across the wait
            if enclosing_whiles:
                out.extend(self._check_stale_alias(
                    project, mm, fi, call, enclosing_whiles[0],
                    pretty, own))
        return out

    def _check_timed_gate(self, mm, fi, call, pretty, assigned, parents):
        out = []

        def fires_if(test_node, anchor):
            if isinstance(test_node, ast.UnaryOp) \
                    and isinstance(test_node.op, ast.Not):
                inner = test_node.operand
                if inner is call:
                    return True
                if isinstance(inner, ast.Name):
                    for a in assigned.get(inner.id, []):
                        if a.value is call:
                            return True
            return False

        for n in _own_nodes(fi.node):
            if isinstance(n, ast.If) and fires_if(n.test, n) \
                    and any(isinstance(s, ast.Raise)
                            for s in ast.walk(n)):
                v = self.violation(
                    mm.mod, n.lineno,
                    f"a False return from timed {pretty}.wait() only "
                    f"means the timeout elapsed, not that the "
                    f"predicate is false — re-check the predicate "
                    f"before raising (the spurious queue.Full class)")
                if v is not None:
                    out.append(v)
        return out

    def _check_stale_alias(self, project, mm, fi, call, loop,
                           pretty, own):
        out = []
        loop_nodes = set(id(x) for x in ast.walk(loop))
        rebound_after = set()
        for n in ast.walk(loop):
            if isinstance(n, (ast.Assign, ast.AugAssign)) \
                    and getattr(n, "lineno", 0) > call.lineno:
                tgts = (n.targets if isinstance(n, ast.Assign)
                        else [n.target])
                for t in tgts:
                    if isinstance(t, ast.Name):
                        rebound_after.add(t.id)
        candidates = {}
        for n in own:
            if isinstance(n, ast.Assign) and len(n.targets) == 1 \
                    and isinstance(n.targets[0], ast.Name) \
                    and n.lineno < call.lineno:
                name = n.targets[0].id
                rooted = False
                for sub in ast.walk(n.value):
                    if isinstance(sub, ast.Attribute) \
                            and isinstance(sub.value, ast.Name) \
                            and sub.value.id == "self":
                        flat = (project.flattened(fi.cls)
                                if fi.cls else None)
                        if flat is not None \
                                and sub.attr in flat.sync_attrs:
                            continue  # lock/cv aliases are fine
                        rooted = True
                if rooted and name not in rebound_after:
                    candidates[name] = n
        if not candidates:
            return out
        mutators = tm.MUTATORS | {"put", "put_nowait"}
        for n in own:
            if getattr(n, "lineno", 0) <= call.lineno:
                continue
            use = None
            if isinstance(n, ast.Call) \
                    and isinstance(n.func, ast.Attribute) \
                    and isinstance(n.func.value, ast.Name) \
                    and n.func.attr in mutators \
                    and n.func.value.id in candidates:
                use = n.func.value.id
            elif isinstance(n, ast.Subscript) \
                    and isinstance(n.ctx, (ast.Store, ast.Del)) \
                    and isinstance(n.value, ast.Name) \
                    and n.value.id in candidates:
                use = n.value.id
            if use is None:
                continue
            src = candidates.pop(use)
            v = self.violation(
                mm.mod, n.lineno,
                f"local {use!r} (bound from shared state at line "
                f"{src.lineno}) is mutated after {pretty}.{call.func.attr}"
                f"() without being re-resolved after the wake — the "
                f"wait releases the lock, so the binding may be stale "
                f"(the lost-query deque race); re-read it from the "
                f"shared structure after the wait returns")
            if v is not None:
                out.append(v)
        return out


# ---------------------------------------------------------------------


class LockOrderRule(ConcurrencyRule):
    name = "lock-order"
    code = 4
    doc = ("cycles in the nested lock-acquisition graph (potential "
           "deadlock), incl. re-acquiring a non-reentrant Lock and "
           "nesting through one level of intra-class calls")

    def check_module(self, project, mm):
        return []  # all work happens in finish() on the global graph

    def finish(self, project):
        out: List[core.Violation] = []
        # function -> set of lock keys it (transitively) acquires
        acquires: Dict[int, Set[tm.LockKey]] = {}
        calls: Dict[int, List[Tuple[tm.FuncInfo, object]]] = {}
        funcs: List[Tuple[tm.ModuleModel, tm.FuncInfo]] = []
        for mm in project.modules:
            for fnode, fi in mm.funcs.items():
                funcs.append((mm, fi))
                acq: Set[tm.LockKey] = set()
                for n in _own_nodes(fnode):
                    if isinstance(n, (ast.With, ast.AsyncWith)):
                        for item in n.items:
                            got = tm.resolve_lock_expr(
                                project, mm, fi, item.context_expr)
                            if got is not None:
                                acq.add(got[0])
                acquires[id(fi)] = acq
                callees = []
                for n in _own_nodes(fnode):
                    if isinstance(n, ast.Call):
                        callee = self._resolve_callee(project, mm, fi, n)
                        if callee is not None:
                            callees.append((callee, n))
                calls[id(fi)] = callees
        closure: Dict[int, Set[tm.LockKey]] = {}

        def close(fi, depth=0):
            if id(fi) in closure:
                return closure[id(fi)]
            acq = set(acquires.get(id(fi), set()))
            closure[id(fi)] = acq  # cycle guard
            if depth < 3:
                for callee, _site in calls.get(id(fi), []):
                    acq |= close(callee, depth + 1)
            closure[id(fi)] = acq
            return acq

        edges: Dict[Tuple[tm.LockKey, tm.LockKey],
                    Tuple[tm.ModuleModel, int, str]] = {}
        kinds: Dict[tm.LockKey, str] = {}
        for mm, fi in funcs:
            for n in _own_nodes(fi.node):
                if isinstance(n, (ast.With, ast.AsyncWith)):
                    held = dict(tm.locks_held(project, mm, n))
                    prior: List[tm.LockKey] = []
                    for item in n.items:
                        got = tm.resolve_lock_expr(project, mm, fi,
                                                   item.context_expr)
                        if got is None:
                            continue
                        key, kind = got
                        kinds.setdefault(key, kind)
                        for h in list(held) + prior:
                            if h != key:
                                edges.setdefault(
                                    (h, key), (mm, n.lineno,
                                               f"{tm.render_key(key)} "
                                               f"acquired while holding "
                                               f"{tm.render_key(h)}"))
                            elif kinds.get(h) in ("lock", "semaphore"):
                                v = self.violation(
                                    mm.mod, n.lineno,
                                    f"re-acquisition of non-reentrant "
                                    f"{tm.render_key(key)} while "
                                    f"already holding it — instant "
                                    f"self-deadlock (use RLock or "
                                    f"restructure)")
                                if v is not None:
                                    out.append(v)
                        prior.append(key)
                elif isinstance(n, ast.Call):
                    held = tm.locks_held(project, mm, n)
                    if not held:
                        continue
                    callee = self._resolve_callee(project, mm, fi, n)
                    if callee is None:
                        continue
                    for key in close(callee):
                        kinds.setdefault(key, "lock")
                        for h in held:
                            if h != key:
                                edges.setdefault(
                                    (h, key),
                                    (mm, n.lineno,
                                     f"{callee.qualname}() acquires "
                                     f"{tm.render_key(key)} while the "
                                     f"caller holds "
                                     f"{tm.render_key(h)}"))
        out.extend(self._report_cycles(edges))
        return out

    def _resolve_callee(self, project, mm, fi, call):
        f = call.func
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "self" and fi.cls is not None:
            flat = project.flattened(fi.cls)
            return flat.methods.get(f.attr)
        if isinstance(f, ast.Name):
            for fnode, other in mm.funcs.items():
                if other.name == f.id and other.cls is None \
                        and tm._enclosing_funcinfo(mm, fnode) is None:
                    return other
        return None

    def _report_cycles(self, edges):
        out = []
        adj: Dict[tm.LockKey, Set[tm.LockKey]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())
        # find one representative cycle per strongly-connected pair
        reported = set()
        for (a, b), (mm, lineno, detail) in sorted(
                edges.items(),
                key=lambda kv: (str(kv[1][0].mod.path), kv[1][1])):
            if a == b:
                continue
            # is there a path b -> a?
            stack, seen = [b], set()
            found = False
            while stack:
                cur = stack.pop()
                if cur == a:
                    found = True
                    break
                if cur in seen:
                    continue
                seen.add(cur)
                stack.extend(adj.get(cur, ()))
            if not found:
                continue
            pair = frozenset((a, b))
            if pair in reported:
                continue
            reported.add(pair)
            back = edges.get((b, a))
            back_txt = (f"; reverse order at "
                        f"{back[0].mod.path}:{back[1]}" if back else
                        f" (reverse path exists through intermediate "
                        f"locks)")
            v = self.violation(
                mm.mod, lineno,
                f"potential deadlock: lock-order cycle between "
                f"{tm.render_key(a)} and {tm.render_key(b)} — {detail}"
                f"{back_txt}; pick one global order and stick to it")
            if v is not None:
                out.append(v)
        return out


# ---------------------------------------------------------------------

#: dotted call targets that block on IO / child processes / time.
_BLOCKING_DOTTED = {
    "time.sleep", "os.fsync", "os.replace", "os.system",
    "numpy.save", "numpy.savez", "numpy.load",
    "shutil.move", "shutil.rmtree", "shutil.copy", "shutil.copyfile",
    "json.dump", "json.load", "pandas.read_parquet",
}
_BLOCKING_TERMINALS = {
    "read_text", "write_text", "read_bytes", "write_bytes",
    "to_parquet",
}


class BlockingUnderLockRule(ConcurrencyRule):
    name = "blocking-under-lock"
    code = 8
    doc = ("blocking queue.put/get, thread joins, .result(), file IO, "
           "sleeps, or waits on a DIFFERENT condition while holding a "
           "lock — every other lock user stalls behind the block")

    def check_module(self, project, mm):
        out: List[core.Violation] = []
        for fnode, fi in mm.funcs.items():
            flat = (project.flattened(fi.cls) if fi.cls is not None
                    else None)
            for n in _own_nodes(fnode):
                if not isinstance(n, ast.Call):
                    continue
                held = tm.locks_held(project, mm, n)
                if not held:
                    continue
                msg = self._classify(project, mm, fi, flat, n, held)
                if msg is None:
                    continue
                held_txt = ", ".join(sorted(
                    tm.render_key(k) for k in held))
                v = self.violation(
                    mm.mod, n.lineno,
                    f"{msg} while holding {held_txt} — every other "
                    f"user of the lock stalls behind it; move the "
                    f"blocking call outside the critical section or "
                    f"suppress with the reason the coupling is "
                    f"deliberate")
                if v is not None:
                    out.append(v)
        return out

    def _classify(self, project, mm, fi, flat, call, held):
        f = call.func
        term = dataflow.terminal_name(f)
        dotted = dataflow.dotted_name(f, mm.aliases) or ""
        if isinstance(f, ast.Name) and f.id == "open":
            return "file open()"
        if dotted in _BLOCKING_DOTTED:
            return f"blocking call {dotted}()"
        if dotted.startswith("subprocess."):
            return f"child-process call {dotted}()"
        if term in _BLOCKING_TERMINALS:
            return f"file IO .{term}()"
        if term in ("put", "get") and isinstance(f, ast.Attribute):
            if self._is_queue_recv(project, mm, fi, flat, f.value):
                for kw in call.keywords:
                    if kw.arg == "block" \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is False:
                        return None
                timed = any(kw.arg == "timeout" for kw in call.keywords) \
                    or len(call.args) >= 2
                how = ("bounded-stall (timed)" if timed
                       else "potentially-unbounded")
                return f"{how} blocking queue .{term}()"
        if term == "join" and isinstance(f, ast.Attribute):
            if self._is_thread_recv(project, mm, fi, flat, f.value):
                return "thread .join()"
        if term == "result":
            return "ticket/future .result()"
        if term in ("wait", "wait_for"):
            recv = _wait_recv(project, mm, fi, call)
            if recv is not None:
                kind, key, pretty = recv
                if kind == "condition":
                    if key in held:
                        return None  # waiting on the held cv releases it
                    if flat is not None and key[0] == "cls":
                        wraps = flat.cond_wraps.get(key[2])
                        if wraps is not None and any(
                                h[0] == "cls" and h[2] == wraps
                                for h in held):
                            return None
                    return (f"wait on condition {pretty} which is NOT "
                            f"the held lock")
                return f"wait on event {pretty}"
        return None

    def _is_queue_recv(self, project, mm, fi, flat, recv):
        chain = tm.attr_chain(recv)
        if not chain:
            return False
        if chain[0] == "self" and flat is not None and len(chain) == 2:
            return chain[1] in flat.queue_attrs
        if len(chain) == 1:
            cur = fi
            while cur is not None:
                if chain[0] in cur.local_queues:
                    return True
                cur = tm._enclosing_funcinfo(mm, cur.node)
        return False

    def _is_thread_recv(self, project, mm, fi, flat, recv):
        chain = tm.attr_chain(recv)
        if not chain:
            return False
        if chain[0] == "self" and flat is not None and len(chain) == 2:
            return chain[1] in flat.thread_attrs
        if len(chain) == 1:
            cur = fi
            while cur is not None:
                if chain[0] in cur.local_threads:
                    return True
                cur = tm._enclosing_funcinfo(mm, cur.node)
        return False


# ---------------------------------------------------------------------


class TicketResolutionRule(ConcurrencyRule):
    name = "ticket-resolution"
    code = 16
    doc = ("every exception edge of a '# owns-tickets:'-registered "
           "worker must resolve/fail its tickets or re-raise (the "
           "forever-blocked-ticket class); registration is checked "
           "both ways against the thread-entry graph")

    #: resolver-shaped terminals that flag an UNregistered thread entry.
    COMMON_RESOLVERS = {"set_result", "set_exception"}

    def check_module(self, project, mm):
        out: List[core.Violation] = []
        project_resolvers = set(self.COMMON_RESOLVERS)
        for m2 in project.modules:
            for fi in m2.funcs.values():
                if fi.owns_tickets:
                    project_resolvers |= set(fi.owns_tickets)

        for fnode, fi in mm.funcs.items():
            if fi.owns_tickets:
                out.extend(self._check_registered(project, mm, fi))
        # both ways: thread entries that resolve tickets unregistered
        for entry in mm.entries:
            fi = entry.target
            if fi is None or fi.owns_tickets:
                continue
            if fi.name in project_resolvers:
                continue
            hits = sorted({
                dataflow.terminal_name(n.func)
                for n in _own_nodes(fi.node)
                if isinstance(n, ast.Call)
                and dataflow.terminal_name(n.func) in project_resolvers})
            if hits:
                v = self.violation(
                    mm.mod, fi.node.lineno,
                    f"thread entry {fi.qualname}() calls ticket "
                    f"resolver(s) {', '.join(hits)} but has no "
                    f"'# owns-tickets:' registration — register it so "
                    f"its exception edges are checked")
                if v is not None:
                    out.append(v)
        return out

    def _check_registered(self, project, mm, fi):
        out: List[core.Violation] = []
        resolvers = set(fi.owns_tickets or ())
        flat = (project.flattened(fi.cls) if fi.cls is not None
                else None)
        # (c) declared resolvers must exist
        for r in sorted(resolvers - self.COMMON_RESOLVERS):
            exists = (flat is not None and r in flat.methods) or any(
                other.name == r for other in mm.funcs.values())
            if not exists and not self._method_anywhere(project, r):
                v = self.violation(
                    mm.mod, fi.node.lineno,
                    f"'# owns-tickets: {r}' on {fi.qualname}() names "
                    f"no known function/method — fix the resolver "
                    f"name or delete it from the registration")
                if v is not None:
                    out.append(v)
        # (b) stale registration: no resolver reachable at all
        terminals = self._call_terminals(project, flat, fi, depth=2)
        if not (terminals & resolvers):
            v = self.violation(
                mm.mod, fi.node.lineno,
                f"stale '# owns-tickets' on {fi.qualname}(): none of "
                f"its declared resolvers ({', '.join(sorted(resolvers))})"
                f" are called by it (directly or through its own "
                f"methods) — delete the registration or fix the worker")
            if v is not None:
                out.append(v)
            return out
        # (a) every except edge resolves or re-raises
        for n in _own_nodes(fi.node):
            if not isinstance(n, ast.ExceptHandler):
                continue
            if self._handler_resolves(project, flat, resolvers, n):
                continue
            v = self.violation(
                mm.mod, n.lineno,
                f"exception edge of ticket-owning worker "
                f"{fi.qualname}() neither calls a declared resolver "
                f"({', '.join(sorted(resolvers))}) nor re-raises — "
                f"submitted tickets block forever (the close-hang "
                f"class); resolve/fail them or re-raise")
            if v is not None:
                out.append(v)
        return out

    def _handler_resolves(self, project, flat, resolvers, handler):
        for n in ast.walk(handler):
            if isinstance(n, ast.Raise):
                return True
            if isinstance(n, ast.Call):
                term = dataflow.terminal_name(n.func)
                if term in resolvers:
                    return True
                if flat is not None \
                        and isinstance(n.func, ast.Attribute) \
                        and isinstance(n.func.value, ast.Name) \
                        and n.func.value.id == "self" \
                        and term in flat.methods:
                    callee = flat.methods[term]
                    for sub in ast.walk(callee.node):
                        if isinstance(sub, ast.Call) \
                                and dataflow.terminal_name(sub.func) \
                                in resolvers:
                            return True
        return False

    def _call_terminals(self, project, flat, fi, depth):
        seen: Set[str] = set()
        frontier = [fi]
        visited = set()
        for _ in range(depth + 1):
            nxt = []
            for cur in frontier:
                if id(cur) in visited:
                    continue
                visited.add(id(cur))
                for n in ast.walk(cur.node):
                    if isinstance(n, ast.Call):
                        term = dataflow.terminal_name(n.func)
                        if term:
                            seen.add(term)
                        if flat is not None \
                                and isinstance(n.func, ast.Attribute) \
                                and isinstance(n.func.value, ast.Name) \
                                and n.func.value.id == "self" \
                                and term in flat.methods:
                            nxt.append(flat.methods[term])
            frontier = nxt
        return seen

    def _method_anywhere(self, project, name):
        for cls in project.class_index.values():
            if name in cls.methods:
                return True
        return False


CONCURRENCY_RULES = [
    GuardedAttrRule(),
    WaitLoopRule(),
    LockOrderRule(),
    BlockingUnderLockRule(),
    TicketResolutionRule(),
]
