"""Thread-context and lock-site model shared by the concurrency rules.

The tier's rules all need the same three structures, built once per
invocation over the swept files (mirroring how ``tools/analysis/core``
shares one parse per file):

* a **lock-site map** — every name bound to a
  ``threading.Lock/RLock/Condition/Semaphore`` (through
  ``dataflow.build_aliases``), whether an instance attribute
  (``self._lock = threading.Lock()``), a module global, or a
  function local (the ``sweep_slabs`` closure pattern); queue, event
  and thread sites ride along because several rules must tell a
  synchronization object apart from plain shared state;
* a **thread-entry graph** — every ``threading.Thread(target=...)``
  site, resolved to the method / nested function it runs, with a
  *multi-instance* flag when the Thread is constructed inside a
  loop or comprehension (N workers sharing one target are N
  contexts, not one);
* per-class (and per-closure) **context sets** — which thread
  context(s) can execute each method, propagated through the
  intra-class ``self.m()`` call graph (including same-file base
  classes, so ``CohortExecutor`` inherits ``MicroBatchExecutor``'s
  supervisor threads).

Annotations the model understands (checked both ways by the rules —
a stale annotation is itself a finding, like the env-knob registry):

* ``# guarded-by: <lock>`` on an attribute/global binding line —
  declares the lock that must be held for **every** access;
* ``# guarded-by: <lock>`` on a ``def`` line — "callers hold this
  lock": the body is analyzed as holding it (the ``_hit_locked`` /
  ``_dispatch_locked`` helper convention);
* ``# thread-shared`` on a ``class`` line — instances are used from
  multiple threads even though the class spawns none of its own
  (``PlanCache``, ``CircuitBreaker``): the caller context counts
  as concurrent;
* ``# owns-tickets: <resolver[, resolver...]>`` on a ``def`` line —
  registers a ticket-owning worker and names the methods that
  resolve/fail its tickets (the ``ticket-resolution`` rule).

Everything is flow-insensitive and intentionally conservative in the
same direction as ``dataflow``: an unresolvable receiver widens to
"unknown" and the rules stay silent rather than guessing.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from tools.analysis import core
from tools.analysis import dataflow

#: factory dotted-name -> lock kind (Condition doubles as a lock;
#: Event is NOT a lock — level-triggered, no ownership).
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Condition": "condition",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
}
QUEUE_FACTORIES = {
    "queue.Queue", "queue.LifoQueue", "queue.PriorityQueue",
    "queue.SimpleQueue",
}
EVENT_FACTORY = "threading.Event"
THREAD_FACTORY = "threading.Thread"

#: method calls that mutate their receiver — a ``self.x.append(v)``
#: is a write to the shared structure ``x`` for context counting.
MUTATORS = {
    "append", "appendleft", "extend", "insert", "add", "discard",
    "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
}

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")
_THREAD_SHARED_RE = re.compile(r"#\s*thread-shared\b")
_OWNS_TICKETS_RE = re.compile(
    r"#\s*owns-tickets:\s*([A-Za-z_][A-Za-z0-9_]*"
    r"(?:\s*,\s*[A-Za-z_][A-Za-z0-9_]*)*)")

CALLER = "caller"

FuncNode = dataflow.FuncNode

#: canonical lock key: ("cls", defining class name, attr) |
#: ("mod", module key, name) | ("fn", function key, name) |
#: ("foreign", scope, dotted).  The DEFINING class names inherited
#: locks so base and subclass references unify.
LockKey = Tuple[str, str, str]


def render_key(key: LockKey) -> str:
    scope, owner, name = key
    if scope == "cls":
        return f"{owner}.{name}"
    if scope == "foreign":
        return name
    owner = owner.rsplit(":", 1)[-1]
    return f"{owner}.{name}" if owner else name


@dataclass
class FuncInfo:
    node: FuncNode
    name: str
    qualname: str
    cls: Optional["ClassInfo"]
    #: raw lockspec strings from a def-line ``# guarded-by:``.
    guarded_by: List[str] = field(default_factory=list)
    #: resolver names from ``# owns-tickets:``, or None.
    owns_tickets: Optional[List[str]] = None
    #: function-local lock/queue/event sites: name -> kind.
    local_locks: Dict[str, str] = field(default_factory=dict)
    local_queues: Set[str] = field(default_factory=set)
    local_events: Set[str] = field(default_factory=set)
    #: names bound to a Thread (or iterated from a thread list).
    local_threads: Set[str] = field(default_factory=set)


@dataclass
class ClassInfo:
    node: ast.ClassDef
    name: str
    bases: List[str]
    methods: Dict[str, FuncInfo] = field(default_factory=dict)
    thread_shared: bool = False
    lock_attrs: Dict[str, str] = field(default_factory=dict)
    queue_attrs: Set[str] = field(default_factory=set)
    event_attrs: Set[str] = field(default_factory=set)
    thread_attrs: Set[str] = field(default_factory=set)
    #: attr -> (lockspec, decl lineno) from annotated binding lines.
    guarded_attrs: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    #: attr -> project class name its value was constructed from
    #: (``self.breaker = CircuitBreaker(...)``) — lets the rules
    #: resolve cross-object locks like ``self.breaker._lock``.
    attr_class: Dict[str, str] = field(default_factory=dict)
    #: (method name, multi-instance) thread entries targeting self.m.
    thread_targets: List[Tuple[str, bool]] = field(default_factory=list)
    #: condition attr -> lock attr it wraps (Condition(self._lock)).
    cond_wraps: Dict[str, str] = field(default_factory=dict)


@dataclass
class ThreadEntry:
    """One ``threading.Thread(target=...)`` site, resolved."""
    lineno: int
    multi: bool
    target: Optional[FuncInfo]


@dataclass
class ModuleModel:
    mod: core.ModuleSource
    key: str  # stable short module key for lock-site names
    aliases: Dict[str, str] = field(default_factory=dict)
    parents: Dict[ast.AST, ast.AST] = field(default_factory=dict)
    classes: Dict[str, ClassInfo] = field(default_factory=dict)
    module_locks: Dict[str, str] = field(default_factory=dict)
    #: global name -> (lockspec, decl lineno).
    module_guarded: Dict[str, Tuple[str, int]] = field(default_factory=dict)
    funcs: Dict[FuncNode, FuncInfo] = field(default_factory=dict)
    entries: List[ThreadEntry] = field(default_factory=list)


class ProjectModel:
    """The whole-sweep model: per-module models plus the cross-module
    indices (class registry, known lock attribute names)."""

    def __init__(self, files: Sequence[core.ModuleSource]):
        self.modules: List[ModuleModel] = []
        self.class_index: Dict[str, ClassInfo] = {}
        self.lock_attr_names: Set[str] = set()
        self._flat_cache: Dict[int, "FlatClass"] = {}
        for mod in files:
            if mod.parse_error is not None or mod.tree is None:
                continue
            mm = _build_module(mod)
            self.modules.append(mm)
            for cname, cls in mm.classes.items():
                # last definition wins on a (rare) name collision —
                # good enough for message rendering and lock keys
                self.class_index[cname] = cls
                self.lock_attr_names |= set(cls.lock_attrs)
            self.lock_attr_names |= set(mm.module_locks)

    # -- flattened class views (same-project single-inheritance) ------

    def flattened(self, cls: ClassInfo) -> "FlatClass":
        got = self._flat_cache.get(id(cls))
        if got is None:
            got = FlatClass(cls, self)
            self._flat_cache[id(cls)] = got
        return got


class FlatClass:
    """A class with its project-resolvable base chain folded in:
    method table (overrides win), lock/queue/event/thread sites,
    guarded-attr declarations, and thread entries, each attributed to
    the DEFINING class so lock keys unify across the hierarchy."""

    def __init__(self, cls: ClassInfo, project: ProjectModel):
        self.cls = cls
        self.name = cls.name
        chain: List[ClassInfo] = []
        seen = set()
        todo = [cls]
        while todo:
            c = todo.pop(0)
            if c.name in seen:
                continue
            seen.add(c.name)
            chain.append(c)
            for b in c.bases:
                base = project.class_index.get(b)
                if base is not None:
                    todo.append(base)
        self.chain = chain  # derived first
        self.thread_shared = any(c.thread_shared for c in chain)
        self.methods: Dict[str, FuncInfo] = {}
        self.lock_attrs: Dict[str, Tuple[str, str]] = {}  # attr->(owner,kind)
        self.queue_attrs: Set[str] = set()
        self.event_attrs: Set[str] = set()
        self.thread_attrs: Set[str] = set()
        self.guarded_attrs: Dict[str, Tuple[str, int, ClassInfo]] = {}
        self.attr_class: Dict[str, str] = {}
        self.cond_wraps: Dict[str, str] = {}
        self.thread_targets: List[Tuple[str, bool]] = []
        for c in chain:  # derived first: first writer wins = override
            for mname, fi in c.methods.items():
                self.methods.setdefault(mname, fi)
            for attr, kind in c.lock_attrs.items():
                self.lock_attrs.setdefault(attr, (c.name, kind))
            self.queue_attrs |= c.queue_attrs
            self.event_attrs |= c.event_attrs
            self.thread_attrs |= c.thread_attrs
            for attr, (spec, ln) in c.guarded_attrs.items():
                self.guarded_attrs.setdefault(attr, (spec, ln, c))
            for attr, k in c.attr_class.items():
                self.attr_class.setdefault(attr, k)
            for cond, lk in c.cond_wraps.items():
                self.cond_wraps.setdefault(cond, lk)
            self.thread_targets.extend(c.thread_targets)
        self.sync_attrs = (set(self.lock_attrs) | self.queue_attrs
                          | self.event_attrs | self.thread_attrs)
        self._contexts: Optional[Dict[str, Set[str]]] = None
        self._multi: Dict[str, bool] = {}

    def lock_key(self, attr: str) -> Optional[Tuple[LockKey, str]]:
        got = self.lock_attrs.get(attr)
        if got is None:
            return None
        owner, kind = got
        return ("cls", owner, attr), kind

    # -- thread-context propagation -----------------------------------

    def contexts(self) -> Dict[str, Set[str]]:
        """method name -> set of context labels ('caller' or
        'thread:<entry>'), via fixpoint over the intra-class call
        graph.  ``multi_label(label)`` says whether a label stands
        for more than one concurrent thread."""
        if self._contexts is not None:
            return self._contexts
        ctxs: Dict[str, Set[str]] = {m: set() for m in self.methods}
        entry_names = set()
        for mname, multi in self.thread_targets:
            if mname in ctxs:
                label = f"thread:{mname}"
                ctxs[mname].add(label)
                entry_names.add(mname)
                self._multi[label] = self._multi.get(label, False) or multi
        edges: Dict[str, Set[str]] = {m: set() for m in self.methods}
        callers: Dict[str, Set[str]] = {m: set() for m in self.methods}
        for mname, fi in self.methods.items():
            for node in ast.walk(fi.node):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == "self"
                        and node.func.attr in self.methods):
                    edges[mname].add(node.func.attr)
                    callers[node.func.attr].add(mname)
        for mname in self.methods:
            is_public = not mname.startswith("_") or (
                mname.startswith("__") and mname.endswith("__"))
            if mname in entry_names:
                continue
            if is_public or not callers[mname]:
                # externally callable (or dead-from-inside): runs on
                # whatever thread the caller is — the caller context
                ctxs[mname].add(CALLER)
        changed = True
        while changed:
            changed = False
            for mname, callees in edges.items():
                for callee in callees:
                    if not ctxs[mname] <= ctxs[callee]:
                        ctxs[callee] |= ctxs[mname]
                        changed = True
        self._contexts = ctxs
        return ctxs

    def multi_label(self, label: str) -> bool:
        return self._multi.get(label, False)

    def context_weight(self, labels: Set[str]) -> int:
        """How many concurrent executors the label set stands for —
        >= 2 means unsynchronized writes can race."""
        w = 0
        for label in labels:
            if label == CALLER:
                w += 2 if self.thread_shared else 1
            else:
                w += 2 if self.multi_label(label) else 1
        return w


# ---------------------------------------------------------------------
# module construction


def _def_comment_lines(mod: core.ModuleSource, node: FuncNode) -> str:
    """The comment-bearing text of a (possibly multi-line) def
    signature: from the ``def`` line to the line before the body."""
    start = node.lineno
    stop = node.body[0].lineno if node.body else node.lineno + 1
    return "\n".join(mod.line(i) for i in range(start, stop))


def _contains_thread_call(node: ast.expr, aliases: Dict[str, str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            if dataflow.dotted_name(sub.func, aliases) == THREAD_FACTORY:
                return True
    return False


def _build_module(mod: core.ModuleSource) -> ModuleModel:
    tree = mod.tree
    assert tree is not None
    parts = mod.path.parts
    key = "/".join(parts[-2:]) if len(parts) >= 2 else mod.path.name
    mm = ModuleModel(mod=mod, key=key,
                     aliases=dataflow.build_aliases(tree))
    for parent in ast.walk(tree):
        for child in ast.iter_child_nodes(parent):
            mm.parents[child] = parent

    # classes + funcs skeleton
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            cls = ClassInfo(
                node=node, name=node.name,
                bases=[b.id for b in node.bases
                       if isinstance(b, ast.Name)],
                thread_shared=bool(
                    _THREAD_SHARED_RE.search(mod.line(node.lineno))))
            mm.classes[node.name] = cls
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        cls = _enclosing_class(mm, node)
        fi = FuncInfo(node=node, name=node.name,
                      qualname=_qualname(mm, node), cls=cls)
        sig = _def_comment_lines(mod, node)
        for m in _GUARDED_BY_RE.finditer(sig):
            fi.guarded_by.append(m.group(1))
        m = _OWNS_TICKETS_RE.search(sig)
        if m:
            fi.owns_tickets = [s.strip() for s in m.group(1).split(",")]
        mm.funcs[node] = fi
        if cls is not None and mm.parents.get(node) is cls.node:
            cls.methods[node.name] = fi

    # sites: locks / queues / events / threads, per scope
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if value is None:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        dotted = (dataflow.dotted_name(value.func, mm.aliases)
                  if isinstance(value, ast.Call) else None)
        kind = LOCK_FACTORIES.get(dotted or "")
        is_queue = dotted in QUEUE_FACTORIES
        is_event = dotted == EVENT_FACTORY
        is_thread = _contains_thread_call(value, mm.aliases)
        ctor_cls = None
        if isinstance(value, ast.Call):
            tail = (dotted or "").rsplit(".", 1)[-1]
            if tail in mm.classes or tail and tail[:1].isupper():
                ctor_cls = tail
        owner_fi = _enclosing_funcinfo(mm, node)
        line = mod.line(node.lineno)
        gm = _GUARDED_BY_RE.search(line)
        for tgt in targets:
            if (isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"):
                cls = owner_fi.cls if owner_fi else None
                if cls is None:
                    continue
                if kind:
                    cls.lock_attrs[tgt.attr] = kind
                    if (kind == "condition"
                            and isinstance(value, ast.Call)
                            and value.args
                            and isinstance(value.args[0], ast.Attribute)
                            and isinstance(value.args[0].value, ast.Name)
                            and value.args[0].value.id == "self"):
                        cls.cond_wraps[tgt.attr] = value.args[0].attr
                elif is_queue:
                    cls.queue_attrs.add(tgt.attr)
                elif is_event:
                    cls.event_attrs.add(tgt.attr)
                elif is_thread:
                    cls.thread_attrs.add(tgt.attr)
                elif ctor_cls:
                    cls.attr_class[tgt.attr] = ctor_cls
                if gm and not kind:
                    cls.guarded_attrs[tgt.attr] = (gm.group(1), node.lineno)
            elif isinstance(tgt, ast.Name):
                if owner_fi is None:  # module level
                    if kind:
                        mm.module_locks[tgt.id] = kind
                    elif gm:
                        mm.module_guarded[tgt.id] = (gm.group(1),
                                                     node.lineno)
                else:
                    if kind:
                        owner_fi.local_locks[tgt.id] = kind
                    elif is_queue:
                        owner_fi.local_queues.add(tgt.id)
                    elif is_event:
                        owner_fi.local_events.add(tgt.id)
                    elif is_thread:
                        owner_fi.local_threads.add(tgt.id)

    # names iterated from a thread-list attribute count as threads
    for node in ast.walk(tree):
        if (isinstance(node, ast.For)
                and isinstance(node.target, ast.Name)
                and isinstance(node.iter, ast.Attribute)
                and isinstance(node.iter.value, ast.Name)
                and node.iter.value.id == "self"):
            fi = _enclosing_funcinfo(mm, node)
            if fi is not None and fi.cls is not None \
                    and node.iter.attr in fi.cls.thread_attrs:
                fi.local_threads.add(node.target.id)

    # thread entries
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and dataflow.dotted_name(node.func, mm.aliases)
                == THREAD_FACTORY):
            continue
        target_expr = None
        for kw in node.keywords:
            if kw.arg == "target":
                target_expr = kw.value
        multi = _in_multi_context(mm, node)
        target_fi = _resolve_target(mm, node, target_expr)
        mm.entries.append(ThreadEntry(lineno=node.lineno, multi=multi,
                                      target=target_fi))
        if (target_fi is not None and target_fi.cls is not None
                and isinstance(target_expr, ast.Attribute)):
            target_fi.cls.thread_targets.append((target_fi.name, multi))
    return mm


def _qualname(mm: ModuleModel, node: FuncNode) -> str:
    parts = [node.name]
    cur = mm.parents.get(node)
    while cur is not None and not isinstance(cur, ast.Module):
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(cur.name)
        cur = mm.parents.get(cur)
    return ".".join(reversed(parts))


def _enclosing_class(mm: ModuleModel, node: ast.AST) -> Optional[ClassInfo]:
    cur = mm.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.ClassDef):
            return mm.classes.get(cur.name)
        cur = mm.parents.get(cur)
    return None


def _enclosing_funcinfo(mm: ModuleModel,
                        node: ast.AST) -> Optional[FuncInfo]:
    cur = mm.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return mm.funcs.get(cur)
        cur = mm.parents.get(cur)
    return None


def _in_multi_context(mm: ModuleModel, node: ast.AST) -> bool:
    """True when the Thread(...) is constructed inside a loop or
    comprehension — N instances of one target are N contexts."""
    cur = mm.parents.get(node)
    while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Module)):
        if isinstance(cur, (ast.For, ast.While, ast.ListComp,
                            ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            return True
        cur = mm.parents.get(cur)
    return False


def _resolve_target(mm: ModuleModel, site: ast.AST,
                    expr: Optional[ast.expr]) -> Optional[FuncInfo]:
    if expr is None:
        return None
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"):
        cls = None
        fi = _enclosing_funcinfo(mm, site)
        if fi is not None:
            cls = fi.cls
        if cls is not None:
            return cls.methods.get(expr.attr)
        return None
    if isinstance(expr, ast.Name):
        # nearest enclosing function with a nested def of that name,
        # else a module-level function
        cur = mm.parents.get(site)
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Module)):
                for child in ast.iter_child_nodes(cur):
                    if isinstance(child, (ast.FunctionDef,
                                          ast.AsyncFunctionDef)) \
                            and child.name == expr.id:
                        return mm.funcs.get(child)
                if isinstance(cur, ast.Module):
                    break
            cur = mm.parents.get(cur)
    return None


# ---------------------------------------------------------------------
# lock resolution / locks-held


def attr_chain(node: ast.expr) -> Optional[List[str]]:
    """``self.breaker._lock`` -> ['self', 'breaker', '_lock'];
    None for non-name roots."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return list(reversed(parts))


def resolve_lock_expr(project: ProjectModel, mm: ModuleModel,
                      fi: Optional[FuncInfo],
                      expr: ast.expr) -> Optional[Tuple[LockKey, str]]:
    """Resolve a ``with``-style expression to a canonical lock key, or
    None when it is not a known lock.  Unknown receivers widen to a
    'foreign' key only when the terminal attribute is a known lock
    attribute name somewhere in the sweep."""
    chain = attr_chain(expr)
    if not chain:
        return None
    cls = fi.cls if fi is not None else None
    if chain[0] == "self" and cls is not None:
        flat = project.flattened(cls)
        rest = chain[1:]
        if len(rest) == 1:
            got = flat.lock_key(rest[0])
            if got is not None:
                return got
        if len(rest) >= 2 and rest[0] in flat.attr_class:
            other = project.class_index.get(flat.attr_class[rest[0]])
            if other is not None:
                oflat = project.flattened(other)
                got = oflat.lock_key(rest[1])
                if got is not None and len(rest) == 2:
                    return got
        if rest[-1] in project.lock_attr_names:
            return (("foreign", cls.name, ".".join(chain)), "foreign")
        return None
    if len(chain) == 1:
        name = chain[0]
        cur = fi
        while cur is not None:
            if name in cur.local_locks:
                return (("fn", f"{mm.key}:{cur.qualname}", name),
                        cur.local_locks[name])
            cur = _enclosing_funcinfo(mm, cur.node)
        if name in mm.module_locks:
            return (("mod", mm.key, name), mm.module_locks[name])
    if chain[-1] in project.lock_attr_names:
        scope = cls.name if cls is not None else mm.key
        return (("foreign", scope, ".".join(chain)), "foreign")
    return None


def resolve_lock_spec(project: ProjectModel, mm: ModuleModel,
                      fi: Optional[FuncInfo],
                      spec: str) -> Optional[Tuple[LockKey, str]]:
    """Resolve an annotation string ('self._lock', '_lock', 'lk')."""
    try:
        expr = ast.parse(spec, mode="eval").body
    except SyntaxError:
        return None
    return resolve_lock_expr(project, mm, fi, expr)


def locks_held(project: ProjectModel, mm: ModuleModel,
               node: ast.AST) -> Dict[LockKey, str]:
    """Lock keys lexically held at ``node``: enclosing ``with``
    statements up to the function boundary, plus the enclosing
    function's def-line ``# guarded-by`` annotations (callers hold
    those by contract)."""
    held: Dict[LockKey, str] = {}
    fi = _enclosing_funcinfo(mm, node)
    cur = mm.parents.get(node)
    prev = node
    while cur is not None:
        if isinstance(cur, (ast.With, ast.AsyncWith)):
            in_body = any(prev is stmt or _is_descendant(mm, prev, stmt)
                          for stmt in cur.body)
            # only the body holds the lock (not the context expr)
            if in_body or (hasattr(prev, "lineno") and cur.body
                           and prev.lineno >= cur.body[0].lineno):
                for item in cur.items:
                    got = resolve_lock_expr(project, mm, fi,
                                            item.context_expr)
                    if got is not None:
                        held.setdefault(got[0], got[1])
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            break
        prev = cur
        cur = mm.parents.get(cur)
    if fi is not None:
        for spec in fi.guarded_by:
            got = resolve_lock_spec(project, mm, fi, spec)
            if got is not None:
                held.setdefault(got[0], got[1])
    return held


def _is_descendant(mm: ModuleModel, node: ast.AST,
                   ancestor: ast.AST) -> bool:
    cur = node
    while cur is not None:
        if cur is ancestor:
            return True
        cur = mm.parents.get(cur)
    return False


# ---------------------------------------------------------------------
# attribute-access collection (guarded-attr's raw material)


@dataclass
class AttrAccess:
    attr: str
    node: ast.Attribute
    lineno: int
    method: str
    is_write: bool


def collect_self_accesses(flat: FlatClass) -> List[AttrAccess]:
    """Every ``self.X`` access in the flattened class's methods,
    classified read/write (Store/Del, subscript stores, and mutator
    method calls all count as writes).  Synchronization attributes
    (locks, queues, events, thread handles) are excluded — calling
    methods on those IS their contract."""
    out: List[AttrAccess] = []
    for mname, fi in flat.methods.items():
        mm_parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(fi.node):
            for child in ast.iter_child_nodes(parent):
                mm_parents[child] = parent
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"):
                continue
            if node.attr in flat.sync_attrs:
                continue
            is_write = isinstance(node.ctx, (ast.Store, ast.Del))
            cur, parent = node, mm_parents.get(node)
            while not is_write and parent is not None:
                if isinstance(parent, ast.Subscript) \
                        and parent.value is cur:
                    if isinstance(parent.ctx, (ast.Store, ast.Del)):
                        is_write = True
                        break
                    cur, parent = parent, mm_parents.get(parent)
                    continue
                if isinstance(parent, ast.Attribute) \
                        and parent.value is cur \
                        and parent.attr in MUTATORS:
                    grand = mm_parents.get(parent)
                    if isinstance(grand, ast.Call) \
                            and grand.func is parent:
                        is_write = True
                    break
                break
            out.append(AttrAccess(attr=node.attr, node=node,
                                  lineno=node.lineno, method=mname,
                                  is_write=is_write))
    return out


# ---------------------------------------------------------------------
# shared model cache (one build per `core.run` invocation)

_MODEL_CACHE: Dict[Tuple[int, ...], ProjectModel] = {}


def get_model(files: Sequence[core.ModuleSource]) -> ProjectModel:
    key = tuple(id(f) for f in files)
    model = _MODEL_CACHE.get(key)
    if model is None:
        _MODEL_CACHE.clear()  # one sweep at a time; don't leak parses
        model = ProjectModel(files)
        _MODEL_CACHE[key] = model
    return model
