#!/usr/bin/env bash
# The enforced gate, runnable as one command: the kernel-safety static
# analyzer (tools/analyze.py — exit code ORs the fired rule bits, see
# BUILDING.md "Static analysis"), the concurrency-discipline tier over
# the threaded host runtime (BUILDING.md "Concurrency discipline"),
# the compiled-contract tier over the production-program registry
# (BUILDING.md "Compiled contracts"), then the tier-1 test suite
# exactly as ROADMAP.md specifies it.
set -o pipefail
cd "$(dirname "$0")/.."

echo "== static analysis (tools/analyze.py) =="
python tools/analyze.py || exit $?

echo "== concurrency discipline (tools/analyze.py --threads) =="
python tools/analyze.py --threads || exit $?

echo "== compiled contracts (tools/analyze.py --compiled) =="
JAX_PLATFORMS=cpu python tools/analyze.py --compiled || exit $?

echo "== mesh identity (tests/test_mesh_scaling.py) =="
# the planned==eager bitwise contract of the mesh chain across the
# virtual 1->8 device sweep, plus reshard placement and the
# stage-sharding/donation handoffs, surfaced as its own gate
JAX_PLATFORMS=cpu python -m pytest tests/test_mesh_scaling.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== serving identity (tests/test_serve.py) =="
# the streamed==batch bitwise contract, surfaced as its own gate (it
# also runs inside tier-1 below; a fast fail here names the subsystem)
JAX_PLATFORMS=cpu python -m pytest tests/test_serve.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== cohort identity (tests/test_cohort.py) =="
# the fleet engine's cohort==independent-streams bitwise contract,
# late-tick isolation, bucket migration, sharded zero-collectives +
# donation, and cohort snapshot/resume — surfaced before tier-1
JAX_PLATFORMS=cpu python -m pytest tests/test_cohort.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== query service (tests/test_service.py + tests/test_cost.py) =="
# the multi-tenant service's single-flight/admission/fairness contracts
# and the cost model's default-priors==rules + bitwise-flip contracts,
# surfaced as their own gate before tier-1
JAX_PLATFORMS=cpu python -m pytest tests/test_service.py tests/test_cost.py \
    -q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly \
    || exit $?

echo "== chaos gate (fault-injection suite incl. the campaign smoke) =="
# the fault-domain contracts, surfaced as their own gate before
# tier-1: batch-side kill/corrupt/resume (test_chaos.py), the serving
# + service fault domains (test_fault_domain.py — deadlines,
# cancellation, supervised recovery, quarantine, differential
# snapshot chains) and the chaos campaign smoke, plus the cohort
# executor kill/resume case
JAX_PLATFORMS=cpu python -m pytest tests -q -m 'chaos and not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== autotuner gate (smoke sweep + profile lifecycle tests) =="
# the tune harness end-to-end on tiny shapes: child probes, the
# coordinate-descent walk, and the bitwise value-audit gate — the CLI
# exits nonzero if any contract-bitwise knob changed result bits —
# plus the profile lifecycle suite (roundtrip, corrupt/foreign
# refusal by name, env-over-profile priority, profile-in-cache-key)
JAX_PLATFORMS=cpu python -m tempo_tpu.tune --smoke || exit $?
JAX_PLATFORMS=cpu python -m pytest tests/test_tune.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== batch chaos gate (plan barriers + transactional ingest + campaign) =="
# the BATCH-plane fault domain, surfaced before tier-1: plan-integrated
# checkpoint barriers (signed manifests, resume-with-zero-rebuilds,
# foreign-signature refusal), the transactional OOC ingest (per-shard
# progress manifests, row-group quarantine, stage-named deadline,
# flapping-file breaker), and the config-16 campaign smoke
JAX_PLATFORMS=cpu python -m pytest tests/test_plan_checkpoint.py \
    tests/test_ingest_resume.py tests/test_batch_chaos.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly \
    || exit $?

echo "== storage chaos gate (transactional store + cohort spill) =="
# the storage engine's crash-consistency contracts, surfaced before
# tier-1: generation commit/resume with zero committed re-writes,
# refusal-by-name of foreign/torn/corrupt state, live compaction
# kills, the legacy writer's staged-swap survival, the write->ingest
# clustering contract, the tiered cohort-state spill's bitwise
# identity, and the config-17 campaign smoke
JAX_PLATFORMS=cpu python -m pytest tests/test_store.py \
    tests/test_store_chaos.py tests/test_cohort_spill.py -q \
    -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly \
    || exit $?

echo "== overlap smoke (slab pipeline bitwise + stitch/block suites) =="
# the PR 17 dispatch-floor planes, surfaced before tier-1: a tiny
# two-slab pipelined sweep_slabs run must be bit-identical to its
# serial twin (exit nonzero on mismatch), then the overlap, stitching
# and block-dispatch contract suites
JAX_PLATFORMS=cpu python - <<'EOF' || exit $?
import sys
import numpy as np
from tempo_tpu.io import ingest

def load(i):
    rng = np.random.default_rng(40 + i)
    return rng.standard_normal(4096).astype(np.float32)

def compute(i, x):
    return np.cumsum(x, dtype=np.float64)

def drain(i, y):
    return y.tobytes()

serial = ingest.sweep_slabs(2, load, compute, drain, ring=1)
piped = ingest.sweep_slabs(2, load, compute, drain, ring=4)
if piped != serial:
    sys.exit("overlap smoke: pipelined slab sweep diverged bitwise "
             "from the serial twin")
print("overlap smoke: 2-slab pipelined == serial bitwise")
EOF
# no slow filter here: the bars-chain bitwise variants and the
# dispatch-count contract are marked slow for tier-1 wall budget but
# must still run per-commit — this gate is where they live
JAX_PLATFORMS=cpu python -m pytest tests/test_overlap.py \
    tests/test_stitch.py tests/test_block_dispatch.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly \
    || exit $?

echo "== SQL parity gate (compiled SQL == method chain == pandas oracle) =="
# the PR 18 front door, surfaced before tier-1: a fast in-process
# matrix proves the compiled-SQL path (sql_compile lowering through
# the planner) bitwise-equal to the eager pandas evaluator on
# jit-plane AND host-vector predicates plus a full statement, exits
# nonzero on the first divergence, then the full parity suite
JAX_PLATFORMS=cpu TEMPO_TPU_PLAN=1 python - <<'EOF' || exit $?
import sys
import numpy as np
import pandas as pd
from tempo_tpu import TSDF, plan, sql
from tempo_tpu.plan import cache as plan_cache, sql_compile

rng = np.random.default_rng(18)
n = 256
df = pd.DataFrame({
    "ts": np.cumsum(rng.integers(1, 3, size=n)).astype(np.int64),
    "sym": np.repeat(np.arange(4), n // 4),
    "price": np.where(rng.random(n) < 0.1, np.nan,
                      rng.standard_normal(n)),
    "vol": rng.integers(1, 100, size=n),
})
t = TSDF(df, "ts", ["sym"])
preds = [
    "price > 0 AND vol < 50",            # jit-plane
    "price + vol / 10 >= 1 OR price IS NULL",
    "vol BETWEEN 10 AND 60",
    "NOT (price <=> NULL)",
    "vol % 7 = 0",                       # host-vector (% excluded)
]
plan_cache.CACHE.clear()
for pred in preds:
    planned = t.filter(pred).df
    with plan.suspended():
        eager = t.filter(pred).df
    try:
        pd.testing.assert_frame_equal(
            planned.reset_index(drop=True), eager.reset_index(drop=True),
            check_exact=True)
    except AssertionError as e:
        sys.exit(f"SQL parity: planned filter diverged from the "
                 f"eager oracle on {pred!r}: {e}")
planned = t.selectExpr("ts", "sym", "price * 2 as p2",
                       "coalesce(price, 0) as p0").df
with plan.suspended():
    eager = t.selectExpr("ts", "sym", "price * 2 as p2",
                         "coalesce(price, 0) as p0").df
pd.testing.assert_frame_equal(planned.reset_index(drop=True),
                              eager.reset_index(drop=True),
                              check_exact=True)
stmt = "SELECT * FROM trades WHERE price > 0 AND vol < 50"
got = sql_compile.run_statement(stmt, {"trades": t}).df
with plan.suspended():
    want = t.filter("price > 0 AND vol < 50").df
pd.testing.assert_frame_equal(
    got[want.columns].reset_index(drop=True),
    want.reset_index(drop=True), check_exact=True)
print(f"SQL parity smoke: {len(preds)} predicates + projection + "
      f"statement, compiled == eager bitwise")
EOF
JAX_PLATFORMS=cpu python -m pytest tests/test_sql_compile.py \
    tests/test_sql.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== standing gate (continuous queries: standing == batch bitwise) =="
# the round 20 subsystem, surfaced before tier-1: a fast in-process
# smoke registers a standing EMA over a live StreamTable, pushes a
# split timeline, and proves the incremental standing result bitwise
# equal to the batch re-run of the same canonical plan over the
# unified snapshot — with the plan cache's builds counter flat across
# the steady-state pushes — then the full standing + unified-scan
# suites
JAX_PLATFORMS=cpu python - <<'EOF' || exit $?
import sys
import numpy as np
import pandas as pd
from tempo_tpu import profiling
from tempo_tpu.query import StandingQueryEngine, StreamTable
from tempo_tpu.query.standing import _run_batch

rng = np.random.default_rng(20)
def mk(n, t0):
    return pd.DataFrame({
        "event_ts": pd.to_datetime(
            t0 + np.sort(rng.integers(0, 1000, n)), unit="s"),
        "sym": rng.choice(["A", "B"], n),
        "px": np.where(rng.random(n) < 0.1, np.nan,
                       rng.normal(100, 5, n)),
    }).sort_values("event_ts", kind="stable").reset_index(drop=True)

t = StreamTable("ticks", "event_ts", ["sym"], ["px"])
t.append(mk(40, 0))
with StandingQueryEngine() as eng:
    frame = t.frame().EMA("px", exp_factor=0.3, exact=True)
    sub = eng.register(frame)
    eng.push(t, mk(20, 2000))
    eng.flush()
    builds0 = profiling.plan_cache_stats()["builds"]
    for k in range(3):
        eng.push(t, mk(20, 4000 + 2000 * k))
    eng.flush()
    builds1 = profiling.plan_cache_stats()["builds"]
    if builds1 != builds0:
        sys.exit(f"standing steady state recompiled: builds went "
                 f"{builds0} -> {builds1}")
    res = sub.result()
    twin = _run_batch(sub.plan.root, {t.name: t.snapshot_df()})
    if res.df["EMA_px"].to_numpy().tobytes() != \
            twin.df["EMA_px"].to_numpy().tobytes():
        sys.exit("standing EMA diverged from the batch twin")
print(f"standing smoke: {len(res.df)} rows, incremental == batch "
      f"bitwise, builds flat at steady state")
EOF
JAX_PLATFORMS=cpu python -m pytest tests/test_standing.py \
    tests/test_unified_scan.py -q -m 'not slow' \
    -p no:cacheprovider -p no:xdist -p no:randomly || exit $?

echo "== tier-1 tests =="
rm -f /tmp/_t1.log
timeout -k 10 1500 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log \
    | tr -cd . | wc -c)
exit $rc
