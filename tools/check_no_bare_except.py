#!/usr/bin/env python
"""Ban silent failure-swallowing in tempo_tpu/.

Flags two anti-patterns that defeat the resilience layer's failure
*detection* (an exception that vanishes can be neither classified nor
retried nor surfaced — tempo_tpu/resilience.py):

* bare ``except:`` — catches everything including SystemExit /
  KeyboardInterrupt / SimulatedKill; always wrong;
* ``except Exception:`` (or ``BaseException``) whose body is only
  ``pass``/``...`` — a broad catch is fine, silently discarding the
  exception is not: log it or narrow the type.

Wired into the test run via tests/test_tooling.py; also runnable
standalone: ``python tools/check_no_bare_except.py [paths...]``
(default: the tempo_tpu/ package next to this script).  Exit code 1
when violations exist.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, Tuple

Violation = Tuple[Path, int, str]


def _is_silent(handler: ast.ExceptHandler) -> bool:
    """Body is only pass / bare ellipsis — the exception is discarded."""
    return all(
        isinstance(stmt, ast.Pass)
        or (isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Constant)
            and stmt.value.value is Ellipsis)
        for stmt in handler.body
    )


def _catches_broad(node: ast.expr) -> bool:
    """The handler type names Exception or BaseException (possibly
    inside a tuple)."""
    elts = node.elts if isinstance(node, ast.Tuple) else [node]
    for e in elts:
        name = e.id if isinstance(e, ast.Name) else (
            e.attr if isinstance(e, ast.Attribute) else None)
        if name in ("Exception", "BaseException"):
            return True
    return False


def check_file(path: Path) -> List[Violation]:
    violations: List[Violation] = []
    try:
        tree = ast.parse(path.read_text(), filename=str(path))
    except SyntaxError as e:
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            violations.append((
                path, node.lineno,
                "bare 'except:' catches BaseException (incl. "
                "KeyboardInterrupt/SimulatedKill) — name the exception "
                "types",
            ))
        elif _catches_broad(node.type) and _is_silent(node):
            violations.append((
                path, node.lineno,
                "'except Exception: pass' silently swallows failures — "
                "log the exception or narrow the type",
            ))
    return violations


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    roots = [Path(a) for a in args] or [
        Path(__file__).resolve().parent.parent / "tempo_tpu"
    ]
    violations: List[Violation] = []
    for root in roots:
        files = [root] if root.is_file() else sorted(root.rglob("*.py"))
        for f in files:
            violations.extend(check_file(f))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} violation(s)", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
