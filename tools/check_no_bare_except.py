#!/usr/bin/env python
"""Ban silent failure-swallowing — shim over the analysis framework.

The actual rule lives in ``tools/analysis/rules/excepts.py``
(``bare-except``, part of ``python tools/analyze.py``); this wrapper
keeps the historical CLI: ``python tools/check_no_bare_except.py
[paths...]`` (default: ``tempo_tpu/`` plus — since the framework
migration — ``tools/`` and ``tests/helpers.py``), printing
``path:line: message`` per violation and exiting 1 when any exist.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List, Tuple

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.analysis import core  # noqa: E402
from tools.analysis.rules import BareExceptRule  # noqa: E402

Violation = Tuple[Path, int, str]

_RULE = BareExceptRule()


def check_file(path: Path) -> List[Violation]:
    mod = core.ModuleSource(path)
    if mod.parse_error is not None:
        e = mod.parse_error
        return [(path, e.lineno or 0, f"unparseable: {e.msg}")]
    return [(v.path, v.line, v.message) for v in _RULE.check(mod)]


def default_paths() -> List[Path]:
    return [_REPO / "tempo_tpu", _REPO / "tools",
            _REPO / "tests" / "helpers.py"]


def main(argv=None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    roots = [Path(a) for a in args] or default_paths()
    violations: List[Violation] = []
    for f in core.iter_py_files(roots):
        violations.extend(check_file(f))
    for path, lineno, msg in violations:
        print(f"{path}:{lineno}: {msg}")
    if violations:
        print(f"{len(violations)} violation(s)", file=sys.stderr)
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
