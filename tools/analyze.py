#!/usr/bin/env python
"""Kernel-safety static analyzer — the whole rule battery in one run.

Generalizes the two single-rule scripts that used to live here
(``check_no_bare_except.py``, ``check_no_dynamic_gather.py`` — both
now shims over this engine) into one AST/dataflow framework
(``tools/analysis/``) with a rule per decidable bug class:

==============  ====  =====================================================
rule            exit  catches
==============  ====  =====================================================
vmem-budget        1  pallas_call sites that can exceed the ~16 MiB scoped
                      VMEM budget without a chunking/feasibility plan (the
                      ~205K-merged-lane compiler-OOM class)
weak-dtype         2  bare Python float constants in kernel bodies / SMEM
                      scalar operands (the weak-f64 22-test regression)
dynamic-gather     4  gather/scatter-shaped calls in Pallas kernel modules,
                      incl. aliased imports, getattr indirection, .at[...]
grid-carry         8  sequential-grid scratch carries overwritten before
                      being read within a step
env-knobs         16  os.environ outside tempo_tpu/config.py; registry vs
                      code vs BUILDING.md knob-table drift
bare-except       32  bare 'except:' / silent 'except Exception: pass'
parse-error       64  files that do not parse (or cannot be read)
plan-registry    128  TSDF/DistributedTSDF op methods neither recording a
                      plan node (plan.ir.PLANNED_METHODS) nor marked
                      '# plan-ok: eager-only'; registry<->code drift
dead-suppression 256  '# lint-ok:' comments whose rule never fires on
                      that line (stale or typo'd suppressions; audited
                      only on full-battery runs)
==============  ====  =====================================================

The in-process exit code (``core.run``) is the bitwise OR of the fired
rules.  The *process* status folds it into 8 bits nonzero-preserving
(bits past 128 no longer fit the shell's exit byte — a status of 255
means "only high-bit families fired"); the per-rule summary on stderr
is always the authoritative breakdown (statuses >= 128 can also be
signal deaths, which print no summary).  0 means clean.  Suppress one
finding with ``# lint-ok: <rule>: <reason>`` on the flagged line.

Two further tiers share the engine and CLI; each owns its OWN exit-bit
space (the tiers are separate invocations, so statuses never mix).
All three bit spaces in one table:

================ ==== ==================== ==== =================== ====
AST tier         exit compiled tier        exit concurrency tier    exit
(default)             (--compiled)              (--threads)
================ ==== ==================== ==== =================== ====
vmem-budget         1 no-f64-leak             1 guarded-attr           1
weak-dtype          2 no-host-transfer        2 wait-loop              2
dynamic-gather      4 collective-inventory    4 lock-order             4
grid-carry          8 donation-applied        8 blocking-under-lock    8
env-knobs          16 stage-sharding-match   16 ticket-resolution     16
bare-except        32 recompile-coverage     32
parse-error        64 build-error            64 parse-error           64
plan-registry     128
dead-suppression  256 dead-suppression      256 dead-suppression    256
================ ==== ==================== ==== =================== ====

* ``--compiled`` checks contracts against what XLA actually compiled
  (sharding, donation, collectives, dtype, host-transfer) for the
  production-program registry in ``tempo_tpu/plan/contracts.py``.
* ``--threads`` checks the threaded host runtime: a thread-entry
  graph + lock-site map over ``tempo_tpu/`` drive race/deadlock/
  liveness rules (``# guarded-by:`` / ``# thread-shared`` /
  ``# owns-tickets:`` annotations, checked both ways).  See
  BUILDING.md "Concurrency discipline".

An unknown ``--rule`` name exits 2 (argparse's usage status) under
every tier.

Usage::

    python tools/analyze.py                  # default sweep, all rules
    python tools/analyze.py --rule vmem-budget [paths...]
    python tools/analyze.py --list-rules     # all three tiers
    python tools/analyze.py --compiled
    python tools/analyze.py --threads
    python tools/analyze.py --threads --rule guarded-attr tempo_tpu/serve
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.analysis import core  # noqa: E402
from tools.analysis.rules import ALL_RULES  # noqa: E402


def default_paths() -> list:
    """The enforced sweep: the package, the tools themselves, the
    shared test helpers, and the dryrun entry point."""
    return [
        _REPO / "tempo_tpu",
        _REPO / "tools",
        _REPO / "tests" / "helpers.py",
        _REPO / "__graft_entry__.py",
    ]


def main(argv=None) -> int:
    # --help carries the three-tier exit-bit table from the module
    # docstring (one source of truth for all three bit spaces)
    table = __doc__[__doc__.index("All three bit spaces"):
                    __doc__.index("Usage::")].rstrip()
    ap = argparse.ArgumentParser(
        description="tempo-tpu kernel-safety static analyzer",
        epilog=table,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to sweep (default: tempo_tpu/, "
                         "tools/, tests/helpers.py, __graft_entry__.py)")
    ap.add_argument("--rule", action="append", dest="rules", default=None,
                    metavar="NAME", help="run only the named rule(s)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--compiled", action="store_true",
                    help="run the compiled-artifact contract tier over "
                         "the production-program registry "
                         "(tempo_tpu/plan/contracts.py) instead of the "
                         "AST tier")
    ap.add_argument("--program", action="append", dest="programs",
                    default=None, metavar="NAME",
                    help="with --compiled: check only the named "
                         "registry program(s)")
    ap.add_argument("--threads", action="store_true",
                    help="run the concurrency-discipline tier (thread-"
                         "entry graph + lock-site map over tempo_tpu/; "
                         "race/deadlock/liveness rules) instead of the "
                         "AST tier")
    ap.add_argument("--root", type=Path, default=_REPO,
                    help="project root for whole-tree consistency passes "
                         "(BUILDING.md / knob registry)")
    args = ap.parse_args(argv)

    if args.list_rules:
        from tools.analysis import compiled as compiled_tier
        from tools.analysis.concurrency import CONCURRENCY_RULES

        print("AST tier (python tools/analyze.py):")
        for rule in ALL_RULES:
            print(f"  {rule.name:18s} exit {rule.code:3d}  {rule.doc}")
        print(f"  {'dead-suppression':18s} exit "
              f"{core.DEAD_SUPPRESSION_CODE:3d}  stale '# lint-ok:' "
              f"markers whose rule never fires on that line")
        print("compiled tier (python tools/analyze.py --compiled; "
              "separate exit-bit space):")
        for rule in compiled_tier.COMPILED_RULES:
            print(f"  {rule.name:18s} exit {rule.code:3d}  {rule.doc}")
        print(f"  {'build-error':18s} exit "
              f"{compiled_tier.BUILD_ERROR_CODE:3d}  registry programs "
              f"that fail to build/compile at all")
        print("concurrency tier (python tools/analyze.py --threads; "
              "separate exit-bit space):")
        for rule in CONCURRENCY_RULES:
            print(f"  {rule.name:19s} exit {rule.code:3d}  {rule.doc}")
        print(f"  {'dead-suppression':19s} exit "
              f"{core.DEAD_SUPPRESSION_CODE:3d}  stale '# lint-ok:' "
              f"markers whose rule never fires on that line")
        return 0

    if args.programs and not args.compiled:
        ap.error("--program requires --compiled")
    if args.compiled and args.threads:
        ap.error("--compiled and --threads are separate tiers; pick one")
    if args.threads:
        from tools.analysis import concurrency as conc_tier

        if args.paths:
            missing = [p for p in args.paths if not Path(p).exists()]
            if missing:
                ap.error("no such path(s): "
                         + ", ".join(str(p) for p in missing))
        return _fold_status(conc_tier.main(
            paths=args.paths or None, rules=args.rules))
    if args.compiled:
        from tools.analysis import compiled as compiled_tier

        return _fold_status(compiled_tier.main(
            programs=args.programs, rules=args.rules))

    rules = list(ALL_RULES)
    if args.rules:
        known = {r.name: r for r in ALL_RULES}
        unknown = [n for n in args.rules if n not in known]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(see --list-rules)")
        rules = [known[n] for n in args.rules]

    if args.paths:
        # an explicitly named path that is missing must not silently
        # shrink the sweep to nothing (exit 0 while checking nothing)
        missing = [p for p in args.paths if not Path(p).exists()]
        if missing:
            ap.error("no such path(s): "
                     + ", ".join(str(p) for p in missing))
        paths = [Path(p) for p in args.paths]
    else:
        paths = [p for p in default_paths() if p.exists()]
    files = core.load_sources(paths)
    # the dead-suppression audit needs the WHOLE battery's hits to
    # judge a marker dead — a filtered run skips it
    violations, exit_code = core.run(rules, files, root=args.root,
                                     audit=args.rules is None)

    for v in violations:
        print(v.render())
    if violations:
        by_rule = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
        print(f"{len(violations)} violation(s) ({summary}); "
              f"exit code {exit_code}", file=sys.stderr)
    return _fold_status(exit_code)


def _fold_status(exit_code: int) -> int:
    """Fold a rule-bit OR into the shell's 8-bit exit status without
    ever folding a failure to 0: families past bit 7 (dead-suppression
    = 256) cannot ride the status byte, so a run where ONLY such
    families fired exits 255 and the stderr summary carries the
    breakdown."""
    if exit_code <= 0xFF:
        return exit_code
    return (exit_code & 0xFF) or 0xFF


if __name__ == "__main__":
    raise SystemExit(main())
