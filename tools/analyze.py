#!/usr/bin/env python
"""Kernel-safety static analyzer — the whole rule battery in one run.

Generalizes the two single-rule scripts that used to live here
(``check_no_bare_except.py``, ``check_no_dynamic_gather.py`` — both
now shims over this engine) into one AST/dataflow framework
(``tools/analysis/``) with a rule per decidable bug class:

==============  ====  =====================================================
rule            exit  catches
==============  ====  =====================================================
vmem-budget        1  pallas_call sites that can exceed the ~16 MiB scoped
                      VMEM budget without a chunking/feasibility plan (the
                      ~205K-merged-lane compiler-OOM class)
weak-dtype         2  bare Python float constants in kernel bodies / SMEM
                      scalar operands (the weak-f64 22-test regression)
dynamic-gather     4  gather/scatter-shaped calls in Pallas kernel modules,
                      incl. aliased imports, getattr indirection, .at[...]
grid-carry         8  sequential-grid scratch carries overwritten before
                      being read within a step
env-knobs         16  os.environ outside tempo_tpu/config.py; registry vs
                      code vs BUILDING.md knob-table drift
bare-except       32  bare 'except:' / silent 'except Exception: pass'
parse-error       64  files that do not parse (or cannot be read)
plan-registry    128  TSDF/DistributedTSDF op methods neither recording a
                      plan node (plan.ir.PLANNED_METHODS) nor marked
                      '# plan-ok: eager-only'; registry<->code drift
==============  ====  =====================================================

The process exit code is the bitwise OR of the fired rules — a CI log's
status names the failing families (for statuses >= 128 read the
per-rule summary on stderr: the shell uses that range for signal
deaths, which print no summary); 0 means clean.  Suppress one finding
with ``# lint-ok: <rule>: <reason>`` on the flagged line.

Usage::

    python tools/analyze.py                  # default sweep, all rules
    python tools/analyze.py --rule vmem-budget [paths...]
    python tools/analyze.py --list-rules
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))

from tools.analysis import core  # noqa: E402
from tools.analysis.rules import ALL_RULES  # noqa: E402


def default_paths() -> list:
    """The enforced sweep: the package, the tools themselves, the
    shared test helpers, and the dryrun entry point."""
    return [
        _REPO / "tempo_tpu",
        _REPO / "tools",
        _REPO / "tests" / "helpers.py",
        _REPO / "__graft_entry__.py",
    ]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="tempo-tpu kernel-safety static analyzer")
    ap.add_argument("paths", nargs="*", type=Path,
                    help="files/dirs to sweep (default: tempo_tpu/, "
                         "tools/, tests/helpers.py, __graft_entry__.py)")
    ap.add_argument("--rule", action="append", dest="rules", default=None,
                    metavar="NAME", help="run only the named rule(s)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--root", type=Path, default=_REPO,
                    help="project root for whole-tree consistency passes "
                         "(BUILDING.md / knob registry)")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule in ALL_RULES:
            print(f"{rule.name:16s} exit {rule.code:3d}  {rule.doc}")
        return 0

    rules = list(ALL_RULES)
    if args.rules:
        known = {r.name: r for r in ALL_RULES}
        unknown = [n for n in args.rules if n not in known]
        if unknown:
            ap.error(f"unknown rule(s): {', '.join(unknown)} "
                     f"(see --list-rules)")
        rules = [known[n] for n in args.rules]

    if args.paths:
        # an explicitly named path that is missing must not silently
        # shrink the sweep to nothing (exit 0 while checking nothing)
        missing = [p for p in args.paths if not Path(p).exists()]
        if missing:
            ap.error("no such path(s): "
                     + ", ".join(str(p) for p in missing))
        paths = [Path(p) for p in args.paths]
    else:
        paths = [p for p in default_paths() if p.exists()]
    files = core.load_sources(paths)
    violations, exit_code = core.run(rules, files, root=args.root)

    for v in violations:
        print(v.render())
    if violations:
        by_rule = {}
        for v in violations:
            by_rule[v.rule] = by_rule.get(v.rule, 0) + 1
        summary = ", ".join(f"{r}: {n}" for r, n in sorted(by_rule.items()))
        print(f"{len(violations)} violation(s) ({summary}); "
              f"exit code {exit_code}", file=sys.stderr)
    return exit_code


if __name__ == "__main__":
    raise SystemExit(main())
