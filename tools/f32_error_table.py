"""Measure f32-vs-f64 max abs error for the metric kernels at scale.

Produces the BASELINE.md numerics table (VERDICT r1 item 5): runs
withRangeStats (10s window), exact EMA, and linear interpolation under
``TEMPO_TPU_COMPUTE_DTYPE=float32`` and ``float64`` on the current
backend and reports per-stat max abs divergence at L = 2^13 .. 2^17
rows/series (standard-normal values, 1-2s ticks).

Run on the TPU for the shipped table (f64 there is exact-but-emulated,
so the comparison isolates the f32 compute policy):

    python tools/f32_error_table.py            # full sweep
    TEMPO_F32_TABLE_MAX=15 python tools/...    # cap exponent (CI smoke)
"""

import os
import sys

import numpy as np
import pandas as pd

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import tempo_tpu  # noqa: E402
from tempo_tpu import TSDF  # noqa: E402

STATS = ("mean", "count", "min", "max", "sum", "stddev", "zscore")


def build(L: int, K: int = 2, seed: int = 0) -> TSDF:
    rng = np.random.default_rng(seed)
    secs = np.concatenate(
        [np.cumsum(rng.integers(1, 3, size=L)) for _ in range(K)]
    )
    n = K * L
    return TSDF(pd.DataFrame({
        "k": np.repeat(np.arange(K), L),
        "event_ts": pd.to_datetime(secs * 1_000_000_000),
        "x": rng.standard_normal(n),
        "gappy": np.where(rng.random(n) > 0.3, rng.standard_normal(n),
                          np.nan),
    }), "event_ts", ["k"])


def run(frame: TSDF, dtype: str):
    os.environ["TEMPO_TPU_COMPUTE_DTYPE"] = dtype
    stats = frame.withRangeStats(colsToSummarize=["x"],
                                 rangeBackWindowSecs=10).df
    ema = frame.EMA("x", exact=True).df["EMA_x"].to_numpy(float)
    interp = frame.interpolate(freq="5 seconds", func="mean",
                               target_cols=["gappy"],
                               method="linear").df["gappy"].to_numpy(float)
    return stats, ema, interp


def main():
    import jax

    max_exp = int(os.environ.get("TEMPO_F32_TABLE_MAX", "17"))
    print(f"backend: {jax.default_backend()}", file=sys.stderr)
    rows = []
    for exp in range(13, max_exp + 1):
        L = 1 << exp
        frame = build(L)
        s64, e64, i64_ = run(frame, "float64")
        s32, e32, i32_ = run(frame, "float32")
        errs = {}
        for stat in STATS:
            a = s32[f"{stat}_x"].to_numpy(float)
            b = s64[f"{stat}_x"].to_numpy(float)
            errs[stat] = float(np.nanmax(np.abs(a - b)))
        errs["ema"] = float(np.nanmax(np.abs(e32 - e64)))
        errs["linear"] = float(np.nanmax(np.abs(i32_ - i64_)))
        rows.append((L, errs))
        print(f"L=2^{exp} done", file=sys.stderr)

    cols = list(STATS) + ["ema", "linear"]
    print("| L | " + " | ".join(cols) + " |")
    print("|---" * (len(cols) + 1) + "|")
    for L, errs in rows:
        cells = " | ".join(f"{errs[c]:.1e}" for c in cols)
        print(f"| 2^{int(np.log2(L))} | {cells} |")


if __name__ == "__main__":
    main()
