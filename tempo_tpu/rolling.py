"""Frame-level rolling & grouped statistics, EMA, VWAP, lookback features.

Reference surface reproduced here:
* ``withRangeStats``  - tsdf.py:673-721
* ``withGroupedStats`` - tsdf.py:723-759
* ``EMA``             - tsdf.py:615-635 (plus an exact scan-based mode)
* ``vwap``            - scala TSDF.scala:378-401 (the Scala version is
  the working spec; the Python one calls builtin ``sum``/``max`` on
  Columns - tsdf.py:608-610 - and cannot run)
* ``withLookbackFeatures`` - tsdf.py:637-671 (incl. the exactSize=True
  bare-DataFrame quirk)
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pandas as pd

from tempo_tpu import packing
from tempo_tpu.freq import freq_to_seconds, UNIT_SECONDS
from tempo_tpu.ops import rolling as rk

import jax
import jax.numpy as jnp


def _packed_metric_stack(tsdf, cols: List[str]):
    """Stack metric columns into [C, K, L] values + valids."""
    vals, valids = [], []
    for c in cols:
        v, m = tsdf.packed_numeric(c)
        vals.append(v)
        valids.append(m)
    return np.stack(vals), np.stack(valids)


def plan_range_engine(tsdf, cols: List[str], rangeBackWindowSecs: int):
    """``(engine, rowbounds, ts_long, w)`` the host ``withRangeStats``
    three-way pick will choose for this frame/window — ONE function so
    the eager path below and the lazy planner's plan-time hoist
    (tempo_tpu/plan/optimizer.py) can never diverge.  ``rowbounds`` is
    None when the static-shift forms cannot vouch for the frame (spans
    past int32, no sort kernels) and the prefix+RMQ windowed form must
    run.  ``ts_long``/``w`` (the rebased per-series seconds and the
    clamped window) ride along so the eager caller does not redo the
    O(K*L) packing work the pick already paid for."""
    from tempo_tpu.ops import pallas_stats as _ps
    from tempo_tpu.ops import pallas_window as _pw
    from tempo_tpu.ops import sortmerge as sm

    layout = tsdf.layout
    if layout.n_rows == 0 or not cols:
        return "windowed", None, None, None
    # Spark cast-to-long seconds; 64-bit compares are emulated on TPU,
    # so rebase to per-series int32 seconds when spans allow (range
    # windows only ever compare within a series, so a per-series
    # origin is safe)
    ts_long = tsdf.packed_ts() // packing.NS_PER_S
    ts_long, _ = packing.rebase_seconds(ts_long, ~tsdf.packed_mask())
    # a window larger than any rebased span is equivalent to
    # 'unbounded preceding'; clamp so huge windows cannot overflow the
    # int32 path
    w = min(int(rangeBackWindowSecs),
            int(np.iinfo(ts_long.dtype).max) // 2)
    rb = (packing.layout_rowbounds(layout, w)
          if ts_long.dtype == np.int32 and sm.use_sort_kernels()
          else None)
    K, L = ts_long.shape
    f32 = np.dtype(packing.compute_dtype()) == np.float32
    # feasibility and the HBM budget are per COLUMN since the packed
    # rewire: the pallas engines block [C<=pack, bk, L] (columns
    # sequenced inside the kernel, pack width folded separately by
    # pack_cols_budget) and the XLA fallbacks loop single [K, L]
    # columns — the old C*K flattened gate modeled the tiled layout
    # that no longer runs.  This matches the mesh path's per-column
    # pick (dist._pick_range_engine_for_shard).
    pallas_ok = f32 and _ps.pallas_block_feasible(K, L)
    stream_ok = f32 and _pw.stream_block_feasible(K, L)
    engine = ("windowed" if rb is None else rk.pick_range_engine(
        K * L, rb[0], rb[1], pallas_ok, stream_ok))
    return engine, rb, ts_long, w


def with_range_stats(tsdf, type: str = "range", colsToSummarize=None,
                     rangeBackWindowSecs: int = 1000):
    from tempo_tpu.frame import TSDF

    cols = colsToSummarize or tsdf.summarizable_columns()
    layout = tsdf.layout
    out = tsdf.df.iloc[layout.order].reset_index(drop=True)
    if not cols:
        # reference adds zero stat columns in this case (tsdf.py:691-721)
        return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)
    if layout.n_rows == 0:
        # empty frame: emit the stat schema (Spark yields the columns
        # with zero rows) without dispatching zero-size reductions
        for c in cols:
            for stat in packing.RANGE_STATS:
                out[f"{stat}_{c}"] = np.zeros(
                    0, dtype=np.int64 if stat == "count" else np.float64
                )
        return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)
    vals, valids = _packed_metric_stack(tsdf, cols)
    C, K, L = vals.shape
    flat = lambda a: jnp.asarray(a).reshape(C * K, L)
    tile = lambda a: jnp.broadcast_to(a[None], (C, K, L)).reshape(C * K, L)

    # three-way auto-pick (bench.py rolling_crossover is the measured
    # evidence): row-boundable frames take the static-shift form — W
    # masked shifted passes, VMEM-resident on TPU; wider frames the
    # streaming VMEM sweep (runtime-width, ops/pallas_window.py); the
    # general prefix-scan + RMQ form covers whatever remains (spans
    # past int32, no TPU, extents past TEMPO_TPU_STREAM_MAX_ROWS).
    # Same picker as the mesh path (dist.withRangeStats); under the
    # lazy planner the decision is hoisted to plan time and arrives
    # here as a hint (plan_range_engine + ops/rolling.pick_range_engine)
    from tempo_tpu.ops import sortmerge as sm

    engine, rb, ts_long, w = plan_range_engine(tsdf, cols,
                                               rangeBackWindowSecs)
    if engine == "shifted":
        # multi-column payload packing: the [C, K, L] metric stack
        # shares ONE [K, L] key plane — the packed kernels read it once
        # per pack where the seed path materialised a C-wide broadcast
        # copy of the timestamps (`tile`) and streamed it per column
        stats = dict(sm.range_stats_shifted_packed(
            jnp.asarray(ts_long), jnp.asarray(vals), jnp.asarray(valids),
            jnp.asarray(np.int32(w)),
            max_behind=int(rb[0]), max_ahead=int(rb[1]),
        ))
        # the truncation audit rides the SAME stacked fetch as the
        # stats below (the axon tunnel has a >1s per-transfer latency
        # floor — one extra scalar round trip would double it)
    elif engine == "stream":
        stats = dict(rk.range_stats_streaming_packed(
            jnp.asarray(ts_long), jnp.asarray(vals), jnp.asarray(valids),
            jnp.asarray(np.int32(w)),
            max_behind=int(rb[0]), max_ahead=int(rb[1]),
        ))
    else:
        ts_arr = jnp.asarray(ts_long)
        start, end = rk.range_window_bounds(
            ts_arr, rk.range_window_width(ts_arr, w)
        )
        # static row bound for the min/max sparse tables: a 10s window
        # over 1Hz data needs 4 levels, not log2(L); bucket to a power
        # of two so distinct datasets reuse the compiled kernel.
        # Padded slots all share the clamped sentinel timestamp, so
        # their windows span the whole pad run — mask them out of the
        # bound or ragged series inflate it toward L
        real = jnp.asarray(tsdf.packed_mask())
        max_w = max(1, int(jax.device_get(
            jnp.max(jnp.where(real, end - start, 0)))))
        max_w = 1 << (max_w - 1).bit_length()
        stats = rk.windowed_stats(
            flat(vals), flat(valids), tile(start), tile(end),
            max_window=max_w
        )
    # one stacked device->host transfer: the axon tunnel has a >1s
    # per-transfer latency floor, so 7 separate fetches cost seconds.
    # The shifted path's truncation-audit scalar piggybacks as one
    # extra element on the same flattened buffer.
    clip = stats.pop("clipped", None)
    names = sorted(stats)
    planes = jnp.stack([stats[k] for k in names]).reshape(-1)
    if clip is not None:
        planes = jnp.concatenate(
            [planes, jnp.sum(clip).reshape(1).astype(planes.dtype)]
        )
    buf = np.asarray(planes)
    if clip is not None:
        clipped_total = float(buf[-1])
        buf = buf[:-1]
        if clipped_total:  # pragma: no cover - bound-derivation bug guard
            raise AssertionError(
                f"withRangeStats: {clipped_total} rows exceeded the "
                f"derived row bounds {rb}; this is a tempo-tpu bug"
            )
    # packed engines yield [C, K, L] planes, the windowed fallback
    # [C*K, L] — the element order is identical either way
    stacked = buf.reshape(len(names), C, K, L)
    stats = {k: stacked[i] for i, k in enumerate(names)}

    for ci, c in enumerate(cols):
        for stat in packing.RANGE_STATS:
            flat = packing.unpack_column(stats[stat][ci], layout)
            if stat == "count":
                out[f"{stat}_{c}"] = flat.astype(np.int64)
            else:
                # Spark emits DoubleType stats regardless of input width
                out[f"{stat}_{c}"] = flat.astype(np.float64)
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)


def _bucket_ns(ts_ns: np.ndarray, freq_sec: int) -> np.ndarray:
    """Epoch-aligned tumbling window start (Spark f.window semantics)."""
    step = np.int64(freq_sec) * packing.NS_PER_S
    return (ts_ns // step) * step


def _segments(layout, bucket: np.ndarray):
    """Contiguous (series, bucket) runs over the sorted flat layout."""
    n = layout.n_rows
    if n == 0:
        return np.zeros(0, np.int32), np.zeros(0, np.int64), np.zeros(0, np.int64)
    change = np.ones(n, dtype=bool)
    change[1:] = (layout.key_ids[1:] != layout.key_ids[:-1]) | (
        bucket[1:] != bucket[:-1]
    )
    seg_ids = np.cumsum(change) - 1
    first_row = np.flatnonzero(change)
    return seg_ids.astype(np.int32), first_row, bucket[first_row]


def with_grouped_stats(tsdf, metricCols=None, freq: Optional[str] = None):
    from tempo_tpu.frame import TSDF

    cols = metricCols or tsdf.summarizable_columns()
    freq_sec = freq_to_seconds(freq)

    layout = tsdf.layout
    bucket = _bucket_ns(layout.ts_ns, freq_sec)
    seg_ids, first_row, seg_bucket = _segments(layout, bucket)
    n_seg = len(first_row)
    n_seg_padded = max(8, 1 << (n_seg - 1).bit_length()) if n_seg else 8

    out = {}
    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    for c in tsdf.partitionCols:
        out[c] = sorted_df[c].to_numpy()[first_row]
    out[tsdf.ts_col] = packing.ns_to_original(seg_bucket, tsdf.ts_dtype())

    dt = packing.compute_dtype()
    for c in cols:
        v, m = tsdf.numeric_flat(c)
        stats = rk.segment_stats(
            jnp.asarray(v.astype(dt)), jnp.asarray(m), jnp.asarray(seg_ids),
            n_seg_padded,
        )
        for stat in ("mean", "count", "min", "max", "sum", "stddev"):
            arr = np.asarray(stats[stat])[:n_seg]
            if stat == "count":
                arr = arr.astype(np.int64)
            else:
                arr = arr.astype(np.float64)
            out[f"{stat}_{c}"] = arr
    return TSDF(pd.DataFrame(out), tsdf.ts_col, tsdf.partitionCols)


def ema(tsdf, colName: str, window: int = 30, exp_factor: float = 0.2,
        exact: bool = False, inclusive_window: bool = False):
    """``inclusive_window=True`` reproduces the Scala lag range 0..window
    (EMA.scala:31, one more tap than the Python 0..window-1 range,
    tsdf.py:627 - the divergence tabled in SURVEY.md §2.4)."""
    from tempo_tpu.frame import TSDF

    layout = tsdf.layout
    v, m = tsdf.packed_numeric(colName)
    n_taps = int(window) + (1 if inclusive_window else 0)
    if exact:
        from tempo_tpu.ops import pallas_kernels as pk

        y = pk.ema_scan(jnp.asarray(v), jnp.asarray(m), exp_factor)
    else:
        y = rk.ema_compat(jnp.asarray(v), jnp.asarray(m), n_taps, float(exp_factor))
    out = tsdf.df.iloc[layout.order].reset_index(drop=True)
    out["EMA_" + colName] = packing.unpack_column(
        np.asarray(y), layout
    ).astype(np.float64)
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)


_VWAP_TRUNC = {"m": "min", "H": "hr", "D": "day"}


def vwap(tsdf, frequency: str = "m", volume_col: str = "volume",
         price_col: str = "price"):
    """Scala-spec VWAP (TSDF.scala:378-401): truncate the ts to the
    given frequency, then per (partition, time group):
    dllr_value = sum(price*volume), volume = sum(volume),
    max_<price> = max(price), vwap = dllr_value / volume."""
    from tempo_tpu.frame import TSDF

    if frequency not in _VWAP_TRUNC:
        raise ValueError("vwap frequency must be one of 'm', 'H', 'D'")
    freq_sec = UNIT_SECONDS[_VWAP_TRUNC[frequency]]

    layout = tsdf.layout
    bucket = _bucket_ns(layout.ts_ns, freq_sec)
    seg_ids, first_row, seg_bucket = _segments(layout, bucket)
    n_seg = len(first_row)
    n_seg_padded = max(8, 1 << (n_seg - 1).bit_length()) if n_seg else 8

    dt = packing.compute_dtype()
    price, p_ok = tsdf.numeric_flat(price_col)
    vol, v_ok = tsdf.numeric_flat(volume_col)
    price, vol = price.astype(dt), vol.astype(dt)
    d_ok = p_ok & v_ok

    seg = jnp.asarray(seg_ids)
    s_d = rk.segment_stats(jnp.asarray(price * vol), jnp.asarray(d_ok), seg, n_seg_padded)
    s_v = rk.segment_stats(jnp.asarray(vol), jnp.asarray(v_ok), seg, n_seg_padded)
    s_p = rk.segment_stats(jnp.asarray(price), jnp.asarray(p_ok), seg, n_seg_padded)

    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    out = {}
    for c in tsdf.partitionCols:
        out[c] = sorted_df[c].to_numpy()[first_row]
    out[tsdf.ts_col] = packing.ns_to_original(seg_bucket, tsdf.ts_dtype())
    dllr_sum = np.asarray(s_d["sum"])[:n_seg].astype(np.float64)
    vol_sum = np.asarray(s_v["sum"])[:n_seg].astype(np.float64)
    out["dllr_value"] = dllr_sum
    out[volume_col] = vol_sum
    out["max_" + price_col] = np.asarray(s_p["max"])[:n_seg].astype(np.float64)
    out["vwap"] = dllr_sum / vol_sum
    return TSDF(pd.DataFrame(out), tsdf.ts_col, tsdf.partitionCols)


def with_lookback_features(tsdf, featureCols: List[str], lookbackWindowSize: int,
                           exactSize: bool = True, featureColName: str = "features"):
    """Parity: tsdf.py:637-671.  Builds, per row, the [w, n_features]
    array of the previous ``lookbackWindowSize`` observations
    (rowsBetween(-N, -1)); rows nearer the series start get shorter
    arrays unless exactSize filters them.

    Returns a bare DataFrame when exactSize=True (reference quirk,
    tsdf.py:668-669), else a TSDF.
    """
    from tempo_tpu.frame import TSDF

    layout = tsdf.layout
    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    n = len(sorted_df)
    w = int(lookbackWindowSize)

    # heavy lifting on device: the dense [K, L, w, F] shifted stack (the
    # same path lookback_tensor exposes), fetched once — the per-row
    # Python slicing loop this replaces crawled at quickstart scale
    tensor, _ = lookback_tensor(tsdf, featureCols, w)
    # flatten packed rows back to the sorted flat layout: [n, w, F]
    pos = np.arange(n, dtype=np.int64) - layout.starts[layout.key_ids]
    flat = np.asarray(tensor, dtype=np.float64)[layout.key_ids, pos]
    # rows nearer their series start have only pos valid lookback
    # entries, sitting at the *end* of the window axis
    cnt = np.minimum(pos, w)

    out = sorted_df.copy()
    if exactSize:
        keep = cnt == w
        out = out[keep].reset_index(drop=True)
        # single C-level materialisation of the object lists
        out[featureColName] = pd.Series(
            flat[keep].tolist(), index=out.index, dtype=object
        )
        return out
    nested = flat.tolist()
    out[featureColName] = pd.Series(
        [nested[i][w - cnt[i]:] for i in range(n)], dtype=object
    )
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)


def lookback_stack(x, m, w: int):
    """[K, L, F] (values, mask) -> [K, L, w, F] shifted stacks: window
    slot j holds observation t - w + j (oldest first), zero/False
    where absent.  The single definition of the lookback-window
    semantics — shared by the host path below and the shard_map kernel
    (dist.py:_lookback_tensor_fn)."""
    L = x.shape[1]
    sh = lambda a, j: jnp.pad(a, ((0, 0), (j, 0), (0, 0)))[:, :L, :]
    return (jnp.stack([sh(x, j) for j in range(w, 0, -1)], axis=2),
            jnp.stack([sh(m, j) for j in range(w, 0, -1)], axis=2))


def lookback_tensor(tsdf, featureCols: List[str], lookbackWindowSize: int):
    """TPU-native variant: the dense [K, L, w, F] lookback tensor as a
    jax array (zero-padded, with a validity mask), suitable for feeding
    models directly without object-array materialisation."""
    vals, valids = _packed_metric_stack(tsdf, featureCols)   # [F, K, L]
    x = jnp.asarray(vals).transpose(1, 2, 0)                 # [K, L, F]
    m = jnp.asarray(valids).transpose(1, 2, 0)
    return lookback_stack(x, m, int(lookbackWindowSize))
