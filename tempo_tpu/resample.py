"""Resampling / downsampling / upsample-fill.

Reference semantics (python/tempo/resample.py):

* ``aggregate`` (resample.py:38-117): epoch-aligned tumbling buckets via
  ``f.window``; five funcs - floor/ceil pick the *whole record* with the
  min/max timestamp in the bucket (struct-min trick, resample.py:62-66,
  87-92), mean/min/max aggregate each metric column independently; the
  bucket start becomes the new ts; metric columns default to every
  non-grouping column (strings included - Spark's avg() of a string
  yields a null double, which we reproduce); output columns are
  partition cols + ts + sorted(rest) (resample.py:97-100); optional
  ``fill`` upsamples to a dense grid and zero-fills numeric columns
  (resample.py:102-116).
* ``_ResampledTSDF`` (tsdf.py:905-944): remembers (freq, func) so a
  chained ``.interpolate(method=...)`` needs no re-sample.

TPU design: bucketing is integer arithmetic on the packed int64-ns time
axis; per-bucket aggregation is a flat segment reduction (already-sorted
rows mean segment ids are contiguous - no shuffle, no hash aggregation);
floor/ceil are first/last-row-of-segment gathers that move *indices*,
not values, so string columns ride along for free.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np
import pandas as pd

import jax.numpy as jnp

from tempo_tpu import packing
from tempo_tpu.freq import (
    freq_to_seconds,
    validateFuncExists,
    floor,
    ceiling,
    average,
    min_func,
    max_func,
    CLOSEST_LEAD,
    MEAN_LEAD,
    MIN_LEAD,
    MAX_LEAD,
)
from tempo_tpu.ops import rolling as rk
from tempo_tpu.rolling import _bucket_ns, _segments


def _is_numeric_col(df: pd.DataFrame, c: str) -> bool:
    return (
        pd.api.types.is_numeric_dtype(df[c].dtype)
        and not pd.api.types.is_bool_dtype(df[c].dtype)
    )


_LEAD_ALIASES = {CLOSEST_LEAD: floor, MEAN_LEAD: average,
                 MIN_LEAD: min_func, MAX_LEAD: max_func}


def aggregate(tsdf, freq: str, func: str, metricCols=None, prefix=None,
              fill=None) -> pd.DataFrame:
    func = _LEAD_ALIASES.get(func, func)
    freq_sec = freq_to_seconds(freq)

    layout = tsdf.layout
    grouping = set(tsdf.partitionCols + [tsdf.ts_col])
    if metricCols is None:
        metricCols = [c for c in tsdf.df.columns if c not in grouping]
    prefix = "" if prefix is None else prefix + "_"

    bucket = _bucket_ns(layout.ts_ns, freq_sec)
    seg_ids, first_row, seg_bucket = _segments(layout, bucket)
    n_seg = len(first_row)
    n_seg_padded = max(8, 1 << (n_seg - 1).bit_length()) if n_seg else 8
    last_row = (np.append(first_row[1:], layout.n_rows) - 1) if n_seg else first_row

    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    out = {}
    for c in tsdf.partitionCols:
        out[c] = sorted_df[c].to_numpy()[first_row]
    out[tsdf.ts_col] = packing.ns_to_original(seg_bucket, tsdf.ts_dtype())

    if func in (floor, ceiling):
        # whole-record min/max-by-timestamp (struct trick equivalent):
        # gather the first/last row of each contiguous segment
        pick = first_row if func == floor else last_row
        for c in metricCols:
            out[prefix + c] = sorted_df[c].to_numpy()[pick]
    else:
        dt = packing.compute_dtype()
        for c in metricCols:
            if _is_numeric_col(sorted_df, c):
                vals = pd.to_numeric(sorted_df[c], errors="coerce").to_numpy(np.float64)
                valid = ~np.isnan(vals)
                stats = rk.segment_stats(
                    jnp.asarray(vals.astype(dt)), jnp.asarray(valid),
                    jnp.asarray(seg_ids), n_seg_padded,
                )
                key = {average: "mean", min_func: "min", max_func: "max"}[func]
                out[prefix + c] = np.asarray(stats[key])[:n_seg].astype(np.float64)
            elif func == average:
                # Spark avg(string) -> null double (exercised by the
                # reference's 5-minute mean resample golden)
                out[prefix + c] = np.full(n_seg, np.nan)
            else:
                # lexicographic min/max for non-numerics, host-side
                s = pd.Series(sorted_df[c].to_numpy(), copy=False)
                agg = s.groupby(seg_ids).min() if func == min_func else s.groupby(seg_ids).max()
                out[prefix + c] = agg.to_numpy()

    res = pd.DataFrame(out)
    # deterministic column order (resample.py:97-100)
    non_part = sorted(set(res.columns) - set(tsdf.partitionCols) - {tsdf.ts_col})
    res = res[tsdf.partitionCols + [tsdf.ts_col] + non_part]

    if fill:
        res = upsample_fill(res, tsdf.partitionCols, tsdf.ts_col, freq_sec)
    return res


def upsample_fill(res: pd.DataFrame, pcols: List[str], ts_col: str,
                  freq_sec: int) -> pd.DataFrame:
    """Dense per-key grid from min to max ts, left-join, zero-fill
    numerics (resample.py:102-116)."""
    step = np.int64(freq_sec) * packing.NS_PER_S
    ts_ns = packing.series_to_ns(res[ts_col])
    frames = []
    key_iter = (
        res.assign(__ts_ns=ts_ns).groupby(pcols, sort=False, dropna=False)
        if pcols
        else [((), res.assign(__ts_ns=ts_ns))]
    )
    for key, g in key_iter:
        lo, hi = g["__ts_ns"].min(), g["__ts_ns"].max()
        grid = np.arange(lo, hi + step, step, dtype=np.int64)
        gdf = pd.DataFrame({ts_col: packing.ns_to_original(grid, res[ts_col].dtype)})
        if pcols:
            if not isinstance(key, tuple):
                key = (key,)
            for c, v in zip(pcols, key):
                gdf[c] = v
        frames.append(gdf)
    imputes = pd.concat(frames, ignore_index=True)
    merged = imputes.merge(res.drop(columns="__ts_ns", errors="ignore"),
                           on=pcols + [ts_col], how="left")
    metrics = [c for c in merged.columns if _is_numeric_col(merged, c)
               and c not in pcols and c != ts_col]
    merged[metrics] = merged[metrics].fillna(0)
    return merged


def resample_ema(tsdf, freq: str, colName: str, exp_factor: float = 0.2):
    """Fused floor-resample + exact EMA in ONE device pass.

    The chained form — ``resample(freq, 'floor')`` then ``ema(...,
    exact=True)`` — streams the column through HBM twice (one pass per
    op) plus a host round trip for the intermediate frame.  Here the
    bucket-head pick and the EMA scan run as a single VMEM kernel on
    TPU (ops/pallas_bucket.py:resample_ema_pallas) or one fused XLA
    program elsewhere: the column is read once.

    Semantics: per (series, epoch-aligned ``freq`` bucket), the value
    of the bucket's first row *when that row is non-null* (a bucket
    whose first row is null yields a null sample and the EMA carries —
    the ``ema_exact`` null contract); the EMA is the exact
    infinite-horizon scan over those samples.
    Returns a TSDF with one row per bucket: partition cols, the bucket
    start as the new ts, ``colName`` (the floor sample) and
    ``EMA_<colName>``.

    **Truncated-lag EMA — the canonical note** (other kernels point
    here).  The reference computes EMA as an explicit ``window``-term
    lag sum — ``EMA_t = sum_{i=0}^{window-1} e(1-e)^i x_{t-i}`` —
    because one Spark window expression per lag is the only form it
    has; its own tsdf.py:617-618 TODO asks for the exact recursive
    formulation.  On this stack the recursion ``y_t = (1-a) y_{t-1} +
    a x_t`` IS the native form, in three interchangeable guises:
    ``ops/rolling.ema_exact`` (associative scan — fastest, but its
    combine-tree bracketing, and so its f32 rounding, depends on the
    total length), ``ops/rolling.ema_scan`` (sequential ``lax.scan`` —
    one multiply-add per element, split-invariant bitwise, the
    serving engine's resumable form), and
    ``ops/pallas_kernels.ema_scan`` (the Mosaic roll-ladder kernel in
    the fused pipeline).  All three are exact infinite-horizon: no
    truncation error, and null inputs carry the previous EMA forward.
    ``TSDF.EMA(exact=False)`` keeps reference-parity truncation
    (``ops/rolling.ema_compat``, one causal depthwise convolution) for
    drop-in compatibility; the exact form is also what lets the
    distributed EMA cross time shards by carrying ``y_end``
    (dist.py) — a lag sum cannot.
    """
    from tempo_tpu.ops import pallas_bucket as pb
    from tempo_tpu.ops import pallas_kernels as pkk

    freq_sec = freq_to_seconds(freq)
    layout = tsdf.layout

    v, m = tsdf.packed_numeric(colName)            # [K, L] + mask
    secs = tsdf.packed_ts() // packing.NS_PER_S    # absolute int64 s
    vj = jnp.asarray(v)
    mj = jnp.asarray(m)
    # bucket boundaries are epoch-aligned, so the kernel needs the
    # ABSOLUTE seconds (a per-series rebase would move them): int32
    # only until 2038 — fall back to XLA beyond that.  Pads carry the
    # TS_PAD sentinel and are invalid either way (head requires a
    # valid row), so only REAL rows bound the cast
    real = tsdf.packed_mask()
    secs_max = int(np.where(real, secs, 0).max(initial=0))
    use_pallas = (secs_max + freq_sec < 2**31
                  and pb.resample_ema_supported(
                      jnp.asarray(secs).astype(jnp.int32), vj))
    if use_pallas:
        res, ema = pb.resample_ema_pallas(
            jnp.asarray(secs).astype(jnp.int32), vj, mj,
            step=freq_sec, alpha=float(exp_factor))
    else:
        bucket = jnp.asarray(secs) // freq_sec
        head = jnp.concatenate(
            [jnp.ones_like(bucket[:, :1], dtype=bool),
             bucket[:, 1:] != bucket[:, :-1]], axis=-1,
        ) & mj
        res = jnp.where(head, vj, jnp.nan)
        ema = pkk.ema_scan(vj, head, float(exp_factor))

    # one stacked fetch, then assemble one output row per (series,
    # bucket) run from the host segment machinery
    planes = np.asarray(jnp.stack([res.astype(jnp.float32),
                                   ema.astype(jnp.float32)]))
    res_flat = packing.unpack_column(planes[0], layout)
    ema_flat = packing.unpack_column(planes[1], layout)

    bucket_ns = _bucket_ns(layout.ts_ns, freq_sec)
    seg_ids, first_row, seg_bucket = _segments(layout, bucket_ns)
    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    out = {}
    for c in tsdf.partitionCols:
        out[c] = sorted_df[c].to_numpy()[first_row]
    out[tsdf.ts_col] = packing.ns_to_original(seg_bucket, tsdf.ts_dtype())
    out[colName] = res_flat[first_row].astype(np.float64)
    out["EMA_" + colName] = ema_flat[first_row].astype(np.float64)
    return TSDF(pd.DataFrame(out), tsdf.ts_col, tsdf.partitionCols)


def resample(tsdf, freq: str, func=None, metricCols=None, prefix=None,
             fill=None):
    """TSDF.resample (tsdf.py:764-776): validates the func, aggregates,
    returns a _ResampledTSDF that remembers (freq, func)."""
    validateFuncExists(func)
    enriched = aggregate(tsdf, freq, func, metricCols, prefix, fill)
    return _ResampledTSDF(
        enriched, ts_col=tsdf.ts_col, partition_cols=tsdf.partitionCols,
        freq=freq, func=func,
    )


def calc_bars(tsdf, freq: str, func=None, metricCols=None, fill=None):
    """OHLC bars (tsdf.py:813-826): four resamples joined on key+ts."""
    opens = resample(tsdf, freq=freq, func="floor", metricCols=metricCols,
                     prefix="open", fill=fill)
    lows = resample(tsdf, freq=freq, func="min", metricCols=metricCols,
                    prefix="low", fill=fill)
    highs = resample(tsdf, freq=freq, func="max", metricCols=metricCols,
                     prefix="high", fill=fill)
    closes = resample(tsdf, freq=freq, func="ceil", metricCols=metricCols,
                      prefix="close", fill=fill)

    join_cols = opens.partitionCols + [opens.ts_col]
    bars = (
        opens.df.merge(highs.df, on=join_cols)
        .merge(lows.df, on=join_cols)
        .merge(closes.df, on=join_cols)
    )
    non_part = sorted(set(bars.columns) - set(opens.partitionCols) - {opens.ts_col})
    bars = bars[opens.partitionCols + [opens.ts_col] + non_part]
    return TSDF(bars, opens.ts_col, opens.partitionCols)


from tempo_tpu.frame import TSDF  # noqa: E402  (frame never imports us eagerly)


class _ResampledTSDF(TSDF):
    """A TSDF that remembers its (freq, func) so a chained
    ``.interpolate(method=...)`` needs no re-sample (tsdf.py:905-944)."""

    def __init__(self, df, ts_col="event_ts", partition_cols=None,
                 sequence_col=None, freq=None, func=None):
        super().__init__(df, ts_col, partition_cols, sequence_col)
        self._freq = freq
        self._func = func

    def interpolate(self, method: str, target_cols: Optional[List[str]] = None,
                    show_interpolated: bool = False):
        from tempo_tpu import interpol

        if target_cols is None:
            prohibited = set(self.partitionCols + [self.ts_col])
            target_cols = [
                c for c in self.df.columns
                if _is_numeric_col(self.df, c) and c not in prohibited
            ]
        service = interpol.Interpolation(is_resampled=True)
        out = service.interpolate(
            tsdf=self, ts_col=self.ts_col, partition_cols=self.partitionCols,
            target_cols=target_cols, freq=self._freq, func=self._func,
            method=method, show_interpolated=show_interpolated,
        )
        return TSDF(out, ts_col=self.ts_col, partition_cols=self.partitionCols)
