"""Checkpoint / resume for distributed pipelines.

The reference has no checkpoint mechanism of its own — its
transformations are stateless Spark plans and recovery is task re-run
(SURVEY.md §5 "Checkpoint / resume: none").  tempo-tpu's distributed
frames DO carry state worth snapshotting: the packed, sharded device
arrays of a :class:`~tempo_tpu.dist.DistributedTSDF` mid-pipeline (a
chain may have executed several expensive device ops since ingest).
This module adds the elasticity story the rebuild was asked to
first-class (driver spec "failure detection, checkpoint/resume"):

* :func:`save` — fetch the frame's device state (one stacked transfer,
  same path as ``collect``) and write a self-describing directory:
  ``manifest.json`` + ``arrays.npz`` (+ ``host.parquet`` for
  host-resident columns and the key frame).
* :func:`load` — restore a device-resident ``DistributedTSDF`` onto a
  caller-provided mesh (the mesh may have a different device count than
  the one that saved — re-placement is just a new NamedSharding).

Checkpoints are atomic (write to ``<dir>.tmp`` then rename) so a crash
mid-save never corrupts the previous checkpoint, and versioned so
future layout changes can refuse gracefully.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import numpy as np
import pandas as pd

import jax

FORMAT_VERSION = 1


def save(frame, path: str) -> None:
    """Snapshot a :class:`DistributedTSDF` (or host :class:`TSDF`) to
    ``path`` (a directory).  Atomic: the directory appears fully
    written or not at all."""
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.frame import TSDF

    tmp = path + ".tmp"
    bak = path + ".bak"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        if isinstance(frame, DistributedTSDF):
            _save_dist(frame, tmp)
        elif isinstance(frame, TSDF):
            _save_host(frame, tmp)
        else:
            raise TypeError(f"cannot checkpoint {type(frame)}")
        # three-step swap: at every crash point either ``path`` or
        # ``path.bak`` holds a complete previous/new checkpoint (load()
        # falls back to .bak), so the guarantee survives a crash between
        # the renames — rmtree(path) before replace would not
        if os.path.exists(bak):
            shutil.rmtree(bak)
        if os.path.exists(path):
            os.replace(path, bak)
        os.replace(tmp, path)
        shutil.rmtree(bak, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load(path: str, mesh=None, series_axis: str = "series",
         time_axis: Optional[str] = None):
    """Restore a checkpoint.  Distributed checkpoints need a ``mesh``
    (any device count — resume elsewhere is a re-placement); host
    checkpoints ignore it."""
    if not os.path.exists(os.path.join(path, "manifest.json")) \
            and os.path.exists(os.path.join(path + ".bak", "manifest.json")):
        path = path + ".bak"   # crash mid-swap: previous checkpoint
    with open(os.path.join(path, "manifest.json")) as f:
        man = json.load(f)
    if man["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {man['format_version']} is newer than "
            f"this library understands ({FORMAT_VERSION})"
        )
    if man["kind"] == "host":
        return _load_host(path, man)
    if mesh is None:
        raise ValueError("distributed checkpoint needs a mesh to resume on")
    return _load_dist(path, man, mesh, series_axis, time_axis)


# ----------------------------------------------------------------------
# host TSDF
# ----------------------------------------------------------------------

def _save_host(tsdf, d: str) -> None:
    tsdf.df.to_parquet(os.path.join(d, "host.parquet"))
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({
            "format_version": FORMAT_VERSION,
            "kind": "host",
            "ts_col": tsdf.ts_col,
            "partition_cols": tsdf.partitionCols,
            "sequence_col": tsdf.sequence_col or None,
        }, f, indent=2)


def _load_host(d: str, man: dict):
    from tempo_tpu.frame import TSDF

    df = pd.read_parquet(os.path.join(d, "host.parquet"))
    return TSDF(df, man["ts_col"], man["partition_cols"],
                man.get("sequence_col"))


# ----------------------------------------------------------------------
# DistributedTSDF
# ----------------------------------------------------------------------

def _save_dist(frame, d: str) -> None:
    import jax.numpy as jnp

    names = list(frame.cols)
    # ONE stacked fetch for all column planes (collect()'s transfer
    # discipline: values + valids ride a single [2C, K, L] transfer),
    # plus ts/mask
    arrays = {
        "ts": np.asarray(frame.ts),
        "mask": np.asarray(frame.mask),
        "layout_ts_ns": frame.layout.ts_ns,
        "layout_starts": frame.layout.starts,
        "layout_key_ids": frame.layout.key_ids,
        "layout_order": frame.layout.order,
    }
    if frame.seq is not None:
        arrays["seq"] = np.asarray(frame.seq)
    if names:
        cdt = frame.cols[names[0]].values.dtype
        stacked = np.asarray(jnp.stack(
            [frame.cols[c].values.astype(cdt) for c in names]
            + [frame.cols[c].valid.astype(cdt) for c in names]
        ))
        val_block, ok_block = stacked[: len(names)], stacked[len(names):]
    col_meta = {}
    hg_idx = 0
    for i, c in enumerate(names):
        col = frame.cols[c]
        arrays[f"col_{i}_values"] = val_block[i]
        arrays[f"col_{i}_valid"] = ok_block[i] > 0.5
        meta = {"name": c, "int64": col.int64, "ts_chunk": col.ts_chunk}
        if col.host_gather is not None:
            flat_vals, r_starts, perm = col.host_gather
            arrays[f"hg_{hg_idx}_vals"] = np.asarray(flat_vals, dtype=object) \
                if flat_vals.dtype == object else flat_vals
            arrays[f"hg_{hg_idx}_starts"] = r_starts
            arrays[f"hg_{hg_idx}_perm"] = perm
            meta["host_gather"] = hg_idx
            meta["host_gather_len"] = int(len(flat_vals))
            hg_idx += 1
        col_meta[str(i)] = meta
    np.savez(os.path.join(d, "arrays.npz"),
             **{k: v for k, v in arrays.items() if v.dtype != object})
    obj_arrays = {k: v for k, v in arrays.items() if v.dtype == object}
    if obj_arrays:
        pd.DataFrame({k: pd.Series(v) for k, v in obj_arrays.items()}) \
            .to_parquet(os.path.join(d, "objects.parquet"))

    frame.layout.key_frame.to_parquet(os.path.join(d, "keys.parquet"))
    if frame._source_df is not None and frame.host_cols:
        frame._source_df[sorted(set(frame.host_cols.values()))].to_parquet(
            os.path.join(d, "host.parquet")
        )
    audits = [(msg, int(np.asarray(cnt))) for msg, cnt in frame.audits]
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({
            "format_version": FORMAT_VERSION,
            "kind": "dist",
            "ts_col": frame.ts_col,
            "partition_cols": frame.partitionCols,
            "ts_dtype": str(frame._ts_dtype),
            "host_cols": frame.host_cols,
            "halo_fraction": frame.halo_fraction,
            "resampled": frame.resampled,
            "seq_col": frame.seq_col,
            "resample_freq": frame._resample_freq,
            "audits": audits,
            "columns": col_meta,
            "n_cols": len(names),
        }, f, indent=2)


def _load_dist(d: str, man: dict, mesh, series_axis: str,
               time_axis: Optional[str]):
    from jax.sharding import NamedSharding

    from tempo_tpu import packing
    from tempo_tpu.dist import DistCol, DistributedTSDF, _pad_k, _spec

    z = np.load(os.path.join(d, "arrays.npz"), allow_pickle=False)
    obj_path = os.path.join(d, "objects.parquet")
    objs = pd.read_parquet(obj_path) if os.path.exists(obj_path) else None
    key_frame = pd.read_parquet(os.path.join(d, "keys.parquet"))
    host_path = os.path.join(d, "host.parquet")
    source_df = pd.read_parquet(host_path) if os.path.exists(host_path) \
        else None

    layout = packing.FlatLayout(
        key_ids=z["layout_key_ids"], ts_ns=z["layout_ts_ns"],
        order=z["layout_order"], starts=z["layout_starts"],
        key_frame=key_frame,
    )

    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    K, L = (int(s) for s in z["ts"].shape)
    # a finer time axis than the saver's needs more row padding; pads
    # carry TS_PAD / invalid and are inert in every kernel
    mult = 8 * n_t
    L_new = -(-L // mult) * mult
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))

    def put2(a, fill):
        if L_new != L:
            pad = np.full(a.shape[:-1] + (L_new - L,), fill, dtype=a.dtype)
            a = np.concatenate([a, pad], axis=-1)
        return jax.device_put(_pad_k(a, K_dev, fill), sharding)

    ts_d = put2(z["ts"], packing.TS_PAD)
    mask_d = put2(z["mask"], False)
    cols = {}
    for i in range(man["n_cols"]):
        meta = man["columns"][str(i)]
        hg = None
        if "host_gather" in meta:
            j = meta["host_gather"]
            key = f"hg_{j}_vals"
            vals = (objs[key].to_numpy(object) if objs is not None
                    and key in objs.columns else z[key])
            vals = vals[: meta["host_gather_len"]]
            hg = (vals, z[f"hg_{j}_starts"], z[f"hg_{j}_perm"])
        v = z[f"col_{i}_values"]
        fill = np.nan if np.issubdtype(v.dtype, np.floating) else 0
        cols[meta["name"]] = DistCol(
            put2(v, fill), put2(z[f"col_{i}_valid"], False),
            int64=meta["int64"],
            ts_chunk=tuple(meta["ts_chunk"]) if meta["ts_chunk"] else None,
            host_gather=hg,
        )
    audits = [(msg, np.int64(cnt)) for msg, cnt in man["audits"]]
    # +inf pad matches from_tsdf's seq packing (padding must sort after
    # real rows; the ts key dominates at pad slots either way).  Null
    # seq values from pre-NULLS-FIRST checkpoints were packed as NaN —
    # normalise to the -inf encoding so restored frames join like fresh
    # ones (idempotent: current-format planes carry no NaN).
    seq_d = (put2(np.where(np.isnan(z["seq"]), -np.inf, z["seq"]), np.inf)
             if "seq" in z.files else None)
    return DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout,
        man["ts_col"], man["partition_cols"], np.dtype(man["ts_dtype"]),
        source_df, man["host_cols"], man["halo_fraction"],
        audits=audits, resampled=man["resampled"],
        seq=seq_d, seq_col=man.get("seq_col", ""),
        resample_freq=man.get("resample_freq"),
    )
