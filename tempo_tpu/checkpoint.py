"""Checkpoint / resume for distributed pipelines.

The reference has no checkpoint mechanism of its own — its
transformations are stateless Spark plans and recovery is task re-run
(SURVEY.md §5 "Checkpoint / resume: none").  tempo-tpu's distributed
frames DO carry state worth snapshotting: the packed, sharded device
arrays of a :class:`~tempo_tpu.dist.DistributedTSDF` mid-pipeline (a
chain may have executed several expensive device ops since ingest).
This module adds the elasticity story the rebuild was asked to
first-class (driver spec "failure detection, checkpoint/resume"):

* :func:`save` — fetch the frame's device state (one stacked transfer,
  same path as ``collect``) and write a self-describing directory:
  ``manifest.json`` + ``arrays.npz`` (+ ``host.parquet`` for
  host-resident columns and the key frame).
* :func:`load` — restore a device-resident ``DistributedTSDF`` onto a
  caller-provided mesh (the mesh may have a different device count than
  the one that saved — re-placement is just a new NamedSharding).

Checkpoints are atomic (write to ``<dir>.tmp`` then rename) so a crash
mid-save never corrupts the previous checkpoint, and versioned so
future layout changes can refuse gracefully.

Hardening (the failure-detection half of the driver spec, with
:mod:`tempo_tpu.resilience`):

* every npz array and parquet file carries a CRC-32 checksum in
  ``manifest.json`` (``checksum_algo: "crc32"``); :func:`load` verifies
  them and raises :class:`CheckpointError` naming the corrupt artifact;
* missing / newer-format checkpoints raise :class:`CheckpointError`
  naming the path and found/expected ``FORMAT_VERSION`` instead of raw
  ``FileNotFoundError``/``KeyError``;
* stale ``<dir>.tmp`` crash residue is detected and cleaned on load;
* :func:`list_steps` / :func:`latest` / :func:`prune` manage the
  ``step_NNNNN`` checkpoint families written by
  :func:`tempo_tpu.resilience.run_resumable` (keep-last-K retention);
* host-side reads/writes ride the transient-IO retry policy.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import re
import shutil
import zipfile
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

import jax

from tempo_tpu import resilience
from tempo_tpu.resilience import CheckpointError, FailureKind

logger = logging.getLogger(__name__)

FORMAT_VERSION = 2

_IO_RETRY = resilience.retrying(resilience.DEFAULT_IO_POLICY,
                                label="checkpoint-io")


# ----------------------------------------------------------------------
# Checksummed, retrying IO primitives
# ----------------------------------------------------------------------

def _array_crc(arr: np.ndarray) -> int:
    """CRC-32 of an array's raw bytes (dtype-agnostic, no copy)."""
    a = np.ascontiguousarray(arr)
    return zlib.crc32(a.reshape(-1).view(np.uint8)) & 0xFFFFFFFF


def _file_crc(path: str, chunk: int = 1 << 20) -> int:
    c = 0
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            c = zlib.crc32(b, c)
    return c & 0xFFFFFFFF


#: Public faces of the checksum primitives: the ingest progress
#: manifests (io/ingest.py) and the cohort snapshot chain verify with
#: the SAME CRCs this module writes — one checksum discipline, not
#: per-module reimplementations.
array_crc = _array_crc
file_crc = _file_crc


@_IO_RETRY
def _read_parquet(path: str) -> pd.DataFrame:
    return pd.read_parquet(path)


@_IO_RETRY
def _write_parquet(df: pd.DataFrame, path: str) -> None:
    df.to_parquet(path)


@_IO_RETRY
def _savez(path: str, arrays: Dict[str, np.ndarray]) -> Dict[str, int]:
    """Write an npz and return the per-array CRCs for the manifest."""
    np.savez(path, **arrays)
    return {k: _array_crc(v) for k, v in arrays.items()}


@_IO_RETRY
def _load_npz(path: str, checksums: Optional[Dict[str, int]] = None,
              verify: bool = True) -> Dict[str, np.ndarray]:
    """Eagerly read every array of an npz, naming the failing array on
    container corruption and checking manifest CRCs when available."""
    try:
        z = np.load(path, allow_pickle=False)
    except FileNotFoundError as e:
        raise CheckpointError(
            f"checkpoint file {path!r} is missing (incomplete save?)"
        ) from e
    except (zipfile.BadZipFile, OSError, ValueError, EOFError) as e:
        if resilience.classify(e) is FailureKind.TRANSIENT_IO:
            raise   # stays retryable under the IO policy
        raise CheckpointError(
            f"checkpoint file {path!r} is unreadable: {e}"
        ) from e
    out: Dict[str, np.ndarray] = {}
    with z:
        for name in z.files:
            try:
                arr = z[name]
            except Exception as e:
                if resilience.classify(e) is FailureKind.TRANSIENT_IO:
                    raise
                raise CheckpointError(
                    f"checkpoint array {name!r} in {path!r} is "
                    f"unreadable (corrupt container): {e}"
                ) from e
            if verify and checksums is not None and name in checksums:
                got = _array_crc(arr)
                want = int(checksums[name])
                if got != want:
                    raise CheckpointError(
                        f"checksum mismatch for array {name!r} in "
                        f"{path!r}: manifest crc32 {want}, computed {got}"
                    )
            out[name] = arr
    return out


def _write_manifest(d: str, man: dict) -> None:
    """Finalize a manifest: stamp the format version and file-level
    CRCs for every parquet artifact already written into ``d``."""
    man.setdefault("format_version", FORMAT_VERSION)
    man["checksum_algo"] = "crc32"
    man["file_checksums"] = {
        os.path.basename(p): _file_crc(p)
        for p in sorted(glob.glob(os.path.join(d, "*.parquet")))
    }
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump(man, f, indent=2)


def _manifest(path: str) -> dict:
    """Read + validate a manifest, raising :class:`CheckpointError`
    (never raw FileNotFoundError/KeyError) on every failure mode."""
    mp = os.path.join(path, "manifest.json")
    if not os.path.exists(mp):
        raise CheckpointError(
            f"no checkpoint at {path!r}: manifest.json not found",
            kind=FailureKind.PERMANENT,
        )
    try:
        with open(mp) as f:
            man = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(
            f"checkpoint manifest {mp!r} is corrupt: {e}"
        ) from e
    fv = man.get("format_version") if isinstance(man, dict) else None
    # bool is an int subclass but never a valid version
    if not isinstance(fv, int) or isinstance(fv, bool) \
            or "kind" not in man:
        raise CheckpointError(
            f"checkpoint manifest {mp!r} is missing required fields "
            f"(integer format_version / kind) — truncated or foreign "
            f"file?"
        )
    if fv > FORMAT_VERSION:
        raise CheckpointError(
            f"checkpoint at {path!r} has format_version {fv}, newer than "
            f"this library understands (expected <= {FORMAT_VERSION}); "
            f"upgrade tempo-tpu to load it",
            kind=FailureKind.PERMANENT,
        )
    return man


def _clean_stale_tmp(path: str) -> None:
    """Remove ``<path>.tmp`` crash residue from a hard-killed save.

    Only manifest-less residue is deleted: a tmp WITH a manifest means
    the save finished writing and died before the rename swap — it is a
    complete newest checkpoint (possibly the only one), so a read
    operation must never destroy it; it is left in place with a
    warning for the operator.  Loading concurrently with an in-flight
    save is not supported (same as before this hardening)."""
    tmp = path + ".tmp"
    if not os.path.isdir(tmp) or jax.process_index() != 0:
        return
    if os.path.exists(os.path.join(tmp, "manifest.json")):
        logger.warning(
            "checkpoint %s: %s holds a fully-written checkpoint from a "
            "save killed before its final rename — leaving it on disk "
            "(rename it to recover that state)", path, tmp)
        return
    logger.warning(
        "checkpoint %s: removing stale crash residue %s", path, tmp)
    shutil.rmtree(tmp, ignore_errors=True)


def save(frame, path: str, sharded: bool = False,
         meta: Optional[dict] = None) -> None:
    """Snapshot a :class:`DistributedTSDF` (or host :class:`TSDF`) to
    ``path`` (a directory).  Atomic: the directory appears fully
    written or not at all.

    ``meta`` (JSON-serializable) rides in the manifest under ``"meta"``
    — the step-checkpoint writers (:func:`tempo_tpu.resilience.
    run_resumable`, the plan executor's barrier nodes) stamp the
    pipeline/plan signature and the predecessor-manifest CRC there, and
    :func:`resolve_step` refuses foreign state by name on resume.

    ``sharded=True`` (distributed frames): every process writes ONLY
    its addressable device shards to its own ``shard_p<i>.npz`` — no
    host ever materialises another host's data, the multi-host DCN
    story the dense format (one stacked global fetch) cannot provide.
    Resume works on any process count and mesh shape: ``load``
    reassembles each process's slice from whichever shard files
    overlap it.  Process 0 writes the manifest and host-side state;
    multi-process runs synchronise around the final rename."""
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.frame import TSDF

    pid = jax.process_index()
    # fully-local validation happens BEFORE the tmp directory and the
    # first barrier exist: every process raises the same error with
    # nothing on disk to clean up (ADVICE r3 — the old order left
    # ``path.tmp`` behind on every such failed save)
    if isinstance(frame, DistributedTSDF):
        if not sharded and jax.process_count() > 1:
            raise ValueError(
                "multi-process checkpoints must use sharded=True "
                "(the dense format fetches the global array)"
            )
    elif not isinstance(frame, TSDF):
        raise TypeError(f"cannot checkpoint {type(frame)}")
    tmp = path + ".tmp"
    bak = path + ".bak"
    if pid == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("tempo_ckpt_dir")
    try:
        if isinstance(frame, DistributedTSDF):
            if sharded:
                _save_dist_sharded(frame, tmp, meta)
            elif jax.process_count() > 1:
                raise ValueError(
                    "multi-process checkpoints must use sharded=True "
                    "(the dense format fetches the global array)"
                )
            else:
                _save_dist(frame, tmp, meta)
        elif isinstance(frame, TSDF):
            if pid == 0:     # host frames are process-replicated state
                _save_host(frame, tmp, meta)
        else:
            raise TypeError(f"cannot checkpoint {type(frame)}")
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tempo_ckpt_written")
        if pid == 0:
            # three-step swap: at every crash point either ``path`` or
            # ``path.bak`` holds a complete previous/new checkpoint
            # (load() falls back to .bak), so the guarantee survives a
            # crash between the renames
            if os.path.exists(bak):
                shutil.rmtree(bak)
            if os.path.exists(path):
                os.replace(path, bak)
            os.replace(tmp, path)
            shutil.rmtree(bak, ignore_errors=True)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tempo_ckpt_swapped")
    except BaseException:
        # single-process: clean up.  Multi-process: leave ``tmp`` in
        # place (peers may still be writing into it; no swap happened,
        # so the previous checkpoint is intact) and re-raise — peers
        # blocked in the next barrier rely on the distributed runtime's
        # failure detection, the same contract as any collective.
        if pid == 0 and jax.process_count() == 1:
            shutil.rmtree(tmp, ignore_errors=True)
        raise


def load(path: str, mesh=None, series_axis: str = "series",
         time_axis: Optional[str] = None, verify: bool = True):
    """Restore a checkpoint.  Distributed checkpoints need a ``mesh``
    (any device count — resume elsewhere is a re-placement); host
    checkpoints ignore it.

    ``verify=True`` (default) checks every artifact against the
    manifest's CRC-32 checksums and raises :class:`CheckpointError`
    naming the corrupt array/file; corruption is never silently
    restored.  Stale ``<path>.tmp`` crash residue is cleaned."""
    _clean_stale_tmp(path)
    if not os.path.exists(os.path.join(path, "manifest.json")) \
            and os.path.exists(os.path.join(path + ".bak", "manifest.json")):
        path = path + ".bak"   # crash mid-swap: previous checkpoint
    man = _manifest(path)
    if verify:
        _verify_file_checksums(path, man)
    if man["kind"] == "stream_state":
        raise CheckpointError(
            f"{path!r} holds a serving StreamState snapshot, not a "
            f"frame: restore it with checkpoint.load_state or "
            f"tempo_tpu.serve.StreamingTSDF.resume",
            kind=FailureKind.PERMANENT,
        )
    if man["kind"] == "cohort_state":
        raise CheckpointError(
            f"{path!r} holds a serving cohort snapshot, not a frame: "
            f"restore it with load_state(kind='cohort_state') or "
            f"tempo_tpu.serve.StreamCohort.resume",
            kind=FailureKind.PERMANENT,
        )
    if man["kind"] == "cohort_member":
        raise CheckpointError(
            f"{path!r} holds ONE spilled cohort member's slot state "
            f"(the StreamCohort LRU spill tier), not a frame: it is "
            f"faulted back in by its own cohort on the member's next "
            f"tick, or inspect it with "
            f"load_state(kind='cohort_member')",
            kind=FailureKind.PERMANENT,
        )
    if man["kind"] == "host":
        return _load_host(path, man)
    if mesh is None:
        raise ValueError("distributed checkpoint needs a mesh to resume on")
    if man["kind"] == "dist_sharded":
        return _load_dist_sharded(path, man, mesh, series_axis, time_axis,
                                  verify=verify)
    return _load_dist(path, man, mesh, series_axis, time_axis, verify=verify)


def _verify_file_checksums(path: str, man: dict) -> None:
    for fname, want in (man.get("file_checksums") or {}).items():
        fp = os.path.join(path, fname)
        if not os.path.exists(fp):
            raise CheckpointError(
                f"checkpoint file {fname!r} recorded in the manifest is "
                f"missing from {path!r}"
            )
        got = _IO_RETRY(_file_crc)(fp)
        if got != int(want):
            raise CheckpointError(
                f"checksum mismatch for file {fname!r} in {path!r}: "
                f"manifest crc32 {want}, computed {got}"
            )


def _npz_checksums(man: dict, npz_name: str) -> Optional[Dict[str, int]]:
    sums = man.get("array_checksums") or {}
    return sums.get(npz_name)


# ----------------------------------------------------------------------
# Raw-array state snapshots (the serving engine's StreamState)
# ----------------------------------------------------------------------

def save_state(arrays: Dict[str, np.ndarray], path: str,
               meta: Optional[dict] = None,
               kind: str = "stream_state") -> None:
    """Atomic, CRC'd snapshot of a flat ``name -> array`` dict — the
    durability primitive behind ``StreamingTSDF.snapshot`` (kind
    ``"stream_state"``, the default) and ``StreamCohort.snapshot``
    (kind ``"cohort_state"``: ONE artifact for the whole cohort).
    Same guarantees as :func:`save`: the directory appears fully
    written or not at all (three-step swap, ``.bak`` fallback), every
    array CRC-32 is recorded in the manifest and verified on load, and
    snapshots written under a ``step_NNNNN`` family compose with
    :func:`list_steps` / :func:`latest` / :func:`prune` (keep-last-K).
    ``meta`` rides in the manifest (JSON-serializable only).
    Single-process: serving streams are single-writer by contract."""
    tmp = path + ".tmp"
    bak = path + ".bak"
    if os.path.exists(tmp):
        shutil.rmtree(tmp)
    os.makedirs(tmp)
    try:
        host = {k: np.asarray(v) for k, v in arrays.items()}
        sums = _savez(os.path.join(tmp, "state.npz"), host)
        man = {
            "kind": str(kind),
            "array_checksums": {"state.npz": sums},
            "meta": meta or {},
        }
        _write_manifest(tmp, man)
        if os.path.exists(bak):
            shutil.rmtree(bak)
        if os.path.exists(path):
            os.replace(path, bak)
        os.replace(tmp, path)
        shutil.rmtree(bak, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_state(path: str, verify: bool = True,
               kind: str = "stream_state"):
    """Restore a :func:`save_state` snapshot: ``(arrays dict, meta)``.
    ``kind`` names the expected snapshot family (``"stream_state"`` /
    ``"cohort_state"`` / ``"standing_state"``) — a mismatch raises by
    name so a cohort resume can never silently swallow a single-stream
    snapshot (or vice versa).  ``verify=True`` checks every array against the manifest
    CRCs and raises :class:`CheckpointError` naming the corrupt array;
    stale ``.tmp`` residue is cleaned and a crash mid-swap falls back
    to ``.bak`` exactly like :func:`load`."""
    _clean_stale_tmp(path)
    if not os.path.exists(os.path.join(path, "manifest.json")) \
            and os.path.exists(os.path.join(path + ".bak",
                                            "manifest.json")):
        path = path + ".bak"
    man = _manifest(path)
    if man["kind"] != kind:
        raise CheckpointError(
            f"{path!r} is a {man['kind']!r} checkpoint, not a "
            f"{kind!r} snapshot: restore frames with checkpoint.load, "
            f"single streams with load_state(kind='stream_state'), "
            f"cohorts with load_state(kind='cohort_state'), standing "
            f"subscriptions with query.resume_subscription "
            f"(kind='standing_state')")
    arrs = _load_npz(os.path.join(path, "state.npz"),
                     _npz_checksums(man, "state.npz"), verify=verify)
    return dict(arrs), man.get("meta") or {}


# ----------------------------------------------------------------------
# Checkpoint families (run_resumable's step_NNNNN layout)
# ----------------------------------------------------------------------

_STEP_RE = re.compile(r"^step_(\d+)$")


def list_steps(parent: str) -> List[Tuple[int, str]]:
    """``[(step, path)]`` of step checkpoints under ``parent``, newest
    first.  ``*.tmp`` crash residue found along the way is cleaned (the
    swap never happened, so it holds nothing recoverable)."""
    if not os.path.isdir(parent):
        return []
    out: List[Tuple[int, str]] = []
    for name in sorted(os.listdir(parent)):
        full = os.path.join(parent, name)
        if name.endswith(".tmp") and os.path.isdir(full):
            _clean_stale_tmp(full[:-len(".tmp")])
            continue
        m = _STEP_RE.match(name)
        if m and os.path.isdir(full):
            out.append((int(m.group(1)), full))
    out.sort(reverse=True)
    return out


def verify_checkpoint(path: str, verify_arrays: bool = True) -> dict:
    """Validate a checkpoint end to end (manifest, file CRCs, every npz
    array CRC) and return its manifest.  Raises
    :class:`CheckpointError` on the first problem found."""
    man = _manifest(path)
    if not verify_arrays:
        return man
    _verify_file_checksums(path, man)
    for npz_name in sorted(man.get("array_checksums") or {}):
        _load_npz(os.path.join(path, npz_name),
                  _npz_checksums(man, npz_name), verify=True)
    if man["kind"] == "dist_sharded":
        for bp in sorted(glob.glob(os.path.join(path, "blocks_p*.json"))):
            doc = _read_blocks(bp)
            pid = os.path.basename(bp)[len("blocks_p"):-len(".json")]
            _load_npz(os.path.join(path, f"shard_p{pid}.npz"),
                      doc.get("checksums"), verify=True)
    return man


def manifest_crc(path: str) -> int:
    """CRC-32 of a checkpoint's finalized ``manifest.json`` bytes — the
    link value of the chained step manifests (each step records its
    predecessor's manifest CRC; :func:`resolve_step` verifies the link
    on resume, the same scheme the cohort differential snapshots use)."""
    return _IO_RETRY(_file_crc)(os.path.join(path, "manifest.json"))


def read_meta(path: str) -> dict:
    """The caller-supplied ``meta`` dict stamped into a checkpoint's
    manifest at save time (empty for pre-stamping checkpoints)."""
    return _manifest(path).get("meta") or {}


def resolve_step(parent: str, signature: Optional[str] = None,
                 max_step: Optional[int] = None, verify: bool = True,
                 below_step: Optional[int] = None
                 ) -> Optional[Tuple[int, str, dict]]:
    """``(step, path, manifest)`` of the newest step checkpoint under
    ``parent`` that is *intact* (every CRC verifies), *ours*
    (``signature`` matches the stamped ``pipeline_signature``) and
    *chain-consistent* (its recorded predecessor-manifest CRC matches
    the predecessor still on disk).  ``None`` when no usable step
    exists.

    Fallback vs refusal: corruption and broken chain links fall back to
    the next-older candidate (an older intact checkpoint is the
    recovery), but a *signature mismatch* raises
    :class:`CheckpointError` by name — state stamped by a different
    pipeline must never be silently restored into this one (the
    foreign-resume hazard).  Unstamped (pre-signing) checkpoints are
    restored with a warning for compatibility.

    ``verify=False`` skips the per-array CRC pass here (cheap manifest
    checks only) — callers that :func:`load` the result immediately
    get the full verification there, once, and fall back by re-calling
    with ``below_step=<failed step>`` (steps at or above it are
    skipped silently: they were already tried)."""
    for step_no, path in list_steps(parent):
        if below_step is not None and step_no >= below_step:
            continue
        if max_step is not None and step_no > max_step:
            logger.warning(
                "resolve_step: ignoring checkpoint %s beyond the %d-step "
                "pipeline (stale ckpt_dir?)", path, max_step,
            )
            continue
        try:
            man = verify_checkpoint(path, verify_arrays=verify)
        except CheckpointError as e:
            logger.warning(
                "checkpoint %s unusable (%s); trying an older one", path, e)
            continue
        meta = man.get("meta") or {}
        stamped = meta.get("pipeline_signature")
        if signature is not None:
            if stamped is None:
                logger.warning(
                    "checkpoint %s carries no pipeline signature "
                    "(pre-signing format); restoring it unverified", path)
            elif stamped != signature:
                raise CheckpointError(
                    f"checkpoint {path!r} was written by a DIFFERENT "
                    f"pipeline: stamped signature {stamped!r} != submitted "
                    f"{signature!r} — refusing to restore foreign state "
                    f"(point ckpt_dir at this pipeline's own directory, "
                    f"or clear it to recompute from scratch)",
                    kind=FailureKind.PERMANENT,
                )
        prev_step = meta.get("prev_step")
        prev_crc = meta.get("prev_manifest_crc")
        if prev_step is not None and prev_crc is not None:
            prev_path = os.path.join(parent, f"step_{int(prev_step):05d}")
            if os.path.exists(os.path.join(prev_path, "manifest.json")) \
                    and manifest_crc(prev_path) != int(prev_crc):
                logger.warning(
                    "checkpoint %s unusable (chained predecessor step %s "
                    "manifest CRC mismatch — rewritten under it?); "
                    "falling back to an older one", path, prev_step)
                continue
        return step_no, path, man
    return None


def latest(parent: str, verify: bool = True) -> Optional[str]:
    """Path of the newest *intact* step checkpoint under ``parent``
    (``None`` when there is none).  Corrupt or truncated candidates are
    skipped with a warning — resume falls back to the previous one."""
    hit = resolve_step(parent, verify=verify)
    return hit[1] if hit is not None else None


def prune(parent: str, keep_last: int = 2) -> None:
    """Keep-last-K retention for a step-checkpoint family."""
    if jax.process_index() != 0:
        return
    for _, path in list_steps(parent)[max(keep_last, 1):]:
        logger.info("pruning old checkpoint %s (keep_last=%d)",
                    path, keep_last)
        shutil.rmtree(path, ignore_errors=True)
        shutil.rmtree(path + ".bak", ignore_errors=True)


# ----------------------------------------------------------------------
# host TSDF
# ----------------------------------------------------------------------

def _save_host(tsdf, d: str, meta: Optional[dict] = None) -> None:
    _write_parquet(tsdf.df, os.path.join(d, "host.parquet"))
    _write_manifest(d, {
        "kind": "host",
        "ts_col": tsdf.ts_col,
        "partition_cols": tsdf.partitionCols,
        "sequence_col": tsdf.sequence_col or None,
        "meta": meta or {},
    })


def _load_host(d: str, man: dict):
    from tempo_tpu.frame import TSDF

    df = _read_parquet(os.path.join(d, "host.parquet"))
    return TSDF(df, man["ts_col"], man["partition_cols"],
                man.get("sequence_col"))


# ----------------------------------------------------------------------
# DistributedTSDF
# ----------------------------------------------------------------------

def _save_dist(frame, d: str, meta: Optional[dict] = None) -> None:
    import jax.numpy as jnp

    names = list(frame.cols)
    # ONE stacked fetch for all column planes (collect()'s transfer
    # discipline: values + valids ride a single [2C, K, L] transfer),
    # plus ts/mask
    arrays = {
        "ts": np.asarray(frame.ts),
        "mask": np.asarray(frame.mask),
        "layout_ts_ns": frame.layout.ts_ns,
        "layout_starts": frame.layout.starts,
        "layout_key_ids": frame.layout.key_ids,
        "layout_order": frame.layout.order,
    }
    if frame.seq is not None:
        arrays["seq"] = np.asarray(frame.seq)
    if names:
        cdt = frame.cols[names[0]].values.dtype
        stacked = np.asarray(jnp.stack(
            [frame.cols[c].values.astype(cdt) for c in names]
            + [frame.cols[c].valid.astype(cdt) for c in names]
        ))
        val_block, ok_block = stacked[: len(names)], stacked[len(names):]
    col_meta = {}
    hg_idx = 0
    for i, c in enumerate(names):
        col = frame.cols[c]
        arrays[f"col_{i}_values"] = val_block[i]
        arrays[f"col_{i}_valid"] = ok_block[i] > 0.5
        cmeta = {"name": c, "int64": col.int64, "ts_chunk": col.ts_chunk}
        if col.host_gather is not None:
            flat_vals, r_starts, perm = col.host_gather
            arrays[f"hg_{hg_idx}_vals"] = np.asarray(flat_vals, dtype=object) \
                if flat_vals.dtype == object else flat_vals
            arrays[f"hg_{hg_idx}_starts"] = r_starts
            arrays[f"hg_{hg_idx}_perm"] = perm
            cmeta["host_gather"] = hg_idx
            cmeta["host_gather_len"] = int(len(flat_vals))
            hg_idx += 1
        col_meta[str(i)] = cmeta
    crcs = _savez(os.path.join(d, "arrays.npz"),
                  {k: v for k, v in arrays.items() if v.dtype != object})
    _write_host_side(frame, d,
                     {k: v for k, v in arrays.items()
                      if v.dtype == object})
    man = _dist_manifest(frame)
    man.update({"kind": "dist", "columns": col_meta,
                "n_cols": len(names),
                "array_checksums": {"arrays.npz": crcs},
                "meta": meta or {}})
    _write_manifest(d, man)


def _write_host_side(frame, d: str, obj_arrays: dict) -> None:
    """Host-resident state both distributed formats share: object
    planes, the key frame, and the host-column source."""
    objs = {k: v for k, v in obj_arrays.items() if v.dtype == object}
    if objs:
        _write_parquet(
            pd.DataFrame({k: pd.Series(v) for k, v in objs.items()}),
            os.path.join(d, "objects.parquet"))
    _write_parquet(frame.layout.key_frame, os.path.join(d, "keys.parquet"))
    if frame._source_df is not None and frame.host_cols:
        _write_parquet(
            frame._source_df[sorted(set(frame.host_cols.values()))],
            os.path.join(d, "host.parquet"))


def _read_host_gather(meta: dict, z, objs):
    """Reconstruct a column's host_gather triple from saved arrays."""
    if "host_gather" not in meta:
        return None
    j = meta["host_gather"]
    key = f"hg_{j}_vals"
    vals = (objs[key].to_numpy(object) if objs is not None
            and key in objs.columns else z[key])
    return (vals[: meta["host_gather_len"]], z[f"hg_{j}_starts"],
            z[f"hg_{j}_perm"])


def _dist_manifest(frame) -> dict:
    """Shared manifest payload of both distributed formats."""
    return {
        "format_version": FORMAT_VERSION,
        "ts_col": frame.ts_col,
        "partition_cols": frame.partitionCols,
        "ts_dtype": str(frame._ts_dtype),
        "host_cols": frame.host_cols,
        "halo_fraction": frame.halo_fraction,
        "resampled": frame.resampled,
        "seq_col": frame.seq_col,
        "resample_freq": frame._resample_freq,
        "audits": [(msg, int(np.asarray(cnt)))
                   for msg, cnt in frame.audits],
    }


def _save_dist_sharded(frame, d: str, meta: Optional[dict] = None) -> None:
    """Per-process shard files: each device's addressable blocks of
    every plane, written by the process that holds them."""
    pid = jax.process_index()
    names = list(frame.cols)
    planes = {"ts": frame.ts, "mask": frame.mask}
    if frame.seq is not None:
        planes["seq"] = frame.seq
    col_meta = {}
    hg_arrays = {}
    hg_idx = 0
    for i, c in enumerate(names):
        col = frame.cols[c]
        planes[f"col_{i}_values"] = col.values
        planes[f"col_{i}_valid"] = col.valid
        cmeta = {"name": c, "int64": col.int64, "ts_chunk": col.ts_chunk}
        if col.host_gather is not None:
            flat_vals, r_starts, perm = col.host_gather
            hg_arrays[f"hg_{hg_idx}_vals"] = flat_vals
            hg_arrays[f"hg_{hg_idx}_starts"] = r_starts
            hg_arrays[f"hg_{hg_idx}_perm"] = perm
            cmeta["host_gather"] = hg_idx
            cmeta["host_gather_len"] = int(len(flat_vals))
            hg_idx += 1
        col_meta[str(i)] = cmeta

    local = {}
    blocks = []
    for name, arr in planes.items():
        for j, sh in enumerate(arr.addressable_shards):
            r, c = sh.index[-2], sh.index[-1]
            blocks.append({
                "plane": name, "key": f"{name}_b{j}",
                "rows": [int(r.start or 0),
                         int(r.stop if r.stop is not None
                             else arr.shape[-2])],
                "lanes": [int(c.start or 0),
                          int(c.stop if c.stop is not None
                              else arr.shape[-1])],
            })
            local[f"{name}_b{j}"] = np.asarray(sh.data)
    shard_crcs = _savez(os.path.join(d, f"shard_p{pid}.npz"), local)
    with open(os.path.join(d, f"blocks_p{pid}.json"), "w") as f:
        json.dump({"blocks": blocks, "checksums": shard_crcs}, f)

    if pid == 0:
        host_arrays = dict(
            layout_ts_ns=frame.layout.ts_ns,
            layout_starts=frame.layout.starts,
            layout_key_ids=frame.layout.key_ids,
            layout_order=frame.layout.order,
            **{k: v for k, v in hg_arrays.items() if v.dtype != object},
        )
        host_crcs = _savez(os.path.join(d, "host_arrays.npz"), host_arrays)
        _write_host_side(frame, d, hg_arrays)
        man = _dist_manifest(frame)
        man.update({
            "kind": "dist_sharded",
            "columns": col_meta,
            "n_cols": len(names),
            "n_processes": jax.process_count(),
            "shape": [int(s) for s in frame.ts.shape],
            "has_seq": frame.seq is not None,
            "array_checksums": {"host_arrays.npz": host_crcs},
            "meta": meta or {},
        })
        _write_manifest(d, man)


def _assemble_plane(all_blocks, name: str, shape, lo: int,
                    hi: int, fill, dtype, shard_files):
    """Rows [lo, hi) of a saved plane, stitched from whichever shard
    files overlap them (every lane; the process-major layout keeps a
    process's lanes local, parallel/multihost.py)."""
    K, L = shape
    out = np.full((hi - lo, L), fill, dtype=dtype)
    for pid, blocks in all_blocks.items():
        for b in blocks:
            if b["plane"] != name:
                continue
            r0, r1 = b["rows"]
            if r1 <= lo or r0 >= hi:
                continue
            c0, c1 = b["lanes"]
            data = shard_files[pid][b["key"]]
            s0, s1 = max(r0, lo), min(r1, hi)
            out[s0 - lo: s1 - lo, c0:c1] = data[s0 - r0: s1 - r0]
    return out


def _read_blocks(bp: str) -> dict:
    """Blocks sidecar in v2 form ({"blocks": ..., "checksums": ...});
    v1 files were a bare list with no checksums."""
    try:
        with open(bp) as f:
            doc = json.load(f)
    except (json.JSONDecodeError, UnicodeDecodeError) as e:
        raise CheckpointError(
            f"checkpoint shard index {bp!r} is corrupt: {e}"
        ) from e
    if isinstance(doc, list):
        return {"blocks": doc, "checksums": None}
    return doc


def _load_dist_sharded(d: str, man: dict, mesh, series_axis: str,
                       time_axis: Optional[str], verify: bool = True):
    from jax.sharding import NamedSharding

    from tempo_tpu import packing
    from tempo_tpu.dist import DistCol, DistributedTSDF, _spec
    from tempo_tpu.parallel import multihost as mh

    z = _load_npz(os.path.join(d, "host_arrays.npz"),
                  _npz_checksums(man, "host_arrays.npz"), verify=verify)
    obj_path = os.path.join(d, "objects.parquet")
    objs = _read_parquet(obj_path) if os.path.exists(obj_path) else None
    key_frame = _read_parquet(os.path.join(d, "keys.parquet"))
    host_path = os.path.join(d, "host.parquet")
    source_df = _read_parquet(host_path) if os.path.exists(host_path) \
        else None
    layout = packing.FlatLayout(
        key_ids=z["layout_key_ids"], ts_ns=z["layout_ts_ns"],
        order=z["layout_order"], starts=z["layout_starts"],
        key_frame=key_frame,
    )

    all_blocks = {}
    shard_files = {}
    for bp in sorted(glob.glob(os.path.join(d, "blocks_p*.json"))):
        pid = int(os.path.basename(bp)[len("blocks_p"):-len(".json")])
        doc = _read_blocks(bp)
        all_blocks[pid] = doc["blocks"]
        shard_files[pid] = _load_npz(
            os.path.join(d, f"shard_p{pid}.npz"),
            doc.get("checksums"), verify=verify,
        )
    if len(all_blocks) != man["n_processes"]:
        raise ValueError(
            f"sharded checkpoint incomplete: manifest records "
            f"{man['n_processes']} writer processes but "
            f"{len(all_blocks)} shard file(s) are present — silently "
            f"filling the gap would fabricate empty series"
        )

    K, L = man["shape"]
    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    mult = 8 * n_t
    L_new = -(-L // mult) * mult
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))
    lo, hi = mh.series_range_for_process(
        jax.process_index(),
        mh.mesh_shard_process_ids(mesh, series_axis), K_dev,
    )

    def put(name, fill, dtype):
        block = np.full((hi - lo, L_new), fill, dtype=dtype)
        src_hi = min(hi, K)
        if src_hi > lo:
            block[: src_hi - lo, :L] = _assemble_plane(
                all_blocks, name, (K, L), lo, src_hi, fill, dtype,
                shard_files,
            )
        if jax.process_count() == 1:
            return jax.device_put(block, sharding)
        return jax.make_array_from_process_local_data(
            sharding, block, (K_dev, L_new)
        )

    ts_d = put("ts", packing.TS_PAD, np.int64)
    mask_d = put("mask", False, bool)
    cols = {}
    for i in range(man["n_cols"]):
        meta = man["columns"][str(i)]
        hg = _read_host_gather(meta, z, objs)
        vdt = _plane_dtype(all_blocks, shard_files,
                           f"col_{i}_values")
        fill = np.nan if np.issubdtype(vdt, np.floating) else 0
        cols[meta["name"]] = DistCol(
            put(f"col_{i}_values", fill, vdt),
            put(f"col_{i}_valid", False, bool),
            int64=meta["int64"],
            ts_chunk=tuple(meta["ts_chunk"]) if meta["ts_chunk"] else None,
            host_gather=hg,
        )
    seq_d = None
    if man.get("has_seq"):
        sdt = _plane_dtype(all_blocks, shard_files, "seq")
        seq_d = put("seq", np.inf, sdt)
    audits = [(msg, np.int64(cnt)) for msg, cnt in man["audits"]]
    return DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout,
        man["ts_col"], man["partition_cols"], np.dtype(man["ts_dtype"]),
        source_df, man["host_cols"], man["halo_fraction"],
        audits=audits, resampled=man["resampled"],
        seq=seq_d, seq_col=man.get("seq_col", ""),
        resample_freq=man.get("resample_freq"),
    )


def _plane_dtype(all_blocks, shard_files, name):
    for pid, blocks in all_blocks.items():
        for b in blocks:
            if b["plane"] == name:
                return shard_files[pid][b["key"]].dtype
    raise ValueError(f"plane {name!r} missing from every shard file")


def _load_dist(d: str, man: dict, mesh, series_axis: str,
               time_axis: Optional[str], verify: bool = True):
    from jax.sharding import NamedSharding

    from tempo_tpu import packing
    from tempo_tpu.dist import DistCol, DistributedTSDF, _pad_k, _spec

    z = _load_npz(os.path.join(d, "arrays.npz"),
                  _npz_checksums(man, "arrays.npz"), verify=verify)
    obj_path = os.path.join(d, "objects.parquet")
    objs = _read_parquet(obj_path) if os.path.exists(obj_path) else None
    key_frame = _read_parquet(os.path.join(d, "keys.parquet"))
    host_path = os.path.join(d, "host.parquet")
    source_df = _read_parquet(host_path) if os.path.exists(host_path) \
        else None

    layout = packing.FlatLayout(
        key_ids=z["layout_key_ids"], ts_ns=z["layout_ts_ns"],
        order=z["layout_order"], starts=z["layout_starts"],
        key_frame=key_frame,
    )

    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    K, L = (int(s) for s in z["ts"].shape)
    # a finer time axis than the saver's needs more row padding; pads
    # carry TS_PAD / invalid and are inert in every kernel
    mult = 8 * n_t
    L_new = -(-L // mult) * mult
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))

    def put2(a, fill):
        if L_new != L:
            pad = np.full(a.shape[:-1] + (L_new - L,), fill, dtype=a.dtype)
            a = np.concatenate([a, pad], axis=-1)
        return jax.device_put(_pad_k(a, K_dev, fill), sharding)

    ts_d = put2(z["ts"], packing.TS_PAD)
    mask_d = put2(z["mask"], False)
    cols = {}
    for i in range(man["n_cols"]):
        meta = man["columns"][str(i)]
        hg = _read_host_gather(meta, z, objs)
        v = z[f"col_{i}_values"]
        fill = np.nan if np.issubdtype(v.dtype, np.floating) else 0
        cols[meta["name"]] = DistCol(
            put2(v, fill), put2(z[f"col_{i}_valid"], False),
            int64=meta["int64"],
            ts_chunk=tuple(meta["ts_chunk"]) if meta["ts_chunk"] else None,
            host_gather=hg,
        )
    audits = [(msg, np.int64(cnt)) for msg, cnt in man["audits"]]
    # +inf pad matches from_tsdf's seq packing (padding must sort after
    # real rows; the ts key dominates at pad slots either way).  Null
    # seq values from pre-NULLS-FIRST checkpoints were packed as NaN —
    # normalise to the -inf encoding so restored frames join like fresh
    # ones (idempotent: current-format planes carry no NaN).
    seq_d = (put2(np.where(np.isnan(z["seq"]), -np.inf, z["seq"]), np.inf)
             if "seq" in z else None)
    return DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout,
        man["ts_col"], man["partition_cols"], np.dtype(man["ts_dtype"]),
        source_df, man["host_cols"], man["halo_fraction"],
        audits=audits, resampled=man["resampled"],
        seq=seq_d, seq_col=man.get("seq_col", ""),
        resample_freq=man.get("resample_freq"),
    )
