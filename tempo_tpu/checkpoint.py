"""Checkpoint / resume for distributed pipelines.

The reference has no checkpoint mechanism of its own — its
transformations are stateless Spark plans and recovery is task re-run
(SURVEY.md §5 "Checkpoint / resume: none").  tempo-tpu's distributed
frames DO carry state worth snapshotting: the packed, sharded device
arrays of a :class:`~tempo_tpu.dist.DistributedTSDF` mid-pipeline (a
chain may have executed several expensive device ops since ingest).
This module adds the elasticity story the rebuild was asked to
first-class (driver spec "failure detection, checkpoint/resume"):

* :func:`save` — fetch the frame's device state (one stacked transfer,
  same path as ``collect``) and write a self-describing directory:
  ``manifest.json`` + ``arrays.npz`` (+ ``host.parquet`` for
  host-resident columns and the key frame).
* :func:`load` — restore a device-resident ``DistributedTSDF`` onto a
  caller-provided mesh (the mesh may have a different device count than
  the one that saved — re-placement is just a new NamedSharding).

Checkpoints are atomic (write to ``<dir>.tmp`` then rename) so a crash
mid-save never corrupts the previous checkpoint, and versioned so
future layout changes can refuse gracefully.
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Optional

import numpy as np
import pandas as pd

import jax

FORMAT_VERSION = 1


def save(frame, path: str, sharded: bool = False) -> None:
    """Snapshot a :class:`DistributedTSDF` (or host :class:`TSDF`) to
    ``path`` (a directory).  Atomic: the directory appears fully
    written or not at all.

    ``sharded=True`` (distributed frames): every process writes ONLY
    its addressable device shards to its own ``shard_p<i>.npz`` — no
    host ever materialises another host's data, the multi-host DCN
    story the dense format (one stacked global fetch) cannot provide.
    Resume works on any process count and mesh shape: ``load``
    reassembles each process's slice from whichever shard files
    overlap it.  Process 0 writes the manifest and host-side state;
    multi-process runs synchronise around the final rename."""
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.frame import TSDF

    pid = jax.process_index()
    # fully-local validation happens BEFORE the tmp directory and the
    # first barrier exist: every process raises the same error with
    # nothing on disk to clean up (ADVICE r3 — the old order left
    # ``path.tmp`` behind on every such failed save)
    if isinstance(frame, DistributedTSDF):
        if not sharded and jax.process_count() > 1:
            raise ValueError(
                "multi-process checkpoints must use sharded=True "
                "(the dense format fetches the global array)"
            )
    elif not isinstance(frame, TSDF):
        raise TypeError(f"cannot checkpoint {type(frame)}")
    tmp = path + ".tmp"
    bak = path + ".bak"
    if pid == 0:
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("tempo_ckpt_dir")
    try:
        if isinstance(frame, DistributedTSDF):
            if sharded:
                _save_dist_sharded(frame, tmp)
            elif jax.process_count() > 1:
                raise ValueError(
                    "multi-process checkpoints must use sharded=True "
                    "(the dense format fetches the global array)"
                )
            else:
                _save_dist(frame, tmp)
        elif isinstance(frame, TSDF):
            if pid == 0:     # host frames are process-replicated state
                _save_host(frame, tmp)
        else:
            raise TypeError(f"cannot checkpoint {type(frame)}")
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tempo_ckpt_written")
        if pid == 0:
            # three-step swap: at every crash point either ``path`` or
            # ``path.bak`` holds a complete previous/new checkpoint
            # (load() falls back to .bak), so the guarantee survives a
            # crash between the renames
            if os.path.exists(bak):
                shutil.rmtree(bak)
            if os.path.exists(path):
                os.replace(path, bak)
            os.replace(tmp, path)
            shutil.rmtree(bak, ignore_errors=True)
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices("tempo_ckpt_swapped")
    except BaseException:
        # single-process: clean up.  Multi-process: leave ``tmp`` in
        # place (peers may still be writing into it; no swap happened,
        # so the previous checkpoint is intact) and re-raise — peers
        # blocked in the next barrier rely on the distributed runtime's
        # failure detection, the same contract as any collective.
        if pid == 0 and jax.process_count() == 1:
            shutil.rmtree(tmp, ignore_errors=True)
        raise


def load(path: str, mesh=None, series_axis: str = "series",
         time_axis: Optional[str] = None):
    """Restore a checkpoint.  Distributed checkpoints need a ``mesh``
    (any device count — resume elsewhere is a re-placement); host
    checkpoints ignore it."""
    if not os.path.exists(os.path.join(path, "manifest.json")) \
            and os.path.exists(os.path.join(path + ".bak", "manifest.json")):
        path = path + ".bak"   # crash mid-swap: previous checkpoint
    with open(os.path.join(path, "manifest.json")) as f:
        man = json.load(f)
    if man["format_version"] > FORMAT_VERSION:
        raise ValueError(
            f"checkpoint format {man['format_version']} is newer than "
            f"this library understands ({FORMAT_VERSION})"
        )
    if man["kind"] == "host":
        return _load_host(path, man)
    if mesh is None:
        raise ValueError("distributed checkpoint needs a mesh to resume on")
    if man["kind"] == "dist_sharded":
        return _load_dist_sharded(path, man, mesh, series_axis, time_axis)
    return _load_dist(path, man, mesh, series_axis, time_axis)


# ----------------------------------------------------------------------
# host TSDF
# ----------------------------------------------------------------------

def _save_host(tsdf, d: str) -> None:
    tsdf.df.to_parquet(os.path.join(d, "host.parquet"))
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({
            "format_version": FORMAT_VERSION,
            "kind": "host",
            "ts_col": tsdf.ts_col,
            "partition_cols": tsdf.partitionCols,
            "sequence_col": tsdf.sequence_col or None,
        }, f, indent=2)


def _load_host(d: str, man: dict):
    from tempo_tpu.frame import TSDF

    df = pd.read_parquet(os.path.join(d, "host.parquet"))
    return TSDF(df, man["ts_col"], man["partition_cols"],
                man.get("sequence_col"))


# ----------------------------------------------------------------------
# DistributedTSDF
# ----------------------------------------------------------------------

def _save_dist(frame, d: str) -> None:
    import jax.numpy as jnp

    names = list(frame.cols)
    # ONE stacked fetch for all column planes (collect()'s transfer
    # discipline: values + valids ride a single [2C, K, L] transfer),
    # plus ts/mask
    arrays = {
        "ts": np.asarray(frame.ts),
        "mask": np.asarray(frame.mask),
        "layout_ts_ns": frame.layout.ts_ns,
        "layout_starts": frame.layout.starts,
        "layout_key_ids": frame.layout.key_ids,
        "layout_order": frame.layout.order,
    }
    if frame.seq is not None:
        arrays["seq"] = np.asarray(frame.seq)
    if names:
        cdt = frame.cols[names[0]].values.dtype
        stacked = np.asarray(jnp.stack(
            [frame.cols[c].values.astype(cdt) for c in names]
            + [frame.cols[c].valid.astype(cdt) for c in names]
        ))
        val_block, ok_block = stacked[: len(names)], stacked[len(names):]
    col_meta = {}
    hg_idx = 0
    for i, c in enumerate(names):
        col = frame.cols[c]
        arrays[f"col_{i}_values"] = val_block[i]
        arrays[f"col_{i}_valid"] = ok_block[i] > 0.5
        meta = {"name": c, "int64": col.int64, "ts_chunk": col.ts_chunk}
        if col.host_gather is not None:
            flat_vals, r_starts, perm = col.host_gather
            arrays[f"hg_{hg_idx}_vals"] = np.asarray(flat_vals, dtype=object) \
                if flat_vals.dtype == object else flat_vals
            arrays[f"hg_{hg_idx}_starts"] = r_starts
            arrays[f"hg_{hg_idx}_perm"] = perm
            meta["host_gather"] = hg_idx
            meta["host_gather_len"] = int(len(flat_vals))
            hg_idx += 1
        col_meta[str(i)] = meta
    np.savez(os.path.join(d, "arrays.npz"),
             **{k: v for k, v in arrays.items() if v.dtype != object})
    _write_host_side(frame, d,
                     {k: v for k, v in arrays.items()
                      if v.dtype == object})
    with open(os.path.join(d, "manifest.json"), "w") as f:
        man = _dist_manifest(frame)
        man.update({"kind": "dist", "columns": col_meta,
                    "n_cols": len(names)})
        json.dump(man, f, indent=2)


def _write_host_side(frame, d: str, obj_arrays: dict) -> None:
    """Host-resident state both distributed formats share: object
    planes, the key frame, and the host-column source."""
    objs = {k: v for k, v in obj_arrays.items() if v.dtype == object}
    if objs:
        pd.DataFrame({k: pd.Series(v) for k, v in objs.items()}) \
            .to_parquet(os.path.join(d, "objects.parquet"))
    frame.layout.key_frame.to_parquet(os.path.join(d, "keys.parquet"))
    if frame._source_df is not None and frame.host_cols:
        frame._source_df[
            sorted(set(frame.host_cols.values()))
        ].to_parquet(os.path.join(d, "host.parquet"))


def _read_host_gather(meta: dict, z, objs):
    """Reconstruct a column's host_gather triple from saved arrays."""
    if "host_gather" not in meta:
        return None
    j = meta["host_gather"]
    key = f"hg_{j}_vals"
    vals = (objs[key].to_numpy(object) if objs is not None
            and key in objs.columns else z[key])
    return (vals[: meta["host_gather_len"]], z[f"hg_{j}_starts"],
            z[f"hg_{j}_perm"])


def _dist_manifest(frame) -> dict:
    """Shared manifest payload of both distributed formats."""
    return {
        "format_version": FORMAT_VERSION,
        "ts_col": frame.ts_col,
        "partition_cols": frame.partitionCols,
        "ts_dtype": str(frame._ts_dtype),
        "host_cols": frame.host_cols,
        "halo_fraction": frame.halo_fraction,
        "resampled": frame.resampled,
        "seq_col": frame.seq_col,
        "resample_freq": frame._resample_freq,
        "audits": [(msg, int(np.asarray(cnt)))
                   for msg, cnt in frame.audits],
    }


def _save_dist_sharded(frame, d: str) -> None:
    """Per-process shard files: each device's addressable blocks of
    every plane, written by the process that holds them."""
    pid = jax.process_index()
    names = list(frame.cols)
    planes = {"ts": frame.ts, "mask": frame.mask}
    if frame.seq is not None:
        planes["seq"] = frame.seq
    col_meta = {}
    hg_arrays = {}
    hg_idx = 0
    for i, c in enumerate(names):
        col = frame.cols[c]
        planes[f"col_{i}_values"] = col.values
        planes[f"col_{i}_valid"] = col.valid
        meta = {"name": c, "int64": col.int64, "ts_chunk": col.ts_chunk}
        if col.host_gather is not None:
            flat_vals, r_starts, perm = col.host_gather
            hg_arrays[f"hg_{hg_idx}_vals"] = flat_vals
            hg_arrays[f"hg_{hg_idx}_starts"] = r_starts
            hg_arrays[f"hg_{hg_idx}_perm"] = perm
            meta["host_gather"] = hg_idx
            meta["host_gather_len"] = int(len(flat_vals))
            hg_idx += 1
        col_meta[str(i)] = meta

    local = {}
    blocks = []
    for name, arr in planes.items():
        for j, sh in enumerate(arr.addressable_shards):
            r, c = sh.index[-2], sh.index[-1]
            blocks.append({
                "plane": name, "key": f"{name}_b{j}",
                "rows": [int(r.start or 0),
                         int(r.stop if r.stop is not None
                             else arr.shape[-2])],
                "lanes": [int(c.start or 0),
                          int(c.stop if c.stop is not None
                              else arr.shape[-1])],
            })
            local[f"{name}_b{j}"] = np.asarray(sh.data)
    np.savez(os.path.join(d, f"shard_p{pid}.npz"), **local)
    with open(os.path.join(d, f"blocks_p{pid}.json"), "w") as f:
        json.dump(blocks, f)

    if pid == 0:
        np.savez(
            os.path.join(d, "host_arrays.npz"),
            layout_ts_ns=frame.layout.ts_ns,
            layout_starts=frame.layout.starts,
            layout_key_ids=frame.layout.key_ids,
            layout_order=frame.layout.order,
            **{k: v for k, v in hg_arrays.items() if v.dtype != object},
        )
        _write_host_side(frame, d, hg_arrays)
        man = _dist_manifest(frame)
        man.update({
            "kind": "dist_sharded",
            "columns": col_meta,
            "n_cols": len(names),
            "n_processes": jax.process_count(),
            "shape": [int(s) for s in frame.ts.shape],
            "has_seq": frame.seq is not None,
        })
        with open(os.path.join(d, "manifest.json"), "w") as f:
            json.dump(man, f, indent=2)


def _assemble_plane(all_blocks, name: str, shape, lo: int,
                    hi: int, fill, dtype, shard_files):
    """Rows [lo, hi) of a saved plane, stitched from whichever shard
    files overlap them (every lane; the process-major layout keeps a
    process's lanes local, parallel/multihost.py)."""
    K, L = shape
    out = np.full((hi - lo, L), fill, dtype=dtype)
    for pid, blocks in all_blocks.items():
        for b in blocks:
            if b["plane"] != name:
                continue
            r0, r1 = b["rows"]
            if r1 <= lo or r0 >= hi:
                continue
            c0, c1 = b["lanes"]
            data = shard_files[pid][b["key"]]
            s0, s1 = max(r0, lo), min(r1, hi)
            out[s0 - lo: s1 - lo, c0:c1] = data[s0 - r0: s1 - r0]
    return out


def _load_dist_sharded(d: str, man: dict, mesh, series_axis: str,
                       time_axis: Optional[str]):
    import glob as _glob

    from jax.sharding import NamedSharding

    from tempo_tpu import packing
    from tempo_tpu.dist import DistCol, DistributedTSDF, _spec
    from tempo_tpu.parallel import multihost as mh

    z = np.load(os.path.join(d, "host_arrays.npz"), allow_pickle=False)
    obj_path = os.path.join(d, "objects.parquet")
    objs = pd.read_parquet(obj_path) if os.path.exists(obj_path) else None
    key_frame = pd.read_parquet(os.path.join(d, "keys.parquet"))
    host_path = os.path.join(d, "host.parquet")
    source_df = pd.read_parquet(host_path) if os.path.exists(host_path) \
        else None
    layout = packing.FlatLayout(
        key_ids=z["layout_key_ids"], ts_ns=z["layout_ts_ns"],
        order=z["layout_order"], starts=z["layout_starts"],
        key_frame=key_frame,
    )

    all_blocks = {}
    shard_files = {}
    for bp in sorted(_glob.glob(os.path.join(d, "blocks_p*.json"))):
        pid = int(os.path.basename(bp)[len("blocks_p"):-len(".json")])
        with open(bp) as f:
            all_blocks[pid] = json.load(f)
        shard_files[pid] = np.load(
            os.path.join(d, f"shard_p{pid}.npz"), allow_pickle=False
        )
    if len(all_blocks) != man["n_processes"]:
        raise ValueError(
            f"sharded checkpoint incomplete: manifest records "
            f"{man['n_processes']} writer processes but "
            f"{len(all_blocks)} shard file(s) are present — silently "
            f"filling the gap would fabricate empty series"
        )

    K, L = man["shape"]
    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    mult = 8 * n_t
    L_new = -(-L // mult) * mult
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))
    lo, hi = mh.series_range_for_process(
        jax.process_index(),
        mh.mesh_shard_process_ids(mesh, series_axis), K_dev,
    )

    def put(name, fill, dtype):
        block = np.full((hi - lo, L_new), fill, dtype=dtype)
        src_hi = min(hi, K)
        if src_hi > lo:
            block[: src_hi - lo, :L] = _assemble_plane(
                all_blocks, name, (K, L), lo, src_hi, fill, dtype,
                shard_files,
            )
        if jax.process_count() == 1:
            return jax.device_put(block, sharding)
        return jax.make_array_from_process_local_data(
            sharding, block, (K_dev, L_new)
        )

    ts_d = put("ts", packing.TS_PAD, np.int64)
    mask_d = put("mask", False, bool)
    cols = {}
    for i in range(man["n_cols"]):
        meta = man["columns"][str(i)]
        hg = _read_host_gather(meta, z, objs)
        vdt = _plane_dtype(all_blocks, shard_files,
                           f"col_{i}_values")
        fill = np.nan if np.issubdtype(vdt, np.floating) else 0
        cols[meta["name"]] = DistCol(
            put(f"col_{i}_values", fill, vdt),
            put(f"col_{i}_valid", False, bool),
            int64=meta["int64"],
            ts_chunk=tuple(meta["ts_chunk"]) if meta["ts_chunk"] else None,
            host_gather=hg,
        )
    seq_d = None
    if man.get("has_seq"):
        sdt = _plane_dtype(all_blocks, shard_files, "seq")
        seq_d = put("seq", np.inf, sdt)
    audits = [(msg, np.int64(cnt)) for msg, cnt in man["audits"]]
    return DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout,
        man["ts_col"], man["partition_cols"], np.dtype(man["ts_dtype"]),
        source_df, man["host_cols"], man["halo_fraction"],
        audits=audits, resampled=man["resampled"],
        seq=seq_d, seq_col=man.get("seq_col", ""),
        resample_freq=man.get("resample_freq"),
    )


def _plane_dtype(all_blocks, shard_files, name):
    for pid, blocks in all_blocks.items():
        for b in blocks:
            if b["plane"] == name:
                return shard_files[pid][b["key"]].dtype
    raise ValueError(f"plane {name!r} missing from every shard file")


def _load_dist(d: str, man: dict, mesh, series_axis: str,
               time_axis: Optional[str]):
    from jax.sharding import NamedSharding

    from tempo_tpu import packing
    from tempo_tpu.dist import DistCol, DistributedTSDF, _pad_k, _spec

    z = np.load(os.path.join(d, "arrays.npz"), allow_pickle=False)
    obj_path = os.path.join(d, "objects.parquet")
    objs = pd.read_parquet(obj_path) if os.path.exists(obj_path) else None
    key_frame = pd.read_parquet(os.path.join(d, "keys.parquet"))
    host_path = os.path.join(d, "host.parquet")
    source_df = pd.read_parquet(host_path) if os.path.exists(host_path) \
        else None

    layout = packing.FlatLayout(
        key_ids=z["layout_key_ids"], ts_ns=z["layout_ts_ns"],
        order=z["layout_order"], starts=z["layout_starts"],
        key_frame=key_frame,
    )

    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    K, L = (int(s) for s in z["ts"].shape)
    # a finer time axis than the saver's needs more row padding; pads
    # carry TS_PAD / invalid and are inert in every kernel
    mult = 8 * n_t
    L_new = -(-L // mult) * mult
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))

    def put2(a, fill):
        if L_new != L:
            pad = np.full(a.shape[:-1] + (L_new - L,), fill, dtype=a.dtype)
            a = np.concatenate([a, pad], axis=-1)
        return jax.device_put(_pad_k(a, K_dev, fill), sharding)

    ts_d = put2(z["ts"], packing.TS_PAD)
    mask_d = put2(z["mask"], False)
    cols = {}
    for i in range(man["n_cols"]):
        meta = man["columns"][str(i)]
        hg = _read_host_gather(meta, z, objs)
        v = z[f"col_{i}_values"]
        fill = np.nan if np.issubdtype(v.dtype, np.floating) else 0
        cols[meta["name"]] = DistCol(
            put2(v, fill), put2(z[f"col_{i}_valid"], False),
            int64=meta["int64"],
            ts_chunk=tuple(meta["ts_chunk"]) if meta["ts_chunk"] else None,
            host_gather=hg,
        )
    audits = [(msg, np.int64(cnt)) for msg, cnt in man["audits"]]
    # +inf pad matches from_tsdf's seq packing (padding must sort after
    # real rows; the ts key dominates at pad slots either way).  Null
    # seq values from pre-NULLS-FIRST checkpoints were packed as NaN —
    # normalise to the -inf encoding so restored frames join like fresh
    # ones (idempotent: current-format planes carry no NaN).
    seq_d = (put2(np.where(np.isnan(z["seq"]), -np.inf, z["seq"]), np.inf)
             if "seq" in z.files else None)
    return DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout,
        man["ts_col"], man["partition_cols"], np.dtype(man["ts_dtype"]),
        source_df, man["host_cols"], man["halo_fraction"],
        audits=audits, resampled=man["resampled"],
        seq=seq_d, seq_col=man.get("seq_col", ""),
        resample_freq=man.get("resample_freq"),
    )
