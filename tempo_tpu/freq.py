"""Frequency-string parsing (parity: python/tempo/resample.py:8-23,120-136).

``checkAllowableFreq`` semantics: bare units 'sec'|'min'|'hr'|'day' mean
period 1; otherwise '<N> <unit>' strings where the unit may be any word
starting with sec/min/hour-or-hr/day.
"""

from __future__ import annotations

from typing import Tuple

SEC = "sec"
MIN = "min"
HR = "hr"
DAY = "day"

allowableFreqs = [SEC, MIN, HR, DAY]

freq_dict = {
    "sec": "seconds",
    "min": "minutes",
    "hr": "hours",
    "day": "days",
    "hour": "hours",
}

UNIT_SECONDS = {"sec": 1, "min": 60, "hr": 3600, "hour": 3600, "day": 86400}

# aggregation function names (resample.py:13-23)
floor = "floor"
min_func = "min"
max_func = "max"
average = "mean"
ceiling = "ceil"
allowableFuncs = [floor, min_func, max_func, average, ceiling]
# scala-side lead funcs (scala resample.scala:17-20)
CLOSEST_LEAD = "closest_lead"
MIN_LEAD = "min_lead"
MAX_LEAD = "max_lead"
MEAN_LEAD = "mean_lead"


def checkAllowableFreq(freq: str) -> Tuple[int, str]:
    """Returns (periods, canonical_unit). Raises ValueError on junk."""
    if freq in allowableFreqs:
        return (1, freq)
    try:
        periods = freq.lower().split(" ")[0].strip()
        units = freq.lower().split(" ")[1].strip()
        periods = int(periods)
    except Exception:
        raise ValueError(
            "Allowable grouping frequencies are sec (second), min (minute), "
            "hr (hour), day. Reformat your frequency as <integer> <day/hour/minute/second>"
        )
    if units.startswith(SEC):
        return (periods, SEC)
    if units.startswith(MIN):
        return (periods, MIN)
    if units.startswith("hour") or units.startswith(HR):
        return (periods, "hour")
    if units.startswith(DAY):
        return (periods, DAY)
    raise ValueError(
        "Allowable grouping frequencies are sec (second), min (minute), "
        "hr (hour), day. Reformat your frequency as <integer> <day/hour/minute/second>"
    )


def freq_to_seconds(freq: str) -> int:
    periods, unit = checkAllowableFreq(freq)
    return int(periods) * UNIT_SECONDS[unit]


def validateFuncExists(func) -> None:
    if func is None:
        raise ValueError(
            "Aggregate function missing. Provide one of the allowable functions: "
            + ", ".join(allowableFuncs)
        )
    if func not in allowableFuncs + [CLOSEST_LEAD, MIN_LEAD, MAX_LEAD, MEAN_LEAD]:
        raise ValueError(
            "Aggregate function is not in the valid list. Provide one of the "
            "allowable functions: " + ", ".join(allowableFuncs)
        )
