"""Interpolation service (parity: python/tempo/interpol.py).

``Interpolation(is_resampled)`` validates inputs (interpol.py:17-64),
optionally resamples (interpol.py:292-296), then fills missing grid
slots and null values with one of zero/null/ffill/bfill/linear -
executed by the dense-grid kernel in ``tempo_tpu.ops.interpolate``
instead of the reference's explode + window-scaffold plan.
"""

from __future__ import annotations

from typing import List

import numpy as np
import pandas as pd

import jax.numpy as jnp

from tempo_tpu import packing
from tempo_tpu.freq import freq_to_seconds, validateFuncExists
from tempo_tpu.ops import interpolate as ik

method_options = ["zero", "null", "bfill", "ffill", "linear"]


class Interpolation:
    def __init__(self, is_resampled: bool):
        self.is_resampled = is_resampled

    def __validate_fill(self, method: str):
        if method not in method_options:
            raise ValueError(
                f"Please select from one of the following fill options: {method_options}"
            )

    def __validate_col(
        self,
        df: pd.DataFrame,
        partition_cols: List[str],
        target_cols: List[str],
        ts_col: str,
    ):
        for column in partition_cols:
            if column not in df.columns:
                raise ValueError(
                    f"Partition Column: '{column}' does not exist in DataFrame."
                )
        for column in target_cols:
            if column not in df.columns:
                raise ValueError(
                    f"Target Column: '{column}' does not exist in DataFrame."
                )
            if not (
                pd.api.types.is_numeric_dtype(df[column].dtype)
                and not pd.api.types.is_bool_dtype(df[column].dtype)
            ):
                raise ValueError(
                    "Target Column needs to be one of the following types: "
                    "['int', 'bigint', 'float', 'double']"
                )
        if ts_col not in df.columns:
            raise ValueError(
                f"Timestamp Column: '{ts_col}' does not exist in DataFrame."
            )
        if not pd.api.types.is_datetime64_any_dtype(df[ts_col].dtype):
            raise ValueError("Timestamp Column needs to be of timestamp type.")

    def interpolate(
        self,
        tsdf,
        ts_col: str,
        partition_cols: List[str],
        target_cols: List[str],
        freq: str,
        func: str,
        method: str,
        show_interpolated: bool,
    ) -> pd.DataFrame:
        from tempo_tpu import resample as rs
        from tempo_tpu.frame import TSDF

        self.__validate_fill(method)
        self.__validate_col(tsdf.df, partition_cols, target_cols, ts_col)

        freq_sec = freq_to_seconds(freq)

        if not self.is_resampled:
            validateFuncExists(func)
            sampled = rs.aggregate(tsdf, freq, func, metricCols=target_cols)
        else:
            sampled = tsdf.df[[*partition_cols, ts_col, *target_cols]]

        sampled_tsdf = TSDF(sampled, ts_col=ts_col, partition_cols=partition_cols)
        layout = sampled_tsdf.layout
        K = layout.n_series
        n = layout.n_rows
        kid = layout.key_ids
        ts_ns = layout.ts_ns
        step_ns = np.int64(freq_sec) * packing.NS_PER_S

        # Per-row generated-slot counts, mirroring the reference's
        # explode(sequence(ts, next_ts - freq, freq)) (interpol.py:330-336):
        # row i emits floor((next_ts - ts)/freq) slots at ts, ts+freq, ...;
        # the last row of a series emits exactly itself; a row whose gap to
        # the next is < freq emits nothing and is dropped (explode of an
        # empty sequence removes the row - duplicate/misaligned input).
        m = np.ones(n, dtype=np.int64)
        if n > 1:
            next_same_key = kid[1:] == kid[:-1]
            gaps = ts_ns[1:] - ts_ns[:-1]
            m[:-1] = np.where(next_same_key, gaps // step_ns, 1)

        total = int(m.sum())
        excl = np.concatenate([[0], np.cumsum(m)[:-1]])
        row_of_slot = np.repeat(np.arange(n), m)
        j = np.arange(total) - excl[row_of_slot]
        grid_ns = ts_ns[row_of_slot] + j * step_ns
        key_of_slot = kid[row_of_slot]
        glen = np.bincount(key_of_slot, minlength=K).astype(np.int64)
        G = packing.pad_length(int(glen.max(initial=1)))
        key_starts = np.concatenate([[0], np.cumsum(glen)[:-1]])
        slot_in_key = np.arange(total) - key_starts[key_of_slot]

        real = np.zeros((K, G), dtype=bool)
        real[key_of_slot, slot_in_key] = j == 0
        ts_sec = np.zeros((K, G), dtype=np.float64)
        # unix_timestamp() truncation semantics (interpol.py:78-84)
        ts_sec[key_of_slot, slot_in_key] = grid_ns // packing.NS_PER_S

        kept = m > 0
        kept_slot = excl[kept]  # flat slot of each kept row's own position
        vals = np.full((len(target_cols), K, G), np.nan)
        valid = np.zeros((len(target_cols), K, G), dtype=bool)
        for ci, c in enumerate(target_cols):
            v, ok = sampled_tsdf.numeric_flat(c)
            vals[ci, key_of_slot[kept_slot], slot_in_key[kept_slot]] = v[kept]
            valid[ci, key_of_slot[kept_slot], slot_in_key[kept_slot]] = ok[kept]

        # f32 compute on TPU: rebase grid seconds to per-series offsets
        # (linear interpolation only ever differences timestamps within a
        # series) so they stay exactly representable; grids spanning
        # >2^24s (~194 days) keep f64
        dt = packing.compute_dtype()
        ts_dev = ts_sec
        if dt == np.float32:
            base = ts_sec[:, :1]
            span = ts_sec - base
            if span.max(initial=0.0) < 2**24:
                ts_dev = span.astype(np.float32)
            else:
                dt = np.dtype(np.float64)
        out_v, out_ok, ts_interp, col_interp = ik.interpolate_columns(
            jnp.asarray(real), jnp.asarray(glen.astype(np.int32)),
            jnp.asarray(ts_dev), jnp.asarray(dt.type(freq_sec)),
            jnp.asarray(vals.astype(dt)), jnp.asarray(valid), method,
        )
        out_v = np.asarray(out_v)
        out_ok = np.asarray(out_ok)
        ts_interp = np.asarray(ts_interp)
        col_interp = np.asarray(col_interp)

        # unpack grid -> flat rows (slots are already in key-major order)
        gmask = np.arange(G)[None, :] < glen[:, None]
        key_ids = np.repeat(np.arange(K), glen)

        out = {}
        key_frame = layout.key_frame
        for c in partition_cols:
            out[c] = key_frame[c].to_numpy()[key_ids]
        out[ts_col] = packing.ns_to_original(grid_ns, sampled[ts_col].dtype)
        for ci, c in enumerate(target_cols):
            col = out_v[ci][gmask].astype(np.float64)
            col[~out_ok[ci][gmask]] = np.nan
            out[c] = col
        out["is_ts_interpolated"] = ts_interp[gmask]
        for ci, c in enumerate(target_cols):
            out[f"is_interpolated_{c}"] = col_interp[ci][gmask]

        result = pd.DataFrame(out)
        if not show_interpolated:
            result = result.drop(
                columns=["is_ts_interpolated"]
                + [f"is_interpolated_{c}" for c in target_cols]
            )
        return result


def interpolate_frame(
    tsdf,
    freq: str,
    func: str,
    method: str,
    target_cols=None,
    ts_col=None,
    partition_cols=None,
    show_interpolated: bool = False,
):
    """TSDF.interpolate (tsdf.py:778-811): defaults resolve from the
    frame; resamples first, then fills."""
    from tempo_tpu.frame import TSDF

    if ts_col is None:
        ts_col = tsdf.ts_col
    if partition_cols is None:
        partition_cols = tsdf.partitionCols
    if target_cols is None:
        prohibited = set(partition_cols + [ts_col])
        target_cols = [
            c
            for c in tsdf.df.columns
            if (
                pd.api.types.is_numeric_dtype(tsdf.df[c].dtype)
                and not pd.api.types.is_bool_dtype(tsdf.df[c].dtype)
                and c not in prohibited
            )
        ]

    service = Interpolation(is_resampled=False)
    tsdf_input = TSDF(tsdf.df, ts_col=ts_col, partition_cols=partition_cols)
    out = service.interpolate(
        tsdf_input, ts_col, partition_cols, target_cols, freq, func, method,
        show_interpolated,
    )
    return TSDF(out, ts_col=ts_col, partition_cols=partition_cols)
