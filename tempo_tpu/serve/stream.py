"""``StreamingTSDF``: the online serving frame.

A long-lived stream over a fixed set of series: ``push(...)`` ingests
right-side ticks (advancing the AS-OF join carry, the EMA carry and
the ring-buffer window state, emitting stats/EMA for exactly the new
rows), ``push_left(...)`` answers AS-OF queries for new left rows from
the carry.  Emissions are **bitwise-equal** to running the batch
operators over the concatenated history at any push split — ties, NaN
runs, sequence columns and maxLookback expiry straddling push
boundaries included (tests/test_serve.py pins the full matrix against
``ops/sortmerge.asof_merge_values`` / ``serve.state.window_stats_batch``
/ ``ops/rolling.ema_scan``).

**Ordering contract**: events must arrive in each series' merged-stream
order — non-decreasing ``(ts, seq, side)`` with right rows before left
rows on full key ties (the batch sort's tie-break, rec_ind -1 < 1).  A
violating tick raises :class:`LateTickError` naming the offender; it is
never silently reordered (MIGRATION.md v0.9).  The constraint is
per-series: series are independent merged streams.

**Durability**: ``snapshot()`` writes the full carry (CRC'd, atomic,
keep-last-K via ``tempo_tpu/checkpoint.py``); ``StreamingTSDF.resume``
restores the newest intact snapshot and reports ``acked`` — the number
of events already folded in — so a restarted server replays only the
unacknowledged tail and lands on byte-identical output.
``TEMPO_TPU_SERVE_CKPT_EVERY`` makes snapshots automatic.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from tempo_tpu import checkpoint as ckpt
from tempo_tpu import config, resilience
from tempo_tpu.packing import TS_PAD
from tempo_tpu.serve import state as sst

_SIDE_RIGHT = 0
_SIDE_LEFT = 1
_SIDE_NAMES = {_SIDE_RIGHT: "right", _SIDE_LEFT: "left"}


class LateTickError(ValueError):
    """An event arrived behind its series' merged-stream watermark.

    The serving engine answers queries from a carry that only ever
    moves forward; accepting a late tick would silently change answers
    already emitted, so it is rejected by name instead of reordered."""

    def __init__(self, series, ts, seq, side, wm):
        self.series, self.ts, self.seq, self.side = series, ts, seq, side
        super().__init__(
            f"late {_SIDE_NAMES[side]} tick for series {series!r}: key "
            f"(ts={ts}, seq={seq}) is behind the watermark "
            f"(ts={wm[0]}, seq={wm[1]}, side={_SIDE_NAMES[wm[2]]}) — "
            f"out-of-order events are rejected, not reordered")


def _bucket(n: int) -> int:
    """Padded per-series row count: next power of two, floor 8 — a
    small fixed set of shapes so the steady state reuses a handful of
    cached executables."""
    b = 8
    while b < n:
        b *= 2
    return b


def admit_batch(series_names, wm_ts, wm_seq, wm_side, rows, ts, seq,
                side: int, n_series: int):
    """Validate one side-homogeneous batch against per-series
    merged-stream watermark PLANES and assign in-batch lanes.

    The ordering core shared by the single-stream frame
    (:meth:`StreamingTSDF._admit`) and the cohort engine
    (serve/cohort.py, which holds [S, K] watermark planes and admits
    each member against its own slot's rows) — one admission rule, so
    the two engines cannot drift on what "late" means.

    Returns ``(lanes, counts, (wm_ts', wm_seq', wm_side'))`` with the
    ADVANCED watermark copies; callers install them only after the
    step program succeeds (commit-after-success), so any failed batch
    leaves the stream untouched.  Raises :class:`LateTickError` naming
    the offending series on the first violation."""
    n = len(rows)
    lanes = np.zeros(n, np.int64)
    counts = np.zeros(n_series, np.int64)
    wm_ts = wm_ts.copy()
    wm_seq = wm_seq.copy()
    wm_side = wm_side.copy()
    for i in range(n):
        k = rows[i]
        key = (ts[i], seq[i], side)
        wm = (wm_ts[k], wm_seq[k], int(wm_side[k]))
        if key < wm:
            raise LateTickError(series_names[k], ts[i], seq[i], side, wm)
        wm_ts[k], wm_seq[k], wm_side[k] = ts[i], seq[i], side
        lanes[i] = counts[k]
        counts[k] += 1
    return lanes, counts, (wm_ts, wm_seq, wm_side)


class StreamingTSDF:
    """See module docstring.  ``series`` fixes the lane rows for the
    stream's lifetime; ``value_cols`` the metric columns.  Operators
    are opt-in: ``window_secs``/``window_rows_bound`` enable the
    causal range-window stats (``rows_bound`` declares the most rows
    any window may reach back — wider true windows are truncated and
    counted on ``clipped``, the batch engines' declared-bound
    contract), ``ema_alpha`` the EMA, ``max_lookback`` the merged-row
    join horizon, ``skip_nulls`` the per-column vs lockstep fill."""

    def __init__(self, series: Sequence, value_cols: Sequence[str], *,
                 skip_nulls: bool = True, max_lookback: int = 0,
                 window_secs=None, window_rows_bound: int = 64,
                 ema_alpha=None, checkpoint_dir: Optional[str] = None,
                 ckpt_every: Optional[int] = None, keep_last: int = 3):
        self.series = list(series)
        self.value_cols = [str(c) for c in value_cols]
        if len(set(self.series)) != len(self.series):
            raise ValueError("duplicate series keys")
        self._row = {s: k for k, s in enumerate(self.series)}
        K, C = len(self.series), len(self.value_cols)
        self.cfg = sst.StreamConfig(
            n_series=K, n_cols=C, skip_nulls=bool(skip_nulls),
            max_lookback=int(max_lookback),
            window_ns=(None if window_secs is None
                       else sst.window_ns(window_secs)),
            rows_bound=int(window_rows_bound),
            ema_alpha=(None if ema_alpha is None else float(ema_alpha)))
        self._state = sst.init_state(self.cfg)
        self._wm_ts = np.full(K, sst._FAR_PAST, np.int64)
        self._wm_seq = np.full(K, -np.inf, np.float64)
        self._wm_side = np.zeros(K, np.int8)
        self.acked = 0            # events folded into the carry
        self.checkpoint_dir = checkpoint_dir
        self.keep_last = int(keep_last)
        if ckpt_every is None:
            ckpt_every = config.get_int("TEMPO_TPU_SERVE_CKPT_EVERY", 0)
        self.ckpt_every = int(ckpt_every or 0)
        self._next_ckpt = self.ckpt_every or None
        # per-stream strong references to the step executables, keyed
        # (kind, bucket).  The shared planner LRU provides cross-stream
        # reuse and the observability counters, but it may be disabled
        # (TEMPO_TPU_PLAN_CACHE_SIZE=0) or evicted under mixed query
        # pressure — the zero-recompile steady state of a LIVE stream
        # must not hinge on either, so whatever this stream has built
        # stays pinned for its lifetime (bounded by its bucket ladder)
        self._exes = {}

    # -- ordering ------------------------------------------------------

    def _admit(self, rows, ts, seq, side: int):
        """Validate merged-stream order per series and assign in-batch
        lanes.  Returns ``(lanes, counts, commit)`` where ``commit()``
        advances the watermarks — callers invoke it only after the
        step program succeeded, so ANY failed batch (late tick, bad
        payload, executable error) leaves the stream untouched and the
        corrected batch replays cleanly."""
        lanes, counts, wm_new = admit_batch(
            self.series, self._wm_ts, self._wm_seq, self._wm_side,
            rows, ts, seq, side, self.cfg.n_series)

        def commit():
            self._wm_ts, self._wm_seq, self._wm_side = wm_new

        return lanes, counts, commit

    def _executable(self, kind: str, Lb: int):
        exe = self._exes.get((kind, Lb))
        if exe is None:
            build = (sst.push_executable if kind == "push"
                     else sst.query_executable)
            exe = build(self.cfg, Lb)
            self._exes[(kind, Lb)] = exe
        return exe

    def _rows_of(self, series_ids) -> List[int]:
        try:
            return [self._row[s] for s in series_ids]
        except KeyError as e:
            raise ValueError(
                f"unknown series {e.args[0]!r}: a StreamingTSDF's "
                f"series set is fixed at construction") from None

    @staticmethod
    def _check_lengths(n, ts, seq):
        if len(ts) != n:
            raise ValueError(
                f"series_ids and ts are parallel arrays: got {n} "
                f"series ids but {len(ts)} timestamps")
        if seq is not None and len(seq) != n:
            raise ValueError(
                f"seq must align with series_ids: {len(seq)} != {n}")

    def _values_planes(self, values, n):
        """All value columns as aligned f32 arrays, validated BEFORE
        any state (watermarks included) moves."""
        out = []
        for col in self.value_cols:
            if col not in values:
                raise ValueError(
                    f"push() is missing value column {col!r} "
                    f"(stream columns: {self.value_cols})")
            v = np.atleast_1d(np.asarray(values[col], np.float32))
            if len(v) != n:
                raise ValueError(
                    f"values[{col!r}] must align with series_ids: "
                    f"{len(v)} != {n}")
            out.append(v)
        return out

    @staticmethod
    def _seq_array(seq, n):
        if seq is None:
            return np.full(n, -np.inf, np.float64)
        s = np.asarray(seq, np.float64)
        return np.where(np.isnan(s), -np.inf, s)   # NULLS FIRST

    # -- ingest --------------------------------------------------------

    def push(self, series_ids, ts, values: Dict[str, np.ndarray],
             seq=None) -> Dict[str, np.ndarray]:
        """Ingest right-side ticks (one event per element of the
        parallel arrays; ``values`` maps column name -> array, NaN =
        null).  Returns per-event emissions for the enabled operators
        (``<col>_ema``, ``<col>_mean`` ... in input order), bitwise
        what the batch operators emit for those rows over the
        concatenated history."""
        rows = self._rows_of(series_ids)
        ts = np.atleast_1d(np.asarray(ts, np.int64))
        n = len(rows)
        self._check_lengths(n, ts, seq)
        planes = self._values_planes(values, n)
        seqf = self._seq_array(seq, n)
        lanes, counts, commit = self._admit(rows, ts, seqf, _SIDE_RIGHT)

        K, C = self.cfg.n_series, self.cfg.n_cols
        Lb = _bucket(int(counts.max()) if n else 1)
        ts_p = np.full((K, Lb), TS_PAD, np.int64)
        xs = np.full((C, K, Lb), np.nan, np.float32)
        mask = np.zeros((K, Lb), bool)
        ts_p[rows, lanes] = ts
        mask[rows, lanes] = True
        for c, v in enumerate(planes):
            xs[c, rows, lanes] = v

        exe = self._executable("push", Lb)
        new_state, emits = exe(*self._state.values(), ts_p, xs, mask,
                               counts)
        commit()
        self._state = dict(zip(self.cfg.state_names(), new_state))
        self.acked += n
        self._maybe_snapshot()

        out: Dict[str, np.ndarray] = {}
        for key, plane in emits.items():
            plane = np.asarray(plane)            # [C, K, Lb]
            for c, col in enumerate(self.value_cols):
                out[f"{col}_{key}"] = plane[c, rows, lanes]
        return out

    def push_left(self, series_ids, ts, seq=None) -> Dict[str, np.ndarray]:
        """Answer AS-OF queries for new left rows: per event, each
        column's joined value + found flag and the last right row index
        within the lookback horizon — bitwise the batch join's answer
        for these rows over the concatenated history."""
        rows = self._rows_of(series_ids)
        ts = np.atleast_1d(np.asarray(ts, np.int64))
        n = len(rows)
        self._check_lengths(n, ts, seq)
        seqf = self._seq_array(seq, n)
        lanes, counts, commit = self._admit(rows, ts, seqf, _SIDE_LEFT)
        Lb = _bucket(int(counts.max()) if n else 1)

        exe = self._executable("query", Lb)
        args = [self._state[name] for name in sst._QUERY_STATE]
        new_n_merged, (vals, found, idx) = exe(*args, counts)
        commit()
        self._state["n_merged"] = new_n_merged
        self.acked += n
        self._maybe_snapshot()

        vals = np.asarray(vals)
        found = np.asarray(found)
        out: Dict[str, np.ndarray] = {}
        for c, col in enumerate(self.value_cols):
            out[col] = vals[c, rows, lanes]
            out[f"{col}_found"] = found[c, rows, lanes]
        out["right_row_idx"] = np.asarray(idx)[rows, lanes]
        return out

    # -- introspection -------------------------------------------------

    @property
    def clipped(self) -> int:
        """Rows whose true stats window exceeded the declared
        ``window_rows_bound`` (truncated — the declared-bound audit)."""
        if not self.cfg.has_window:
            return 0
        return int(np.asarray(self._state["clipped"]).sum())

    def warmup(self, max_rows: int) -> int:
        """Pre-build the push/query executables for every padded-batch
        bucket up to ``max_rows``, so a fresh process reaches the
        zero-recompile steady state before traffic.  Returns the
        number of bucket shapes covered."""
        shapes = []
        b = _bucket(1)
        while True:
            shapes.append(b)
            if b >= max_rows:
                break
            b *= 2
        for Lb in shapes:
            self._executable("push", Lb)
            self._executable("query", Lb)
        return len(shapes)

    # -- durability ----------------------------------------------------

    def _config_meta(self) -> dict:
        return {
            "value_cols": self.value_cols,
            "skip_nulls": self.cfg.skip_nulls,
            "max_lookback": self.cfg.max_lookback,
            "window_ns": self.cfg.window_ns,
            "rows_bound": self.cfg.rows_bound,
            "ema_alpha": self.cfg.ema_alpha,
        }

    def snapshot(self) -> str:
        """Write a CRC'd atomic snapshot of the full carry under
        ``checkpoint_dir`` (step = events acked), pruning to
        ``keep_last``.  IO rides the resilience retry policy."""
        if not self.checkpoint_dir:
            raise ValueError("StreamingTSDF has no checkpoint_dir")
        arrays = {k: np.asarray(v) for k, v in self._state.items()}
        arrays["wm_ts"] = self._wm_ts
        arrays["wm_seq"] = self._wm_seq
        arrays["wm_side"] = self._wm_side
        meta = {"serve_config": self._config_meta(),
                "series": self.series, "acked": self.acked}
        path = os.path.join(self.checkpoint_dir,
                            f"step_{self.acked:010d}")
        resilience.retrying(resilience.DEFAULT_IO_POLICY,
                            label="serve-snapshot")(ckpt.save_state)(
            arrays, path, meta)
        ckpt.prune(self.checkpoint_dir, keep_last=self.keep_last)
        return path

    def _maybe_snapshot(self):
        if self._next_ckpt is not None and self.acked >= self._next_ckpt \
                and self.checkpoint_dir:
            self.snapshot()
            self._next_ckpt = self.acked + self.ckpt_every

    @classmethod
    def resume(cls, checkpoint_dir: str, verify: bool = True,
               **overrides) -> "StreamingTSDF":
        """Restore the newest intact snapshot under ``checkpoint_dir``
        (corrupt candidates are skipped with a warning, exactly like
        pipeline resume).  The returned stream's ``acked`` tells the
        caller where to restart its event source — replay everything
        after it and the output tail is byte-identical to a run that
        never died."""
        path = ckpt.latest(checkpoint_dir, verify=verify)
        if path is None:
            raise ckpt.CheckpointError(
                f"no intact stream snapshot under {checkpoint_dir!r}")
        arrays, meta = ckpt.load_state(path, verify=verify)
        scfg = meta["serve_config"]
        stream = cls(
            meta["series"], scfg["value_cols"],
            skip_nulls=scfg["skip_nulls"],
            max_lookback=scfg["max_lookback"],
            window_secs=None, ema_alpha=scfg["ema_alpha"],
            window_rows_bound=scfg["rows_bound"],
            checkpoint_dir=overrides.pop("checkpoint_dir",
                                         checkpoint_dir),
            **overrides)
        if scfg["window_ns"] is not None:
            # reconstruct the exact integer width (window_secs would
            # re-floor; the snapshot already holds the folded int)
            stream.cfg = dataclasses.replace(stream.cfg,
                                             window_ns=scfg["window_ns"])
            stream._state = sst.init_state(stream.cfg)
        for name in stream.cfg.state_names():
            stream._state[name] = np.ascontiguousarray(arrays[name])
        stream._wm_ts = np.asarray(arrays["wm_ts"], np.int64)
        stream._wm_seq = np.asarray(arrays["wm_seq"], np.float64)
        stream._wm_side = np.asarray(arrays["wm_side"], np.int8)
        stream.acked = int(meta["acked"])
        if stream.ckpt_every:
            stream._next_ckpt = stream.acked + stream.ckpt_every
        return stream
