"""Incremental operator state for the online serving engine.

Three operator states, each with an ``init / update(batch) / query``
contract, all held as explicit device arrays threaded through jitted
step programs (carries in, carries out, retired buffers donated):

* **AS-OF join carry** — the chunked merge kernel's cross-chunk VMEM
  scratch (``ops/pallas_merge.py:_make_chunked_kernel``: last filled
  value per payload plane, live series id, maxLookback source
  positions) lifted into named arrays
  (``pallas_merge.asof_carry_init``).  Fills *select* values, they
  never compute, so threading the carry across any push split is
  bit-identical to the batch join over the concatenated history — the
  same argument that makes the chunked engine bit-identical to the
  single-plan kernel.
* **EMA scan carry** — ``ops/rolling.py:ema_scan``'s ``y`` carry: one
  multiply-add per element, strictly left-to-right, so resuming from
  the carry is exact (``ema_exact``'s associative-scan tree is not
  resumable bitwise; see ``ema_scan``'s docstring).
* **ring-buffer window state** — the last ``rows_bound + 1`` right
  rows per series (timestamps, values, validity).  Range/rows stats
  for a new row are computed by the same masked shifted-pass loop
  (``_window_passes``) over ``[ring | batch]`` that the batch
  reference :func:`window_stats_batch` runs over ``[fill | history]``
  — identical op sequence over identical operands, hence bitwise
  identity by construction.  NOTE these serving stats are the *causal,
  uncentred* window form: ``withRangeStats``'s engines centre every
  series on its full-history mean (``sortmerge.range_stats_shifted``)
  — a value that changes when future rows arrive — so their per-row
  bits are unknowable mid-stream by construction.  The serving form
  drops the centring (and the Spark following-ties extension) and is
  its own batch operator.

Every step program is AOT-compiled once per (config, padded-batch
bucket) and cached in the planner's executable cache
(``tempo_tpu/plan/cache.py``), so the steady state is recompile-free
and the claim is checkable via ``profiling.plan_cache_stats()``.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from tempo_tpu.ops import pallas_merge as pm
from tempo_tpu.ops import rolling as ops_rolling
from tempo_tpu.packing import TS_PAD

_FAR_PAST = np.int64(-(1 << 62))


def donate_serve_steps() -> bool:
    """Whether the serve/cohort step programs donate their retired
    state buffers.

    On accelerator backends donation is the whole point: the steady
    state updates in place and a dropped donation doubles serving HBM
    per tick (the ``serve.step`` / ``serve.cohort_step`` compiled
    contracts pin it).  On XLA:**CPU** donation is disabled: host
    buffers are cheap, AND the virtual multi-device host platform
    (``--xla_force_host_platform_device_count``, the test/dryrun
    topology) exhibits use-after-free corruption when donated serve
    steps run in a process that has also executed stream-axis-sharded
    programs — observed as garbage emissions, glibc heap aborts and
    segfaults (jaxlib 0.4.36; minimal trigger pinned by the chaos
    suite's provenance notes).  ``TEMPO_TPU_SERVE_DONATE`` overrides
    both directions (1 forces donation on CPU, 0 disables it
    everywhere); unset = backend-automatic."""
    from tempo_tpu import config

    val = config.get("TEMPO_TPU_SERVE_DONATE")
    if val is not None and val.strip() != "":
        return val.strip().lower() not in ("0", "false", "no", "off")
    return jax.default_backend() != "cpu"


def _serve_donate(argnums: Tuple[int, ...]) -> Tuple[int, ...]:
    return argnums if donate_serve_steps() else ()


def window_ns(window_secs) -> int:
    """Window width in integer nanoseconds.  Membership ``ts >= t - w``
    over int64-ns keys equals ``ts >= t - floor(w_ns)`` (the
    ``rolling.range_window_width`` argument, applied in the ns domain):
    every float width folds to an exact integer compare, no float
    timestamp math anywhere in the serving programs."""
    return int(math.floor(float(window_secs) * 1e9))


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Static configuration of one stream: everything that shapes the
    compiled step programs (state-array layout included)."""

    n_series: int                       # K lane rows, fixed for life
    n_cols: int                         # C metric columns
    skip_nulls: bool = True
    max_lookback: int = 0               # merged-row horizon; 0 = off
    window_ns: Optional[int] = None     # range-stats width; None = off
    rows_bound: int = 64                # ring capacity D (declared max
    #                                     rows any window reaches back)
    ema_alpha: Optional[float] = None   # EMA factor; None = off

    @property
    def has_window(self) -> bool:
        return self.window_ns is not None

    @property
    def has_ema(self) -> bool:
        return self.ema_alpha is not None

    def state_names(self) -> Tuple[str, ...]:
        names = ["last_val", "last_src", "lock_val", "lock_valid",
                 "lock_src", "last_ridx", "r_count", "n_merged"]
        if self.has_ema:
            names.append("ema_y")
        if self.has_window:
            names += ["ring_ts", "ring_x", "ring_valid", "clipped"]
        return tuple(names)

    def key(self) -> tuple:
        return (self.n_series, self.n_cols, self.skip_nulls,
                self.max_lookback, self.window_ns, self.rows_bound,
                self.ema_alpha)


def init_state(cfg: StreamConfig) -> Dict[str, np.ndarray]:
    """Fresh carry arrays for every operator the config enables (the
    ``init`` leg of the operator contract)."""
    C, K = cfg.n_cols, cfg.n_series
    state = pm.asof_carry_init(C, K)
    state["r_count"] = np.zeros((K,), np.int64)
    if cfg.has_ema:
        state["ema_y"] = np.zeros((C, K), np.float32)
    if cfg.has_window:
        R = cfg.rows_bound + 1   # +1 keeps the truncation-audit row
        state["ring_ts"] = np.full((K, R), TS_PAD, np.int64)
        state["ring_x"] = np.zeros((C, K, R), np.float32)
        state["ring_valid"] = np.zeros((C, K, R), bool)
        state["clipped"] = np.zeros((K,), np.int64)
    return {name: state[name] for name in cfg.state_names()}


# ----------------------------------------------------------------------
# Shared window-pass structure (streaming step == batch reference)
# ----------------------------------------------------------------------

def _lag(a, d: int):
    """out[..., i] = a[..., i - d] (the ``sortmerge._shift_back``
    shape, re-stated here so both window forms trace the identical
    op).  Fill lanes are never consumed by in-range outputs — the ring
    prefix guarantees ``i - d >= 0`` for every emitted lane — but the
    constant must still match across the two forms, which sharing this
    helper enforces."""
    if d == 0:
        return a
    if jnp.issubdtype(a.dtype, jnp.integer):
        fill = jnp.asarray(np.iinfo(np.int64).max, a.dtype)
    elif a.dtype == jnp.bool_:
        fill = False
    else:
        fill = jnp.float32(0.0)
    pad = jnp.full(a.shape[:-1] + (d,), fill, a.dtype)
    return jnp.concatenate([pad, a[..., :-d]], axis=-1)


def _window_passes(ext_ts, ext_xs, ext_valids, w_ns: int, D: int,
                   n_out: int):
    """Causal range-window stats for the trailing ``n_out`` lanes of an
    extended layout ``[prefix(D+1) | rows]``: ``D+1`` masked shifted
    passes (self + up to ``D`` preceding rows), accumulation order
    d = 0, 1, ..., D — the uncentred twin of
    ``sortmerge._range_stats_shifted_xla``'s loop.  The prefix is the
    ring (streaming) or inert fill (batch); rows beyond it never enter
    a window because their keys sit >= ``w_ns`` above any real key
    (TS_PAD headroom), the same pad argument as the batch engine's.

    Returns ``(stats dict of [C, K, n_out] planes, clipped [K, n_out]
    bool)`` where ``clipped`` marks rows whose true window reaches past
    the declared ``D``-row bound (the pass-``D+1`` audit — the reason
    the prefix holds ``D+1`` rows)."""
    f32 = jnp.float32
    ts = ext_ts[:, -n_out:]
    lo = ts - jnp.asarray(w_ns, ext_ts.dtype)
    x_self = ext_xs[..., -n_out:]
    v_self = ext_valids[..., -n_out:]
    pinf = f32(jnp.inf)

    cnt = jnp.zeros_like(x_self)
    s1 = jnp.zeros_like(x_self)
    s2 = jnp.zeros_like(x_self)
    mn = jnp.full_like(x_self, pinf)
    mx = jnp.full_like(x_self, -pinf)
    for d in range(D + 1):
        sj = _lag(ext_ts, d)[:, -n_out:]
        xj = _lag(ext_xs, d)[..., -n_out:]
        vj = _lag(ext_valids, d)[..., -n_out:]
        inw = ((sj >= lo) & (sj <= ts))[None] & vj
        cnt = cnt + inw.astype(jnp.float32)
        s1 = s1 + jnp.where(inw, xj, f32(0.0))
        s2 = s2 + jnp.where(inw, xj * xj, f32(0.0))
        mn = jnp.minimum(mn, jnp.where(inw, xj, pinf))
        mx = jnp.maximum(mx, jnp.where(inw, xj, -pinf))

    one = f32(1.0)
    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, one), f32(jnp.nan))
    var = jnp.where(
        cnt > 1,
        (s2 - s1 * s1 / jnp.maximum(cnt, one))
        / jnp.maximum(cnt - one, one),
        f32(jnp.nan))
    std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, f32(0.0))),
                    f32(jnp.nan))
    stats = {
        "mean": mean,
        "count": cnt,
        "min": jnp.where(cnt > 0, mn, f32(jnp.nan)),
        "max": jnp.where(cnt > 0, mx, f32(jnp.nan)),
        "sum": jnp.where(cnt > 0, s1, f32(jnp.nan)),
        "stddev": std,
        "zscore": jnp.where(v_self, (x_self - mean) / std, f32(jnp.nan)),
    }
    sjD = _lag(ext_ts, D + 1)[:, -n_out:]
    vD = _lag(ext_valids, D + 1)[..., -n_out:]
    clip = ((sjD >= lo) & (sjD <= ts))[None] & (v_self | vD)
    return stats, jnp.any(clip, axis=0)


def window_stats_batch(ts, xs, valids, w_ns: int, rows_bound: int):
    """Batch reference of the serving window stats: the identical
    ``_window_passes`` loop over ``[fill | full history]``.  Streaming
    the same history through any push split reproduces these planes
    bit-for-bit (tests/test_serve.py pins it).  Returns ``(stats dict
    of [C, K, L] planes, clipped-row count [K])``."""
    ts = jnp.asarray(ts)
    xs = jnp.asarray(xs)
    valids = jnp.asarray(valids)
    C, K, L = xs.shape
    R = int(rows_bound) + 1
    ext_ts = jnp.concatenate(
        [jnp.full((K, R), TS_PAD, ts.dtype), ts], axis=-1)
    ext_xs = jnp.concatenate(
        [jnp.zeros((C, K, R), xs.dtype), xs], axis=-1)
    ext_valids = jnp.concatenate(
        [jnp.zeros((C, K, R), bool), valids], axis=-1)
    stats, clip = _window_passes(ext_ts, ext_xs, ext_valids, int(w_ns),
                                 int(rows_bound), L)
    return stats, jnp.sum(clip, axis=-1).astype(jnp.int64)


# ----------------------------------------------------------------------
# The jitted step programs
# ----------------------------------------------------------------------

_STAT_KEYS = ("mean", "count", "min", "max", "sum", "stddev", "zscore")


def _last_lane(cond, lanes):
    """(index of the last True lane, any True) per row — the carry
    update's only primitive: a max-select, never arithmetic."""
    idx = jnp.max(jnp.where(cond, lanes, jnp.int64(-1)), axis=-1)
    return idx, idx >= 0


def _at_lane(plane, idx):
    """plane[..., idx] per row (idx clamped; callers mask on has)."""
    return jnp.take_along_axis(
        plane, jnp.maximum(idx, 0)[..., None], axis=-1)[..., 0]


def _push_fn(cfg: StreamConfig, Lb: int):
    """The steady-state serving step: ONE jitted program advancing the
    AS-OF carry, the EMA carry, and the ring-buffer window state with a
    right-side micro-batch, emitting stats/EMA planes for exactly the
    new rows.  ``[K, Lb]`` batches are left-aligned per series (``mask``
    a prefix mask, ``counts`` its row sums); pad lanes carry TS_PAD
    keys and NaN values so every masked op ignores them."""
    C, K = cfg.n_cols, cfg.n_series
    lanes64 = jnp.arange(Lb, dtype=jnp.int64)

    def step(*args):
        names = cfg.state_names()
        st = dict(zip(names, args[:len(names)]))
        ts, xs, mask, counts = args[len(names):]
        valids = mask[None] & ~jnp.isnan(xs)          # packing invariant
        new = {}

        # ---- AS-OF carry update (selection only, bit-exact) ----------
        lidx, lhas = _last_lane(valids, lanes64[None, None])   # [C, K]
        new["last_val"] = jnp.where(lhas, _at_lane(xs, lidx),
                                    st["last_val"])
        new["last_src"] = jnp.where(
            lhas, st["n_merged"][None] + lidx, st["last_src"])
        rows_has = counts > 0
        last = jnp.maximum(counts - 1, 0)
        new["lock_val"] = jnp.where(
            rows_has[None], _at_lane(xs, last[None].repeat(C, 0)),
            st["lock_val"])
        new["lock_valid"] = jnp.where(
            rows_has[None], _at_lane(valids, last[None].repeat(C, 0)),
            st["lock_valid"])
        new["lock_src"] = jnp.where(
            rows_has, st["n_merged"] + counts - 1, st["lock_src"])
        new["last_ridx"] = jnp.where(
            rows_has, st["r_count"] + counts - 1, st["last_ridx"])
        new["r_count"] = st["r_count"] + counts
        new["n_merged"] = st["n_merged"] + counts

        emits = {}
        # ---- EMA scan carry ------------------------------------------
        if cfg.has_ema:
            ys, y_end = ops_rolling.ema_scan(
                xs, valids, np.float32(cfg.ema_alpha), y0=st["ema_y"])
            new["ema_y"] = y_end
            emits["ema"] = ys

        # ---- ring-buffer window stats --------------------------------
        if cfg.has_window:
            R = cfg.rows_bound + 1
            ext_ts = jnp.concatenate([st["ring_ts"], ts], axis=-1)
            ext_xs = jnp.concatenate([st["ring_x"], xs], axis=-1)
            ext_valids = jnp.concatenate([st["ring_valid"], valids],
                                         axis=-1)
            stats, clip = _window_passes(ext_ts, ext_xs, ext_valids,
                                         cfg.window_ns, cfg.rows_bound,
                                         Lb)
            emits.update(stats)
            new["clipped"] = st["clipped"] + jnp.sum(
                clip & mask, axis=-1).astype(jnp.int64)
            # retire the oldest ``counts`` rows: the new ring is the
            # last R real rows of [ring | batch] (batches are
            # left-aligned, so real rows end at lane R + counts - 1)
            ridx = (jnp.arange(R, dtype=jnp.int64)[None]
                    + counts[:, None])                     # [K, R]
            new["ring_ts"] = jnp.take_along_axis(ext_ts, ridx, axis=-1)
            new["ring_x"] = jnp.take_along_axis(
                ext_xs, ridx[None].repeat(C, 0), axis=-1)
            new["ring_valid"] = jnp.take_along_axis(
                ext_valids, ridx[None].repeat(C, 0), axis=-1)

        return tuple(new[n] for n in cfg.state_names()), emits

    return step


def _query_fn(cfg: StreamConfig, Lb: int):
    """The AS-OF query step: answers for a left micro-batch straight
    from the carry (every right row in history precedes every row of an
    accepted left batch in merged order — the push-ordering contract),
    with per-row maxLookback expiry on the carried source positions.
    Left rows consume merged positions, so the carry's ``n_merged``
    advances — querying mutates state."""
    lanes64 = jnp.arange(Lb, dtype=jnp.int64)
    ml = int(cfg.max_lookback)

    C, K = cfg.n_cols, cfg.n_series

    def step(last_val, last_src, lock_val, lock_valid, lock_src,
             last_ridx, r_count, n_merged, counts):
        pos = n_merged[:, None] + lanes64[None]           # [K, Lb]
        ok_row = jnp.broadcast_to((r_count > 0)[:, None], (K, Lb))
        if ml:
            ok_row = ok_row & (pos - lock_src[:, None] <= ml)
        if cfg.skip_nulls:
            found = jnp.broadcast_to(
                ~jnp.isnan(last_val)[:, :, None], (C, K, Lb))
            if ml:
                found = found & (pos[None] - last_src[:, :, None] <= ml)
            vals = jnp.where(
                found, last_val[:, :, None], jnp.float32(jnp.nan))
        else:
            found = ok_row[None] & lock_valid[:, :, None]
            vals = jnp.where(
                found, lock_val[:, :, None], jnp.float32(jnp.nan))
        idx = jnp.where(ok_row, last_ridx[:, None],
                        jnp.int64(-1)).astype(jnp.int32)
        return n_merged + counts, (vals, found, idx)

    return step


def _abstract(shape, dtype):
    return jax.ShapeDtypeStruct(shape, dtype)


def state_avals(cfg: StreamConfig):
    """ShapeDtypeStructs of the state tuple, in ``state_names`` order."""
    return tuple(_abstract(a.shape, a.dtype)
                 for a in init_state(cfg).values())


def push_avals(cfg: StreamConfig, Lb: int):
    C, K = cfg.n_cols, cfg.n_series
    return state_avals(cfg) + (
        _abstract((K, Lb), np.int64),        # ts
        _abstract((C, K, Lb), np.float32),   # xs
        _abstract((K, Lb), np.bool_),        # mask
        _abstract((K,), np.int64),           # counts
    )


def push_jitted(cfg: StreamConfig, Lb: int):
    """``(jitted push step, n_state)`` — the retired state buffers are
    donated, so the steady state updates in place (the compiled
    artifact's input_output_aliases; checked by the ``serve.step``
    compiled contract)."""
    n_state = len(cfg.state_names())
    fn = jax.jit(_push_fn(cfg, Lb),
                 donate_argnums=_serve_donate(tuple(range(n_state))))
    return fn, n_state


_QUERY_STATE = ("last_val", "last_src", "lock_val", "lock_valid",
                "lock_src", "last_ridx", "r_count", "n_merged")


def query_jitted(cfg: StreamConfig, Lb: int):
    # only n_merged is retired by a query
    return jax.jit(_query_fn(cfg, Lb),
                   donate_argnums=_serve_donate((7,)))


def query_avals(cfg: StreamConfig, Lb: int):
    base = dict(zip(cfg.state_names(), state_avals(cfg)))
    K = cfg.n_series
    return tuple(base[n] for n in _QUERY_STATE) + (
        _abstract((K,), np.int64),)


def _cache_key(kind: str, cfg: StreamConfig, Lb: int):
    from tempo_tpu.plan.cache import device_key

    return ("serve", kind, cfg.key(), Lb, device_key())


def push_executable(cfg: StreamConfig, Lb: int):
    """AOT-compiled push program for one padded-batch bucket, through
    the planner's LRU executable cache (hit/miss/build counters in
    ``profiling.plan_cache_stats`` — the zero-recompile steady state is
    a checked invariant, not a hope)."""
    from tempo_tpu.plan.cache import CACHE

    def build():
        fn, _ = push_jitted(cfg, Lb)
        return fn.lower(*push_avals(cfg, Lb)).compile()

    return CACHE.get_or_build(_cache_key("push", cfg, Lb), build)


def query_executable(cfg: StreamConfig, Lb: int):
    from tempo_tpu.plan.cache import CACHE

    def build():
        return query_jitted(cfg, Lb).lower(
            *query_avals(cfg, Lb)).compile()

    return CACHE.get_or_build(_cache_key("query", cfg, Lb), build)


# ----------------------------------------------------------------------
# Cohort step programs: ONE program for S streams sharing a shape
# bucket (serve/cohort.py).  The cohort step is jax.vmap of the
# per-stream step over a leading [S] stream axis — the identical op
# sequence per stream (elementwise/batched ops, per-row gathers, the
# sequential EMA scan), so each stream's slice of the cohort result is
# bitwise the single-stream program's output.  No op in the step mixes
# streams (or series), which is also why the mesh-sharded variant
# compiles with ZERO collectives: sharding the S axis splits a batch of
# independent per-stream programs across devices, nothing more.
# ----------------------------------------------------------------------

def cohort_state_init(cfg: StreamConfig, S: int) -> Dict[str, np.ndarray]:
    """Fresh [S, ...] cohort carry: S stacked :func:`init_state`s."""
    base = init_state(cfg)
    return {k: np.broadcast_to(v, (S,) + v.shape).copy()
            for k, v in base.items()}


def cohort_push_avals(cfg: StreamConfig, S: int, Lb: int):
    return tuple(_abstract((S,) + a.shape, a.dtype)
                 for a in push_avals(cfg, Lb))


def cohort_query_avals(cfg: StreamConfig, S: int, Lb: int):
    return tuple(_abstract((S,) + a.shape, a.dtype)
                 for a in query_avals(cfg, Lb))


def _cohort_shardings(fn, avals, mesh, stream_axis: str):
    """Explicit in/out shardings placing the leading stream axis of
    EVERY operand and result on ``mesh``'s ``stream_axis`` (built
    through :func:`tempo_tpu.dist.stream_shardings` — the PR 10
    pre-partitioned-handoff idiom: the step's out_shardings ARE the
    next step's in_shardings, so the steady-state loop never implies
    a reshard)."""
    from tempo_tpu import dist

    in_sh = dist.stream_shardings(mesh, stream_axis, tuple(avals))
    out_sh = dist.stream_shardings(mesh, stream_axis,
                                   jax.eval_shape(fn, *avals))
    return in_sh, out_sh


def cohort_push_jitted(cfg: StreamConfig, S: int, Lb: int, mesh=None,
                       stream_axis: str = "streams"):
    """``(jitted cohort push step, n_state)``: the vmapped per-stream
    step with every retired [S, ...] state buffer donated.  With a
    ``mesh``, the jit carries explicit stream-axis in/out shardings."""
    n_state = len(cfg.state_names())
    fn = jax.vmap(_push_fn(cfg, Lb))
    donate = _serve_donate(tuple(range(n_state)))
    if mesh is None:
        return jax.jit(fn, donate_argnums=donate), n_state
    in_sh, out_sh = _cohort_shardings(fn, cohort_push_avals(cfg, S, Lb),
                                      mesh, stream_axis)
    return jax.jit(fn, donate_argnums=donate, in_shardings=in_sh,
                   out_shardings=out_sh), n_state


def cohort_query_jitted(cfg: StreamConfig, S: int, Lb: int, mesh=None,
                        stream_axis: str = "streams"):
    fn = jax.vmap(_query_fn(cfg, Lb))
    donate = _serve_donate((7,))
    if mesh is None:
        return jax.jit(fn, donate_argnums=donate)
    in_sh, out_sh = _cohort_shardings(
        fn, cohort_query_avals(cfg, S, Lb), mesh, stream_axis)
    return jax.jit(fn, donate_argnums=donate, in_shardings=in_sh,
                   out_shardings=out_sh)


def _cohort_cache_key(kind: str, cfg: StreamConfig, S: int, Lb: int,
                      mesh):
    from tempo_tpu.plan.cache import device_key

    return ("serve", kind, cfg.key(), S, Lb, device_key(mesh))


def cohort_push_executable(cfg: StreamConfig, S: int, Lb: int,
                           mesh=None, stream_axis: str = "streams"):
    """AOT-compiled cohort push program for one (shape bucket, S,
    padded-batch bucket), through the planner's executable cache —
    the cohort steady state shares the zero-recompile counters
    (``profiling.plan_cache_stats``) with every other program."""
    from tempo_tpu.plan.cache import CACHE

    def build():
        fn, _ = cohort_push_jitted(cfg, S, Lb, mesh, stream_axis)
        return fn.lower(*cohort_push_avals(cfg, S, Lb)).compile()

    return CACHE.get_or_build(
        _cohort_cache_key("cohort_push", cfg, S, Lb, mesh), build)


def cohort_query_executable(cfg: StreamConfig, S: int, Lb: int,
                            mesh=None, stream_axis: str = "streams"):
    from tempo_tpu.plan.cache import CACHE

    def build():
        fn = cohort_query_jitted(cfg, S, Lb, mesh, stream_axis)
        return fn.lower(*cohort_query_avals(cfg, S, Lb)).compile()

    return CACHE.get_or_build(
        _cohort_cache_key("cohort_query", cfg, S, Lb, mesh), build)


# ----------------------------------------------------------------------
# Block dispatch programs: batch build + step + emission gather as ONE
# device program.  The per-tick cohort path assembles the [S, K, Lb]
# batch with host numpy fancy-indexing, ships it H2D, and pulls EVERY
# emission plane ([S, C, K, Lb] each) back D2H just to gather a handful
# of rows — at fleet rates the host scatter plus the full-plane
# transfers ARE the dispatch floor.  The block program takes the ticks
# in COMPACT form (flat index/value arrays of one pow2-padded length
# Nb), scatters them into the padded batch ON DEVICE (pad lanes carry
# an out-of-range slot index, dropped by ``mode='drop'``), runs the
# identical vmapped step, and gathers the emissions back to compact
# ``[Nb, C]`` planes on device — H2D is O(ticks), D2H is O(ticks), and
# the host never touches an [S, ...] array.
#
# Bitwise contract: the scattered batch holds exactly the values the
# host path would have built (same TS_PAD/NaN/zero fill, same f32
# payloads, one tick per (slot, row) by the caller's single-tick
# precondition, lane 0 like the singles path), and an
# ``optimization_barrier`` pins the batch arrays so the step consumes
# concrete operands — the step itself is the SAME traced
# ``_push_fn``/``_query_fn`` under ``jax.vmap``, so each member's
# emissions and state are bitwise the per-tick dispatch's
# (tests/test_block_dispatch.py pins it).
# ----------------------------------------------------------------------

def block_lanes() -> int:
    """The block programs' padded per-series row count: one tick per
    (slot, row) means every batch lane beyond the first is pad, but the
    step shape must MATCH the per-tick singles path (which pads a
    1-row batch to ``stream._bucket(1)``) so both paths share one step
    trace per config."""
    from tempo_tpu.serve import stream as stream_mod

    return stream_mod._bucket(1)


def _block_push_fn(cfg: StreamConfig, S: int, Nb: int):
    C, K = cfg.n_cols, cfg.n_series
    Lb = block_lanes()
    step = jax.vmap(_push_fn(cfg, Lb))
    n_state = len(cfg.state_names())

    def prog(*args):
        st = args[:n_state]
        sl, rw, tsv, colv = args[n_state:]
        ts_p = jnp.full((S, K, Lb), TS_PAD, jnp.int64)
        ts_p = ts_p.at[sl, rw, 0].set(tsv, mode="drop")
        mask = jnp.zeros((S, K, Lb), bool)
        mask = mask.at[sl, rw, 0].set(True, mode="drop")
        xs = jnp.full((S, C, K, Lb), jnp.nan, jnp.float32)
        for c in range(C):
            xs = xs.at[sl, c, rw, 0].set(colv[c], mode="drop")
        counts = jnp.zeros((S, K), jnp.int64)
        counts = counts.at[sl, rw].add(jnp.int64(1), mode="drop")
        ts_p, xs, mask, counts = jax.lax.optimization_barrier(
            (ts_p, xs, mask, counts))
        new_state, emits = step(*st, ts_p, xs, mask, counts)
        slg = jnp.minimum(sl, S - 1)    # pad slots clamp; host drops
        gathered = {k: v[slg, :, rw, 0] for k, v in emits.items()}
        return new_state, gathered

    return prog


def _block_query_fn(cfg: StreamConfig, S: int, Nb: int):
    K = cfg.n_series
    Lb = block_lanes()
    qstep = jax.vmap(_query_fn(cfg, Lb))

    def prog(*args):
        st = args[:len(_QUERY_STATE)]
        sl, rw = args[len(_QUERY_STATE):]
        counts = jnp.zeros((S, K), jnp.int64)
        counts = counts.at[sl, rw].add(jnp.int64(1), mode="drop")
        counts = jax.lax.optimization_barrier(counts)
        new_n_merged, (vals, found, idx) = qstep(*st, counts)
        slg = jnp.minimum(sl, S - 1)
        return new_n_merged, (vals[slg, :, rw, 0], found[slg, :, rw, 0],
                              idx[slg, rw, 0])

    return prog


def block_push_avals(cfg: StreamConfig, S: int, Nb: int):
    C = cfg.n_cols
    return cohort_push_avals(cfg, S, block_lanes())[
        :len(cfg.state_names())] + (
        _abstract((Nb,), np.int32),          # slot per tick
        _abstract((Nb,), np.int32),          # series row per tick
        _abstract((Nb,), np.int64),          # ts per tick
        _abstract((C, Nb), np.float32),      # value planes per tick
    )


def block_query_avals(cfg: StreamConfig, S: int, Nb: int):
    base = dict(zip(cfg.state_names(),
                    cohort_push_avals(cfg, S, block_lanes())[
                        :len(cfg.state_names())]))
    return tuple(base[n] for n in _QUERY_STATE) + (
        _abstract((Nb,), np.int32),
        _abstract((Nb,), np.int32),
    )


def _require_meshless(mesh, kind: str) -> None:
    if mesh is not None:
        raise NotImplementedError(
            f"the {kind} block program is host-edge code for the "
            f"meshless cohort; a mesh-sharded cohort takes the "
            f"per-tick dispatch path (its batch build is already "
            f"device-resident per shard)")


def cohort_block_push_executable(cfg: StreamConfig, S: int, Nb: int,
                                 mesh=None,
                                 stream_axis: str = "streams"):
    """AOT-compiled block push program for one (shape bucket, S, pow2
    tick-count bucket ``Nb``): device-side scatter + the vmapped step +
    compact emission gathers, with the retired state donated — cached
    under the planner's executable cache like every other serve
    program."""
    from tempo_tpu.plan.cache import CACHE

    _require_meshless(mesh, "push")

    def build():
        n_state = len(cfg.state_names())
        fn = jax.jit(_block_push_fn(cfg, S, Nb),
                     donate_argnums=_serve_donate(tuple(range(n_state))))
        return fn.lower(*block_push_avals(cfg, S, Nb)).compile()

    return CACHE.get_or_build(
        _cohort_cache_key("cohort_block_push", cfg, S, Nb, mesh), build)


def cohort_block_query_executable(cfg: StreamConfig, S: int, Nb: int,
                                  mesh=None,
                                  stream_axis: str = "streams"):
    from tempo_tpu.plan.cache import CACHE

    _require_meshless(mesh, "query")

    def build():
        fn = jax.jit(_block_query_fn(cfg, S, Nb),
                     donate_argnums=_serve_donate((7,)))
        return fn.lower(*block_query_avals(cfg, S, Nb)).compile()

    return CACHE.get_or_build(
        _cohort_cache_key("cohort_block_query", cfg, S, Nb, mesh),
        build)
