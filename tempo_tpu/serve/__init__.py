"""Online serving engine: incremental StreamingTSDF operators behind
an async micro-batch executor.

The batch library re-touches history on every answer; this package is
the long-lived-process form of the same operators: explicit carry
state (``serve/state.py`` — the chunked merge kernel's cross-chunk
scratch lifted into jitted-function carries), a streaming frame
(``serve/stream.py`` — ``push`` / ``push_left`` emitting results for
exactly the new rows, bitwise-equal to the batch operators over the
concatenated history), a shape-bucketing background executor
(``serve/executor.py`` — bounded queue, backpressure, p50/p99 latency
stamps, zero-recompile steady state through the planner's executable
cache), and crash-resume via CRC'd StreamState snapshots
(``tempo_tpu/checkpoint.py:save_state`` / ``StreamingTSDF.resume``).
"""

from tempo_tpu.serve.executor import MicroBatchExecutor, Ticket
from tempo_tpu.serve.state import StreamConfig, init_state, window_stats_batch
from tempo_tpu.serve.stream import LateTickError, StreamingTSDF

__all__ = [
    "StreamingTSDF", "MicroBatchExecutor", "Ticket", "LateTickError",
    "StreamConfig", "init_state", "window_stats_batch",
]
