"""Online serving engine: incremental StreamingTSDF operators behind
an async micro-batch executor.

The batch library re-touches history on every answer; this package is
the long-lived-process form of the same operators: explicit carry
state (``serve/state.py`` — the chunked merge kernel's cross-chunk
scratch lifted into jitted-function carries), a streaming frame
(``serve/stream.py`` — ``push`` / ``push_left`` emitting results for
exactly the new rows, bitwise-equal to the batch operators over the
concatenated history), the fleet-scale cohort engine
(``serve/cohort.py`` — thousands of streams as ONE ``[S, ...]`` state
block per shape bucket, stepped by one AOT program, stream axis
shardable over the mesh with zero per-push collectives), shape-
bucketing background executors (``serve/executor.py`` — bounded queue,
backpressure, per-ticket p50/p99 latency over a bounded window,
zero-recompile steady state through the planner's executable cache),
and crash-resume via CRC'd snapshots
(``tempo_tpu/checkpoint.py:save_state`` — per-stream
``StreamingTSDF.resume``, whole-cohort ``StreamCohort.resume``).
"""

from tempo_tpu.resilience import (Cancelled, Deadline, DeadlineExceeded,
                                  QuarantinedError, ShutdownError)
from tempo_tpu.serve.cohort import CohortMember, StreamCohort, row_bucket
from tempo_tpu.serve.executor import (CohortExecutor, MicroBatchExecutor,
                                      Ticket)
from tempo_tpu.serve.state import StreamConfig, init_state, window_stats_batch
from tempo_tpu.serve.stream import LateTickError, StreamingTSDF

__all__ = [
    "StreamingTSDF", "StreamCohort", "CohortMember", "row_bucket",
    "MicroBatchExecutor", "CohortExecutor", "Ticket", "LateTickError",
    "StreamConfig", "init_state", "window_stats_batch",
    # the fault-domain vocabulary (defined in tempo_tpu.resilience,
    # re-exported here because serving callers meet them on tickets)
    "Deadline", "DeadlineExceeded", "Cancelled", "ShutdownError",
    "QuarantinedError",
]
