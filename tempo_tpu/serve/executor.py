"""Async micro-batch executor: the serving front door.

A background worker drains a **bounded** tick queue
(``TEMPO_TPU_SERVE_QUEUE_DEPTH``; a full queue blocks ``submit`` — the
backpressure signal) into shape-bucketed, padded micro-batches: ticks
are coalesced greedily, split into side-homogeneous runs **in arrival
order** (a push and a query can never be reordered around each other —
that would change merged-stream positions), capped at
``TEMPO_TPU_SERVE_BATCH_ROWS`` rows per series, and dispatched through
``StreamingTSDF.push`` / ``push_left``.  Padded row counts land on a
handful of power-of-two buckets, so the steady state runs a small
fixed set of cached executables (``plan/cache.py``) with zero
recompiles — asserted, not hoped, by the serving bench.

Every tick carries latency stamps (submit -> batch completion, queue
wait included — the number a caller actually experiences);
``latency_stats()`` reports p50/p99 per side.  ``close()`` drains
gracefully: everything already submitted completes, then the worker
exits.  A batch failure is delivered on each affected ticket's
``result()``, never swallowed.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from tempo_tpu import config
from tempo_tpu.serve import stream as stream_mod

_CLOSE = object()


def latency_percentiles(lats: List[float]) -> dict:
    """p50/p99 (milliseconds) + count of a latency sample — the ONE
    percentile reducer behind every queue-side latency report (this
    executor's ``latency_stats`` and the query service's per-tenant
    stats, tempo_tpu/service/service.py)."""
    if not lats:
        return {"count": 0, "p50_ms": None, "p99_ms": None}
    s = sorted(lats)
    pick = lambda q: s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]
    return {"count": len(s),
            "p50_ms": round(pick(0.50) * 1e3, 3),
            "p99_ms": round(pick(0.99) * 1e3, 3)}


class Ticket:
    """One submitted tick: a waitable handle for its per-row result."""

    __slots__ = ("kind", "series", "ts", "seq", "values", "t_submit",
                 "t_done", "_event", "_result", "_exc")

    def __init__(self, kind, series, ts, seq, values):
        self.kind = kind
        self.series = series
        self.ts = ts
        self.seq = seq
        self.values = values
        self.t_submit = time.perf_counter()
        self.t_done = None
        self._event = threading.Event()
        self._result = None
        self._exc = None

    def _finish(self, result=None, exc=None):
        self._result, self._exc = result, exc
        self.t_done = time.perf_counter()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Per-row emission dict for this tick (blocks until its
        micro-batch completes); re-raises the batch's failure."""
        if not self._event.wait(timeout):
            raise TimeoutError("tick not processed yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


class MicroBatchExecutor:
    """See module docstring.  While an executor is attached, all
    traffic must go through it (``StreamingTSDF`` itself is
    single-writer)."""

    def __init__(self, stream, queue_depth: Optional[int] = None,
                 batch_rows: Optional[int] = None):
        if queue_depth is None:
            queue_depth = config.get_int("TEMPO_TPU_SERVE_QUEUE_DEPTH",
                                         1024)
        if batch_rows is None:
            batch_rows = config.get_int("TEMPO_TPU_SERVE_BATCH_ROWS", 64)
        self.stream = stream
        self.batch_rows = max(1, int(batch_rows))
        self._q: "queue.Queue" = queue.Queue(maxsize=int(queue_depth))
        self._latencies: Dict[str, List[float]] = {"right": [],
                                                   "left": []}
        self.batches = 0
        self.ticks = 0
        self.bucket_hist: Dict[int, int] = {}
        self._closed = False
        # serializes the closed-check+enqueue against close(): without
        # it a tick can land BEHIND the close sentinel and hang its
        # result() forever
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="tempo-serve-executor")
        self._thread.start()

    # -- producer side -------------------------------------------------

    def submit(self, kind: str, series, ts, values=None, seq=None,
               timeout: Optional[float] = None) -> Ticket:
        """Enqueue one tick (``kind`` 'right' = data, 'left' = query).
        Blocks while the queue is full (backpressure); a ``timeout``
        surfaces ``queue.Full`` instead of waiting forever."""
        if kind not in ("right", "left"):
            raise ValueError(f"kind must be 'right' or 'left', got "
                             f"{kind!r}")
        t = Ticket(kind, series, ts, seq, values)
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("executor is closed")
            self._q.put(t, block=True, timeout=timeout)
        return t

    def close(self, timeout: Optional[float] = None):
        """Graceful drain: stop accepting, process everything already
        queued, stop the worker."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._q.put(_CLOSE)
        self._thread.join(timeout)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- worker side ---------------------------------------------------

    def _run(self):
        closing = False
        while not closing:
            item = self._q.get()
            if item is _CLOSE:
                break
            group = [item]
            while True:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _CLOSE:
                    closing = True
                    break
                group.append(nxt)
            for batch in self._split(group):
                self._process(batch)

    def _split(self, group: List[Ticket]):
        """Side-homogeneous runs in arrival order, cut when any series
        reaches the per-batch row cap."""
        batch: List[Ticket] = []
        counts: Dict[object, int] = {}
        for t in group:
            if batch and (t.kind != batch[0].kind
                          or counts.get(t.series, 0) >= self.batch_rows):
                yield batch
                batch, counts = [], {}
            batch.append(t)
            counts[t.series] = counts.get(t.series, 0) + 1
        if batch:
            yield batch

    def _process(self, batch: List[Ticket]):
        kind = batch[0].kind
        try:
            # conversions live INSIDE the failure boundary: a bad
            # ts/seq/value payload poisons its own batch, not the
            # worker thread
            series = [t.series for t in batch]
            ts = np.array([t.ts for t in batch], np.int64)
            seq = None
            if any(t.seq is not None for t in batch):
                seq = np.array([np.nan if t.seq is None else t.seq
                                for t in batch], np.float64)
            if kind == "right":
                cols = self.stream.value_cols
                values = {c: np.array([t.values[c] for t in batch],
                                      np.float32) for c in cols}
                out = self.stream.push(series, ts, values, seq=seq)
            else:
                out = self.stream.push_left(series, ts, seq=seq)
        except Exception as e:       # delivered on each ticket's
            for t in batch:          # result(); the worker lives on
                t._finish(exc=e)
            return
        self.batches += 1
        self.ticks += len(batch)
        counts: Dict[object, int] = {}
        for t in batch:
            counts[t.series] = counts.get(t.series, 0) + 1
        b = stream_mod._bucket(max(counts.values()))
        self.bucket_hist[b] = self.bucket_hist.get(b, 0) + 1
        for i, t in enumerate(batch):
            t._finish(result={k: v[i] for k, v in out.items()})
            lat = t.latency_s
            if lat is not None:
                self._latencies[kind].append(lat)

    # -- metrics -------------------------------------------------------

    def latency_stats(self) -> Dict[str, dict]:
        """p50/p99 (milliseconds) + count per side, and pooled."""
        out = {}
        pooled: List[float] = []
        for kind, lats in self._latencies.items():
            pooled.extend(lats)
            out[kind] = latency_percentiles(lats)
        out["all"] = latency_percentiles(pooled)
        return out
