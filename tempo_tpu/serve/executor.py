"""Async micro-batch executor: the serving front door.

A background worker drains a **bounded** tick queue
(``TEMPO_TPU_SERVE_QUEUE_DEPTH``; a full queue blocks ``submit`` — the
backpressure signal) into shape-bucketed, padded micro-batches: ticks
are coalesced greedily, split into side-homogeneous runs **in arrival
order** (a push and a query can never be reordered around each other —
that would change merged-stream positions), capped at
``TEMPO_TPU_SERVE_BATCH_ROWS`` rows per series, and dispatched through
``StreamingTSDF.push`` / ``push_left``.  Padded row counts land on a
handful of power-of-two buckets, so the steady state runs a small
fixed set of cached executables (``plan/cache.py``) with zero
recompiles — asserted, not hoped, by the serving bench.

Every tick carries latency stamps (submit -> batch completion, queue
wait included — the number a caller actually experiences);
``latency_stats()`` reports p50/p99 per side.  ``close()`` drains
gracefully: everything already submitted completes, then the worker
exits.  A batch failure is delivered on each affected ticket's
``result()``, never swallowed.

**The fault domain** (resilience.py primitives):

* *deadlines* — a :class:`~tempo_tpu.resilience.Deadline` rides each
  ticket from ``submit`` (``deadline=`` seconds, default
  ``TEMPO_TPU_SERVE_DEADLINE_S``); a tick whose budget dies while it
  is still queued fails fast with a stage-named ``DeadlineExceeded``
  and never reaches a dispatch (once dispatched, its state change is
  real, so its result is always delivered).
* *cancellation* — ``Ticket.cancel()`` resolves the ticket with
  :class:`~tempo_tpu.resilience.Cancelled`; the worker drops it on
  sight, so cancelled work never reaches the stream.
* *supervision* — the drain thread runs under a supervisor: an
  unexpected exception escaping the worker loop fails the in-flight
  tickets, restarts the drain (``restarts`` counts them), and the
  plane lives on; a ``BaseException`` (``SimulatedKill`` — modelled
  process death) marks the plane dead, fails every outstanding ticket
  with :class:`~tempo_tpu.resilience.ShutdownError` and closes it.
* *quarantine* — :class:`CohortExecutor` carries a per-stream-member
  :class:`~tempo_tpu.resilience.CircuitBreaker`: a member failing
  repeatedly is quarantined (its tickets fail fast with
  ``QuarantinedError``) until a half-open probe succeeds.
* *shutdown* — ``close(timeout)`` shares ONE deadline across the
  drain; whatever is still pending when it expires (or when the
  worker is dead) is failed with ``ShutdownError`` — a ticket NEVER
  hangs its caller.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from tempo_tpu import config
from tempo_tpu.resilience import (Cancelled, CircuitBreaker, Deadline,
                                  DeadlineExceeded, QuarantinedError,
                                  ShutdownError)
from tempo_tpu.serve import stream as stream_mod

logger = logging.getLogger(__name__)

_CLOSE = object()

#: bounded percentile-sample window shared by every queue-side latency
#: report: this executor's per-side samples, the cohort executor's, and
#: the query service's per-tenant deques (service/service.py) all keep
#: the most recent window, so a long-lived server never grows a float
#: per tick served forever.
LATENCY_WINDOW = 4096


def latency_percentiles(lats) -> dict:
    """p50/p99 (milliseconds) + count of a latency sample — the ONE
    percentile reducer behind every queue-side latency report (this
    executor's ``latency_stats`` and the query service's per-tenant
    stats, tempo_tpu/service/service.py)."""
    if not lats:
        return {"count": 0, "p50_ms": None, "p99_ms": None}
    s = sorted(lats)
    pick = lambda q: s[min(len(s) - 1, int(q * (len(s) - 1) + 0.5))]
    return {"count": len(s),
            "p50_ms": round(pick(0.50) * 1e3, 3),
            "p99_ms": round(pick(0.99) * 1e3, 3)}


class _ChunkGate:
    """Shared completion gate for a ``submit_many`` chunk: ONE lock
    for the whole chunk.  A per-ticket ``threading.Event`` costs ~10us
    to allocate on this image — at fleet rates that alone caps the
    feeder below the dispatch side.  Tickets flip their ``_done``
    flag; the worker rings the gate once per processed batch; waiters
    re-check their own flag (a chunk split across batches wakes some
    waiters early — they just wait again)."""

    __slots__ = ("cv",)

    def __init__(self):
        self.cv = threading.Condition()

    def ring(self):
        with self.cv:
            self.cv.notify_all()

    def wait_for(self, ticket: "Ticket",
                 timeout: Optional[float]) -> bool:
        with self.cv:
            return self.cv.wait_for(lambda: ticket._done, timeout)


class Ticket:
    """One submitted tick: a waitable handle for its per-row result.
    ``member`` is the cohort stream handle on
    :class:`CohortExecutor` tickets, ``None`` on single-stream ones."""

    __slots__ = ("kind", "series", "ts", "seq", "values", "member",
                 "deadline", "t_submit", "t_done", "_event", "_gate",
                 "_done", "_cancelled", "_result", "_exc")

    def __init__(self, kind, series, ts, seq, values, member=None,
                 t_submit=None, gate: Optional[_ChunkGate] = None,
                 deadline: Optional[Deadline] = None):
        self.kind = kind
        self.series = series
        self.ts = ts
        self.seq = seq
        self.values = values
        self.member = member
        self.deadline = deadline
        self.t_submit = (time.perf_counter() if t_submit is None
                         else t_submit)
        self.t_done = None
        self._gate = gate
        self._event = None if gate is not None else threading.Event()
        self._done = False
        self._cancelled = False
        self._result = None
        self._exc = None

    def _finish(self, result=None, exc=None):
        if self._done:      # first outcome wins: a shutdown sweep and
            return          # a still-draining worker may race here
        self._result, self._exc = result, exc
        self.t_done = time.perf_counter()
        self._done = True
        if self._event is not None:
            self._event.set()
        # gate tickets are woken by the worker's per-batch ring()

    def cancel(self) -> bool:
        """Request cancellation (best-effort, asynchronous): the WORKER
        resolves the ticket with :class:`Cancelled` when it reaches it
        still queued — cancelled work never reaches a dispatch.  A tick
        already inside a dispatch cannot be un-run: its real outcome is
        delivered (resolving it Cancelled while the state change lands
        would make an at-least-once feeder double-apply the event).
        Returns ``True`` when the request was registered before the
        ticket resolved; the caller learns the actual outcome from
        ``result()``."""
        if self._done:
            return False
        self._cancelled = True
        return not self._done

    def done(self) -> bool:
        return self._done

    def result(self, timeout: Optional[float] = None):
        """Per-row emission dict for this tick (blocks until its
        micro-batch completes); re-raises the batch's failure."""
        if not self._done:
            ok = (self._event.wait(timeout) if self._event is not None
                  else self._gate.wait_for(self, timeout))
            if not ok:
                raise TimeoutError("tick not processed yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


class BlockTicket(Ticket):
    """One submitted columnar tick block
    (:meth:`CohortExecutor.submit_block`): a waitable handle whose
    ``result()`` is the block's full-length columnar emission dict
    (``StreamCohort.dispatch_block``'s ``out``).  Per-tick rejections
    (late tick, unknown series, quarantined member) land in
    :attr:`errors` — index -> exception — with the rejected rows left
    at their fill values; only a BLOCK-level failure raises from
    ``result()``.  ``cancel()``/deadlines drop the whole block before
    dispatch, exactly like a per-tick ticket."""

    __slots__ = ("kinds", "members", "series_ids", "tsv", "seqv",
                 "_errors")

    def __init__(self, kinds, members, series_ids, ts, seq, values,
                 deadline: Optional[Deadline] = None):
        n = len(members)
        ts_span = f"{int(ts[0])}..{int(ts[-1])}" if n else ""
        super().__init__("block", f"<{n} ticks>", ts_span, None,
                         values, deadline=deadline)
        self.kinds = kinds
        self.members = members
        self.series_ids = series_ids
        self.tsv = ts
        self.seqv = seq
        self._errors: Dict[int, Exception] = {}

    @property
    def errors(self) -> Dict[int, Exception]:
        """Per-tick rejections (tick index -> exception), populated by
        the time ``result()`` returns."""
        return self._errors


class MicroBatchExecutor:
    """See module docstring.  While an executor is attached, all
    traffic must go through it (``StreamingTSDF`` itself is
    single-writer)."""

    #: upper bound on a coalesced run before the worker stops waiting
    #: for more ticks and dispatches what it has
    _COALESCE_MAX = 8192

    def __init__(self, stream, queue_depth: Optional[int] = None,
                 batch_rows: Optional[int] = None,
                 coalesce_s: float = 0.0):
        if queue_depth is None:
            queue_depth = config.get_int("TEMPO_TPU_SERVE_QUEUE_DEPTH",
                                         1024)
        if batch_rows is None:
            # env knob first, then the autotuner's measured winner for
            # this device kind (tempo_tpu/tune), then the built-in 64
            from tempo_tpu import tune

            batch_rows = config.get_int("TEMPO_TPU_SERVE_BATCH_ROWS")
            if batch_rows is None:
                batch_rows = tune.knob_value(
                    "TEMPO_TPU_SERVE_BATCH_ROWS", "serve_batch") or 64
        self.stream = stream
        self.batch_rows = max(1, int(batch_rows))
        # micro-batch coalescing window: after the first tick of a
        # run, wait up to this long for more before dispatching.  A
        # dispatch has a real fixed cost (for a cohort, stepping the
        # whole [S, ...] state block); under load, paying it for a
        # handful of ticks caps aggregate throughput — the window
        # trades bounded extra latency for amortization.  0 (the
        # single-stream default) preserves drain-what's-queued
        self.coalesce_s = max(0.0, float(coalesce_s))
        self._q: "queue.Queue" = queue.Queue(maxsize=int(queue_depth))
        # bounded per-side sample windows: percentiles are over the
        # most recent LATENCY_WINDOW ticks, per ticket (submit ->
        # completion), never per dispatch
        self._latencies: Dict[str, collections.deque] = {
            "right": collections.deque(maxlen=LATENCY_WINDOW),
            "left": collections.deque(maxlen=LATENCY_WINDOW)}
        self.batches = 0
        self.ticks = 0
        self.bucket_hist: Dict[int, int] = {}
        #: default per-ticket deadline budget (seconds); None = none
        self.deadline_s = config.get_float("TEMPO_TPU_SERVE_DEADLINE_S")
        #: drain-thread restarts performed by the supervisor
        self.restarts = 0
        #: tickets failed with a stage-named DeadlineExceeded
        self.deadline_failures = 0
        #: the BaseException that killed the plane, when it is dead
        self.fatal: Optional[BaseException] = None
        self._inflight: List[Ticket] = []
        self._closed = False  # guarded-by: self._submit_lock
        # serializes the closed-check+enqueue against close(): without
        # it a tick can land BEHIND the close sentinel and hang its
        # result() forever
        self._submit_lock = threading.Lock()
        self._thread = threading.Thread(target=self._supervise,
                                        daemon=True,
                                        name="tempo-serve-executor")
        self._thread.start()

    # -- producer side -------------------------------------------------

    def _deadline(self, deadline) -> Optional[Deadline]:
        """Per-submit override (seconds or a Deadline) over the
        executor default (``TEMPO_TPU_SERVE_DEADLINE_S``)."""
        if deadline is None:
            deadline = self.deadline_s
        return Deadline.after(deadline)

    def submit(self, kind: str, series, ts, values=None, seq=None,
               timeout: Optional[float] = None, deadline=None) -> Ticket:
        """Enqueue one tick (``kind`` 'right' = data, 'left' = query).
        Blocks while the queue is full (backpressure); a ``timeout``
        surfaces ``queue.Full`` instead of waiting forever.
        ``deadline`` (seconds, or a :class:`Deadline`) bounds the
        tick's WHOLE trip: expiry during the backpressure wait or in
        the queue fails it with a stage-named ``DeadlineExceeded``."""
        if kind not in ("right", "left"):
            raise ValueError(f"kind must be 'right' or 'left', got "
                             f"{kind!r}")
        dl = self._deadline(deadline)
        t = Ticket(kind, series, ts, seq, values, deadline=dl)
        self._put(t, timeout, dl)
        return t

    def _put(self, item, timeout: Optional[float],
             dl: Optional[Deadline]) -> None:
        """Closed-checked enqueue; a deadline bounds the backpressure
        wait (stage 'submit backpressure') under the caller timeout."""
        if dl is not None:
            dl.check("submit backpressure")
            rem = dl.remaining()
            timeout = rem if timeout is None else min(timeout, rem)
        with self._submit_lock:
            if self._closed:
                raise ShutdownError("executor is closed")
            try:
                # Deliberate (PR 8): the closed-check+enqueue must be
                # atomic vs close() or a tick lands BEHIND the close
                # sentinel and its result() hangs forever; the lock's
                # only other users flip the _closed flag, so the stall
                # here is pure backpressure.
                self._q.put(item, block=True, timeout=timeout)  # lint-ok: blocking-under-lock: atomic closed-check+enqueue vs close() is the PR-8 close-sentinel fix; see comment above
            except queue.Full:
                if dl is not None and dl.expired():
                    raise DeadlineExceeded(
                        f"deadline exceeded at stage 'submit "
                        f"backpressure': queue still full after the "
                        f"{dl.budget_s:.3f}s budget",
                        stage="submit backpressure") from None
                raise

    def close(self, timeout: Optional[float] = None):
        """Graceful drain: stop accepting, process everything already
        queued, stop the worker.  ``timeout`` bounds the WHOLE drain
        (one shared deadline, the ``QueryService.close`` discipline);
        tickets still pending when it expires — or when the worker is
        dead — are failed with :class:`ShutdownError`, never left to
        hang their callers."""
        with self._submit_lock:
            sentinel_needed = not self._closed
            self._closed = True
        if sentinel_needed:
            # the sentinel enqueue deliberately sits OUTSIDE the
            # critical section: with _closed already up, submitters
            # fail fast with ShutdownError instead of stacking behind
            # a close() blocked on a full queue, and ordering is
            # preserved — _put's closed-check+enqueue is atomic under
            # the same lock, so nothing can land behind the sentinel
            self._q.put(_CLOSE)
        # idempotent: a second close (e.g. __exit__ after an explicit
        # close) joins the SAME drain within its own timeout — it must
        # never steal queued tickets from a worker that is still
        # draining them gracefully
        dl = Deadline.after(timeout)
        self._thread.join(timeout if dl is None else
                          max(0.0, dl.remaining()))
        if self._thread.is_alive() or self.fatal is not None \
                or not self._q.empty():
            cause = (f" (plane died: {self.fatal})"
                     if self.fatal is not None else
                     " (drain deadline expired)"
                     if self._thread.is_alive() else "")
            self._fail_pending(ShutdownError(
                f"executor closed with this tick still pending{cause}"))

    def _fail_pending(self, exc: BaseException) -> None:
        """Resolve every ticket the worker will never process: the
        queue backlog and the not-yet-finished in-flight group.  A
        still-alive worker finds a fresh close sentinel so it exits at
        its next queue read instead of blocking forever."""
        drained = False
        while True:
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            drained = True
            if item is _CLOSE:
                continue
            group: List[Ticket] = []
            self._extend(group, item)
            for t in group:
                t._finish(exc=exc)
                self._on_dropped(t)     # free an abandoned breaker probe
            self._ring(group)
        for t in list(self._inflight):
            if not t._done:
                t._finish(exc=exc)
                self._on_dropped(t)
        self._ring(self._inflight)
        if drained and self._thread.is_alive():
            self._q.put(_CLOSE)

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    # -- worker side ---------------------------------------------------

    @staticmethod
    def _extend(group: List[Ticket], item) -> None:
        """Fold one queue entry into the run — a bare ticket or a
        ``submit_many`` chunk (list of tickets)."""
        if type(item) is list:
            group.extend(item)
        else:
            group.append(item)

    @staticmethod
    def _ring(batch):
        gates = {t._gate for t in batch}
        gates.discard(None)
        for gate in gates:
            gate.ring()

    def _supervise(self):  # owns-tickets: _finish, _fail_pending
        """The drain thread's supervisor: an unexpected ``Exception``
        escaping the worker loop (poisoned work already fails inside
        its own batch — this catches plane-level faults) fails the
        in-flight group, restarts the drain, and the executor keeps
        serving.  A ``BaseException`` (``SimulatedKill`` — modelled
        process death, real interpreter teardown) is NOT survivable:
        the plane closes itself, every outstanding ticket resolves
        with :class:`ShutdownError`, and the thread exits."""
        while True:
            try:
                self._run()
                return                        # clean close
            except Exception as e:  # noqa: BLE001 - supervised restart
                for t in list(self._inflight):
                    t._finish(exc=e)
                self._ring(self._inflight)
                self._inflight = []
                self.restarts += 1
                logger.warning(
                    "serve executor worker died (%s: %s); supervisor "
                    "restart #%d", type(e).__name__, e, self.restarts)
            except BaseException as e:        # the plane is dead
                self.fatal = e
                with self._submit_lock:
                    self._closed = True
                self._fail_pending(ShutdownError(
                    f"executor plane died ({type(e).__name__}: {e}); "
                    f"tick was never processed"))
                logger.error("serve executor plane died: %s", e)
                return

    def _admit_live(self, group: List[Ticket]) -> List[Ticket]:
        """Drop tickets that must never reach a dispatch: cancelled
        ones (resolved HERE with :class:`Cancelled` — the worker is
        the single decision point, so a cancellation can never race a
        dispatch's state change) and those whose deadline died in the
        queue — failed with a stage-named ``DeadlineExceeded``.
        Deadlines are only enforced BEFORE dispatch: once the step
        program ran, the state change is real and the result is
        always delivered."""
        live: List[Ticket] = []
        woke: List[Ticket] = []
        for t in group:
            if t._done:
                continue
            if t._cancelled:
                t._finish(exc=Cancelled(
                    f"tick ({t.kind!r}, series {t.series!r}, ts "
                    f"{t.ts}) cancelled before dispatch"))
                self._on_dropped(t)
                woke.append(t)
                continue
            if t.deadline is not None and t.deadline.expired():
                t._finish(exc=DeadlineExceeded(
                    f"deadline exceeded at stage 'serve queue': tick "
                    f"({t.kind!r}, series {t.series!r}, ts {t.ts}) "
                    f"spent its {t.deadline.budget_s:.3f}s budget "
                    f"waiting for dispatch", stage="serve queue"))
                self.deadline_failures += 1
                self._on_dropped(t)
                woke.append(t)
                continue
            live.append(t)
        self._ring(woke)
        return live

    def _on_dropped(self, t: Ticket) -> None:
        """Hook: a ticket resolved before reaching a dispatch (deadline
        death).  CohortExecutor frees an abandoned breaker probe."""

    def _run(self):
        closing = False
        while not closing:
            item = self._q.get()
            if item is _CLOSE:
                break
            group: List[Ticket] = []
            self._extend(group, item)
            if self.coalesce_s > 0.0:
                deadline = time.monotonic() + self.coalesce_s
                while len(group) < self._COALESCE_MAX:
                    rem = deadline - time.monotonic()
                    if rem <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=rem)
                    except queue.Empty:
                        break
                    if nxt is _CLOSE:
                        closing = True
                        break
                    self._extend(group, nxt)
            if not closing:
                while True:
                    try:
                        nxt = self._q.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _CLOSE:
                        closing = True
                        break
                    self._extend(group, nxt)
            group = self._admit_live(group)
            # visible to the supervisor/shutdown sweep: anything not
            # finished when this group dies mid-processing gets failed
            # instead of hanging its caller
            self._inflight = group
            for batch in self._split(group):
                self._process(batch)
            self._inflight = []

    @staticmethod
    def _series_key(t: Ticket):
        return t.series

    def _split(self, group: List[Ticket]):
        """Side-homogeneous runs in arrival order, cut when any series
        (per stream, on cohort executors) reaches the per-batch row
        cap."""
        batch: List[Ticket] = []
        counts: Dict[object, int] = {}
        for t in group:
            key = self._series_key(t)
            if batch and (t.kind != batch[0].kind
                          or counts.get(key, 0) >= self.batch_rows):
                yield batch
                batch, counts = [], {}
            batch.append(t)
            counts[key] = counts.get(key, 0) + 1
        if batch:
            yield batch

    def _process(self, batch: List[Ticket]):
        kind = batch[0].kind
        try:
            # conversions live INSIDE the failure boundary: a bad
            # ts/seq/value payload poisons its own batch, not the
            # worker thread
            series = [t.series for t in batch]
            ts = np.array([t.ts for t in batch], np.int64)
            seq = None
            if any(t.seq is not None for t in batch):
                seq = np.array([np.nan if t.seq is None else t.seq
                                for t in batch], np.float64)
            if kind == "right":
                cols = self.stream.value_cols
                values = {c: np.array([t.values[c] for t in batch],
                                      np.float32) for c in cols}
                out = self.stream.push(series, ts, values, seq=seq)
            else:
                out = self.stream.push_left(series, ts, seq=seq)
        except Exception as e:       # delivered on each ticket's
            for t in batch:          # result(); the worker lives on
                t._finish(exc=e)
            return
        self.batches += 1
        self.ticks += len(batch)
        counts: Dict[object, int] = {}
        for t in batch:
            counts[t.series] = counts.get(t.series, 0) + 1
        b = stream_mod._bucket(max(counts.values()))
        self.bucket_hist[b] = self.bucket_hist.get(b, 0) + 1
        for i, t in enumerate(batch):
            t._finish(result={k: v[i] for k, v in out.items()})
            lat = t.latency_s
            if lat is not None:
                self._latencies[kind].append(lat)

    # -- metrics -------------------------------------------------------

    def latency_stats(self) -> Dict[str, dict]:
        """p50/p99 (milliseconds) + count per side, and pooled."""
        out = {}
        pooled: List[float] = []
        for kind, lats in self._latencies.items():
            pooled.extend(lats)
            out[kind] = latency_percentiles(lats)
        out["all"] = latency_percentiles(pooled)
        return out


class CohortExecutor(MicroBatchExecutor):
    """The fleet-serving front door: one executor, N member streams,
    ONE cohort dispatch per micro-batch.

    Same bounded-queue/backpressure/drain machinery as
    :class:`MicroBatchExecutor`, but tickets name a
    :class:`~tempo_tpu.serve.cohort.CohortMember` and a coalesced run
    becomes one :meth:`~tempo_tpu.serve.cohort.StreamCohort.dispatch`
    regardless of how many streams it spans — aggregate throughput is
    bounded by the step program, not by per-stream dispatch count.
    Accounting is **per ticket**: latency is each tick's own
    submit → completion interval (a 10k-stream dispatch contributes 10k
    samples, not one) over the bounded ``LATENCY_WINDOW``, and a
    rejected member's tickets fail individually while the rest of the
    dispatch completes (the cohort's per-stream isolation, surfaced
    per ticket)."""

    def __init__(self, cohort, queue_depth: Optional[int] = None,
                 batch_rows: Optional[int] = None,
                 coalesce_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        if coalesce_s is None:
            # the coalescing window is a measured autotuner axis on the
            # cohort path (tune/space.py): env knob wins, then the
            # profile's winner, then the built-in 2ms
            from tempo_tpu import tune

            coalesce_s = config.get_float("TEMPO_TPU_SERVE_COALESCE_S")
            if coalesce_s is None:
                tuned = tune.knob_value("TEMPO_TPU_SERVE_COALESCE_S",
                                        "serve_cohort")
                coalesce_s = 0.002 if tuned is None else float(tuned)
        super().__init__(cohort, queue_depth=queue_depth,
                         batch_rows=batch_rows, coalesce_s=coalesce_s)
        self.cohort = cohort
        #: per-stream-member circuit breaker: a member whose ticks keep
        #: failing is quarantined (fail-fast QuarantinedError tickets)
        #: until a half-open probe succeeds — one poisoned feed cannot
        #: burn the whole plane's retry budget
        self.breaker = breaker if breaker is not None else CircuitBreaker()

    def _quarantined(self, member, kind, series, ts, seq, values,
                     t_submit=None, gate=None) -> Optional[Ticket]:
        """A pre-resolved QuarantinedError ticket when ``member`` is
        quarantined (it never enters the queue); None when admitted."""
        try:
            self.breaker.allow(member.name, label="stream member")
        except QuarantinedError as e:
            t = Ticket(kind, series, ts, seq, values, member=member,
                       t_submit=t_submit, gate=gate)
            t._finish(exc=e)
            return t
        return None

    def submit(self, member, kind: str, series, ts, values=None,
               seq=None, timeout: Optional[float] = None,
               deadline=None) -> Ticket:
        """Enqueue one tick for ``member`` (``kind`` 'right' = data,
        'left' = query); blocks on a full queue (backpressure).
        ``deadline`` as on :meth:`MicroBatchExecutor.submit`; a
        quarantined member's ticket resolves immediately with
        ``QuarantinedError`` and never reaches the queue."""
        if kind not in ("right", "left"):
            raise ValueError(f"kind must be 'right' or 'left', got "
                             f"{kind!r}")
        bad = self._quarantined(member, kind, series, ts, seq, values)
        if bad is not None:
            return bad
        dl = self._deadline(deadline)
        t = Ticket(kind, series, ts, seq, values, member=member,
                   deadline=dl)
        try:
            self._put(t, timeout, dl)
        except BaseException:
            # the failed enqueue may have been the member's half-open
            # probe: free the slot or the member quarantines forever
            self.breaker.abandon(member.name)
            raise
        return t

    def submit_many(self, ticks, timeout: Optional[float] = None,
                    deadline=None) -> List[Ticket]:
        """Bulk enqueue: ``ticks`` is ``[(kind, member, series, ts,
        values, seq)]`` in arrival order (``values`` None for
        queries; kinds may mix — the worker's member-order-preserving
        split sorts it out).  ONE queue entry and one shared submit
        stamp for the whole chunk — the fleet feeder's path: at
        10k-stream rates, per-tick ``submit()`` overhead (a lock round
        and a queue put per tick) costs more than the whole
        dispatch-side share.  Results, failures and latency stay per
        ticket; a chunk counts as one entry toward the queue bound.
        One shared ``deadline`` covers the chunk; quarantined members'
        tickets resolve immediately with ``QuarantinedError`` while
        the rest of the chunk proceeds."""
        t0 = time.perf_counter()
        gate = _ChunkGate()
        dl = self._deadline(deadline)
        chunk, out = [], []
        for kind, member, series, ts, values, seq in ticks:
            if kind not in ("right", "left"):
                raise ValueError(f"kind must be 'right' or 'left', "
                                 f"got {kind!r}")
            bad = self._quarantined(member, kind, series, ts, seq,
                                    values, t_submit=t0)
            if bad is not None:
                out.append(bad)
                continue
            t = Ticket(kind, series, ts, seq, values, member=member,
                       t_submit=t0, gate=gate, deadline=dl)
            chunk.append(t)
            out.append(t)
        if chunk:
            try:
                self._put(chunk, timeout, dl)
            except BaseException:
                # any of the chunk's members may have been probing;
                # abandon() is a no-op for the rest
                for t in chunk:
                    self.breaker.abandon(t.member.name)
                raise
        return out

    def submit_block(self, kinds, members, series_ids, ts, values=None,
                     seq=None, timeout: Optional[float] = None,
                     deadline=None) -> BlockTicket:
        """Enqueue a columnar tick block: parallel arrays instead of a
        per-tick item list, ONE queue entry, ONE waitable
        :class:`BlockTicket`, dispatched through
        :meth:`~tempo_tpu.serve.cohort.StreamCohort.dispatch_block` —
        at most one device program per side for the single-tick-
        per-(member, series) majority, no per-tick python on either
        side of the queue.  Arguments mirror ``dispatch_block``
        (``kinds`` a side string or per-tick array; ``series_ids``
        scalar or per-tick; ``values`` columnar).  A block is a
        BARRIER in the worker's split: per-tick tickets queued before
        it dispatch before it and vice versa, so mixing
        ``submit``/``submit_many`` with blocks preserves every
        member's arrival order.  Quarantined members are checked at
        dispatch time (their ticks land in :attr:`BlockTicket.errors`
        as ``QuarantinedError`` while the rest of the block proceeds);
        ``deadline`` covers the whole block exactly like a per-tick
        ticket's."""
        if isinstance(kinds, str) and kinds not in ("right", "left"):
            raise ValueError(f"kinds must be 'right' or 'left', got "
                             f"{kinds!r}")
        dl = self._deadline(deadline)
        bt = BlockTicket(kinds, list(members), series_ids,
                         np.asarray(ts, np.int64), seq, values,
                         deadline=dl)
        self._put(bt, timeout, dl)
        return bt

    @staticmethod
    def _series_key(t: Ticket):
        return (id(t.member), t.series)

    def _split(self, group: List[Ticket]):
        """Block tickets are barriers: per-tick runs split on either
        side of each block (``_split_ticks``), the block itself is
        yielded whole — relative order of a member's per-tick and
        block traffic is preserved."""
        run: List[Ticket] = []
        for t in group:
            if isinstance(t, BlockTicket):
                if run:
                    yield from self._split_ticks(run)
                    run = []
                yield t
            else:
                run.append(t)
        if run:
            yield from self._split_ticks(run)

    def _split_ticks(self, group: List[Ticket]):
        """Cohort-aware micro-batching: member streams are independent
        merged streams, so ticks of DIFFERENT members may legally
        reorder around each other — only each member's own order is a
        contract.  Each tick lands in the earliest side-matching batch
        at or after its member's last batch (capped at ``batch_rows``
        rows per (member, series)), so a side-alternating tick mix
        collapses to ~one batch per side instead of a dispatch per
        side flip (which would pay the whole-cohort step cost for a
        handful of ticks).  Yields ``(tickets, max_rows)``."""
        batches: List[list] = []      # [kind, tickets, counts, max]
        last_idx: Dict[int, int] = {}
        cap = self.batch_rows
        for t in group:
            mid = id(t.member)
            key = (mid, t.series)
            placed = -1
            for bi in range(last_idx.get(mid, 0), len(batches)):
                b = batches[bi]
                if b[0] == t.kind and b[2].get(key, 0) < cap:
                    placed = bi
                    break
            if placed < 0:
                batches.append([t.kind, [t], {key: 1}, 1])
                placed = len(batches) - 1
            else:
                b = batches[placed]
                b[1].append(t)
                c = b[2].get(key, 0) + 1
                b[2][key] = c
                if c > b[3]:
                    b[3] = c
            last_idx[mid] = placed
        for b in batches:
            yield b[1], b[3]

    def _on_dropped(self, t: Ticket) -> None:
        # a deadline-dead ticket may have been the member's half-open
        # probe; free the probe slot so the member is not quarantined
        # forever by an outcome that will never arrive
        if t.member is not None:
            self.breaker.abandon(t.member.name)

    def _process(self, batch):
        if isinstance(batch, BlockTicket):
            return self._process_block(batch)
        batch, max_rows = batch
        kind = batch[0].kind
        try:
            items = [(t.member, t.series, t.ts, t.seq, t.values)
                     for t in batch]
            results = self.cohort.dispatch(kind, items)
        except Exception as e:       # dispatch-level failure: delivered
            for t in batch:          # per ticket, worker lives on
                t._finish(exc=e)
                self.breaker.record(t.member.name, ok=False)
            self._ring(batch)
            return
        self.batches += 1
        lats = self._latencies[kind]
        ok = 0
        for t, r in zip(batch, results):
            if isinstance(r, Exception):
                t._finish(exc=r)
                self.breaker.record(t.member.name, ok=False)
                continue
            t._finish(result=r)
            self.breaker.record(t.member.name, ok=True)
            ok += 1
            lats.append(t.t_done - t.t_submit)
        self.ticks += ok
        self._ring(batch)
        b = stream_mod._bucket(max_rows)
        self.bucket_hist[b] = self.bucket_hist.get(b, 0) + 1

    def _process_block(self, bt: BlockTicket):
        """One block ticket -> one ``dispatch_block``.  Breaker
        integration is sized for block rates: the quarantine pre-pass
        only runs when the breaker has EVER tripped (``trips`` never
        decrements, so a healthy fleet pays one integer check per
        block, not a lock round per tick), and successes are recorded
        only for members the breaker already tracks — ``record(ok)``
        setdefaults an entry per key, so blanket per-tick success
        recording would both grow the state dict by fleet size and
        take the breaker lock per tick."""
        members = bt.members
        kinds, series_ids = bt.kinds, bt.series_ids
        tsv, seqv, values = bt.tsv, bt.seqv, bt.values
        n_full = len(members)
        pre: Dict[int, Exception] = {}
        keep = None
        if self.breaker.trips:
            qexc: Dict[str, Exception] = {}
            with self.breaker._lock:
                open_names = {k for k, st in self.breaker._st.items()
                              if st[1] is not None}
            for name in ({m.name for m in members} & open_names):
                try:
                    self.breaker.allow(name, label="stream member")
                except QuarantinedError as e:
                    qexc[name] = e
            if qexc:
                keep = [i for i in range(n_full)
                        if members[i].name not in qexc]
                for i in range(n_full):
                    e = qexc.get(members[i].name)
                    if e is not None:
                        pre[i] = e
                ki = np.asarray(keep, np.intp)
                members = [members[i] for i in keep]
                if not isinstance(kinds, str):
                    kinds = np.asarray(kinds)[ki]
                if isinstance(series_ids, (list, tuple, np.ndarray)):
                    series_ids = [series_ids[i] for i in keep]
                tsv = np.asarray(tsv)[ki]
                if seqv is not None:
                    seqv = np.asarray(seqv)[ki]
                if values is not None:
                    values = {c: np.asarray(v)[ki]
                              for c, v in values.items()}
        try:
            out, errors = self.cohort.dispatch_block(
                kinds, members, series_ids, tsv, seq=seqv,
                values=values)
        except Exception as e:       # block-level failure: one result
            for m in members:
                self.breaker.record(m.name, ok=False)
            bt._errors = pre
            bt._finish(exc=e)
            self._ring([bt])
            return
        if keep is not None:
            # remap the kept-subset columns/errors back to full-length
            # block indices; quarantined rows keep their fill values
            errors = {keep[j]: e for j, e in errors.items()}
            full = {}
            for name, col in out.items():
                self.cohort._out_col(full, name, n_full)[
                    np.asarray(keep, np.intp)] = col
            out = full
        merged = dict(pre)
        merged.update(errors)
        for i, e in errors.items():
            self.breaker.record(bt.members[i].name, ok=False)
        if self.breaker._st:
            with self.breaker._lock:
                hot = {k for k, st in self.breaker._st.items()
                       if st[0] or st[1] is not None}
            if hot:
                for i, m in enumerate(bt.members):
                    if m.name in hot and i not in merged:
                        self.breaker.record(m.name, ok=True)
        bt._errors = merged
        bt._finish(result=out)
        self._ring([bt])
        self.batches += 1
        nok = n_full - len(merged)
        self.ticks += nok
        lat = bt.t_done - bt.t_submit
        if isinstance(bt.kinds, str):
            n_left = nok if bt.kinds == "left" else 0
        else:
            ka = np.asarray(bt.kinds)
            is_left = (ka == "left") if ka.dtype.kind in "UO" \
                else ka.astype(bool)
            ok_mask = np.ones(n_full, bool)
            for i in merged:
                ok_mask[i] = False
            n_left = int((is_left & ok_mask).sum())
        for side, cnt in (("right", nok - n_left), ("left", n_left)):
            if cnt:
                self._latencies[side].extend(
                    [lat] * min(cnt, LATENCY_WINDOW))
        b = stream_mod._bucket(max(1, nok))
        self.bucket_hist[b] = self.bucket_hist.get(b, 0) + 1

    # -- failover ------------------------------------------------------

    @classmethod
    def resume(cls, checkpoint_dir: str, *, verify: bool = True,
               mesh=None, stream_axis: str = "streams",
               queue_depth: Optional[int] = None,
               batch_rows: Optional[int] = None,
               coalesce_s: Optional[float] = None,
               breaker: Optional[CircuitBreaker] = None,
               **overrides) -> "CohortExecutor":
        """Failover in one call: restore the newest intact cohort
        snapshot (full or differential chain —
        :meth:`~tempo_tpu.serve.cohort.StreamCohort.resume`) and stand
        a fresh executor over it.  The resumed cohort's per-stream
        ``acked`` cursors tell each event source where to restart;
        replay the unacked tails through :meth:`submit_many` and the
        emissions are byte-identical to a plane that never died."""
        from tempo_tpu.serve.cohort import StreamCohort

        cohort = StreamCohort.resume(checkpoint_dir, verify=verify,
                                     mesh=mesh, stream_axis=stream_axis,
                                     **overrides)
        return cls(cohort, queue_depth=queue_depth,
                   batch_rows=batch_rows, coalesce_s=coalesce_s,
                   breaker=breaker)
