"""``StreamCohort``: fleet-scale serving — thousands of streams, ONE
step program.

``StreamingTSDF`` (serve/stream.py) is one stream per instance with its
own step executables: N streams means N Python objects and N tiny
dispatches, so aggregate throughput is dispatch-bound long before the
hardware is busy.  All incremental state is already explicit device
arrays (serve/state.py), so the batching dimension is free — stack it:

* **cohort state** — every carry array gains a leading ``[S]`` stream
  axis (``state.cohort_state_init``), one block per *shape bucket*:
  streams whose padded series-row count lands on the same power of two
  (:func:`row_bucket` — the executor's pow2 bucketing promoted to
  cohort membership) share one ``[S, ...]`` state block and ONE
  AOT-compiled push/query program (``state.cohort_push_jitted`` — the
  per-stream step under ``jax.vmap``, so each stream's slice of the
  cohort result is **bitwise** the single-stream program's output).
* **scatter admission** — a dispatch takes ticks from any number of
  member streams, validates each member against its own watermark rows
  of the cohort's ``[S, K]`` watermark planes (the same
  ``stream.admit_batch`` rule as the single-stream engine), and
  scatters the admitted ticks into one padded ``[S, K, Lb]`` batch.
  Idle slots ride along as masked no-op rows — the step leaves their
  state bit-identical — so per-push work is one scatter plus one
  executable call regardless of how many streams ticked.
* **per-stream isolation** — a late tick rejects only its own member's
  rows: that member's sub-batch is zeroed out of the dispatch (its
  tickets get the :class:`~tempo_tpu.serve.stream.LateTickError`), the
  rest of the cohort steps normally, and the rejected member's state
  and watermarks stay untouched (commit-after-success per member).
* **mesh scale-out** — with a ``mesh``, the ``[S]`` axis is sharded
  across devices via explicit ``in_shardings``/``out_shardings``
  (``dist.stream_shardings``) with whole-state donation: no op in the
  step mixes streams, so the compiled HLO carries **zero per-push
  collectives** (asserted by the ``serve.cohort_push`` compiled
  contract and the ``--only-fleet-serving`` bench) and scale-out is
  embarrassingly stream-parallel.
* **durability** — ``snapshot()`` writes ONE CRC'd artifact for the
  whole cohort (``checkpoint.save_state(kind="cohort_state")``);
  :meth:`StreamCohort.resume` restores it and reports per-stream
  ``acked`` so only each stream's unacknowledged tail replays.

Semantics are the single-stream engine's, exactly: results are bitwise
equal to S independent ``StreamingTSDF`` instances fed the same
per-stream events at any push interleaving (tests/test_cohort.py pins
the matrix), per-stream watermarks and ``maxLookback`` expiry
included.
"""

from __future__ import annotations

import hashlib
import logging
import os
import shutil
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tempo_tpu import checkpoint as ckpt
from tempo_tpu import config, resilience
from tempo_tpu.packing import TS_PAD
from tempo_tpu.serve import state as sst
from tempo_tpu.serve import stream as stream_mod
from tempo_tpu.serve.stream import LateTickError, _SIDE_LEFT, _SIDE_RIGHT

logger = logging.getLogger(__name__)

#: per-state-array position of the SERIES axis (without the leading
#: stream axis); everything not listed keeps it last.  Used by slot
#: reset and bucket migration, which copy/clear series-row prefixes.
_K_AXIS = {"ring_ts": -2, "ring_x": -2, "ring_valid": -2}


def row_bucket(n: int) -> int:
    """Cohort membership: padded series-row count of a stream — next
    power of two, floor 1.  Streams sharing a bucket share one state
    block and one step program; a stream that outgrows its bucket
    migrates to the next one (:meth:`CohortMember.add_series`)."""
    if n < 1:
        raise ValueError("a stream needs at least one series")
    b = 1
    while b < n:
        b *= 2
    return b


def _k_slice(arr_ndim: int, name: str, k: int) -> tuple:
    """Indexer selecting the first ``k`` series rows of a PER-SLOT
    state array (no stream axis)."""
    ax = _K_AXIS.get(name, -1) % arr_ndim
    sl = [slice(None)] * arr_ndim
    sl[ax] = slice(0, k)
    return tuple(sl)


class _Singles:
    """Per-dispatch accumulator for single-tick members (the fleet
    regime): plain python lists, turned into ONE set of index arrays
    and ONE vectorized watermark check in ``_dispatch_group``."""

    __slots__ = ("members", "idxs", "slots", "rows", "ts", "sqf",
                 "planes")

    def __init__(self, n_cols: int):
        self.members: List[CohortMember] = []
        self.idxs: List[int] = []
        self.slots: List[int] = []
        self.rows: List[int] = []
        self.ts: List[int] = []
        self.sqf: List[float] = []
        self.planes: List[List[float]] = [[] for _ in range(n_cols)]


class CohortMember:
    """One stream of a cohort: the ``StreamingTSDF``-shaped handle
    (``push`` / ``push_left`` with the same argument and emission
    contract), backed by one slot of its bucket group's stacked state.
    Single-writer like the standalone frame; route concurrent traffic
    through :class:`~tempo_tpu.serve.executor.CohortExecutor`."""

    def __init__(self, cohort: "StreamCohort", name: str,
                 series: Sequence):
        self.cohort = cohort
        self.name = str(name)
        self.series = list(series)
        if len(set(self.series)) != len(self.series):
            raise ValueError("duplicate series keys")
        self._row = {s: k for k, s in enumerate(self.series)}
        self.acked = 0
        self._group: Optional["_Group"] = None
        self.slot: Optional[int] = None
        # spill tier: the bucket a non-resident member belongs to
        # (``_group is None`` = spilled or never-allocated cold member)
        self._spill_bucket: Optional[int] = None

    @property
    def resident(self) -> bool:
        """True when this member holds a live slot (hot tier); False
        when its state is spilled to a CRC'd artifact (or it has never
        ticked and its fresh state needs no artifact at all)."""
        return self._group is not None

    @property
    def bucket(self) -> int:
        """The member's current shape bucket (padded series rows)."""
        if self._group is None:
            return int(self._spill_bucket)
        return self._group.cfg.n_series

    # -- the StreamingTSDF-shaped surface ------------------------------

    def push(self, series_ids, ts, values: Dict[str, np.ndarray],
             seq=None) -> Dict[str, np.ndarray]:
        """Ingest right-side ticks for this stream (parallel arrays,
        same contract as ``StreamingTSDF.push``) — dispatched as this
        member's sub-batch of one cohort step."""
        items = self._items(series_ids, ts, seq, values)
        return self._collect(self.cohort.dispatch("right", items))

    def push_left(self, series_ids, ts, seq=None) -> Dict[str, np.ndarray]:
        """Answer AS-OF queries for new left rows (the
        ``StreamingTSDF.push_left`` contract)."""
        items = self._items(series_ids, ts, seq, None)
        return self._collect(self.cohort.dispatch("left", items))

    def _items(self, series_ids, ts, seq, values):
        ts = np.atleast_1d(np.asarray(ts, np.int64))
        series_ids = list(np.atleast_1d(np.asarray(series_ids, object)))
        n = len(series_ids)
        if len(ts) != n:
            raise ValueError(
                f"series_ids and ts are parallel arrays: got {n} "
                f"series ids but {len(ts)} timestamps")
        if seq is not None and len(np.atleast_1d(seq)) != n:
            raise ValueError(
                f"seq must align with series_ids: "
                f"{len(np.atleast_1d(seq))} != {n}")
        seqa = (np.full(n, None, object) if seq is None
                else list(np.atleast_1d(np.asarray(seq, object))))
        if values is None:
            return [(self, series_ids[i], int(ts[i]), seqa[i], None)
                    for i in range(n)]
        rows = []
        for i in range(n):
            row = {}
            for col, v in values.items():
                v = np.atleast_1d(np.asarray(v, np.float32))
                if len(v) != n:
                    raise ValueError(
                        f"values[{col!r}] must align with series_ids: "
                        f"{len(v)} != {n}")
                row[col] = v[i]
            rows.append((self, series_ids[i], int(ts[i]), seqa[i], row))
        return rows

    @staticmethod
    def _collect(results) -> Dict[str, np.ndarray]:
        for r in results:
            if isinstance(r, Exception):
                raise r
        if not results:
            return {}
        return {k: np.array([r[k] for r in results])
                for k in results[0]}

    # -- growth / introspection ----------------------------------------

    def add_series(self, new_series: Sequence) -> None:
        """Extend this stream's series set.  Within the current bucket
        the new rows are already-fresh state; outgrowing it migrates
        the stream to the next bucket's group (its carries copied
        bit-for-bit, the new rows fresh) — cohort membership follows
        the shape bucket, not the object."""
        new_series = list(new_series)
        dup = [s for s in new_series if s in self._row]
        if dup or len(set(new_series)) != len(new_series):
            raise ValueError(f"duplicate series keys: {dup or new_series}")
        self.cohort._grow_member(self, new_series)

    @property
    def clipped(self) -> int:
        """Rows of THIS stream whose true stats window exceeded the
        declared row bound (truncated — the declared-bound audit)."""
        if not self.cohort.cfg_has_window:
            return 0
        if self._group is None:
            # spilled member: its counts live in the artifact (a
            # never-ticked cold member has no artifact and no clips)
            arrays = self.cohort._spilled_arrays(self)
            if arrays is None:
                return 0
            return int(np.asarray(
                arrays["s.clipped"])[:len(self.series)].sum())
        plane = np.asarray(self._group.state["clipped"])
        return int(plane[self.slot, :len(self.series)].sum())


class _Group:
    """One shape bucket's stacked state: ``[S, ...]`` arrays for up to
    ``capacity`` member slots, plus the watermark planes and the pinned
    per-bucket executables."""

    def __init__(self, cohort: "StreamCohort", bucket: int,
                 capacity: int):
        self.cohort = cohort
        self.bucket = bucket
        self.cfg = cohort._member_cfg(bucket)
        self.capacity = capacity
        self.state = sst.cohort_state_init(self.cfg, capacity)
        self._slot_init = sst.init_state(self.cfg)
        self.wm_ts = np.full((capacity, bucket), sst._FAR_PAST, np.int64)
        self.wm_seq = np.full((capacity, bucket), -np.inf, np.float64)
        self.wm_side = np.zeros((capacity, bucket), np.int8)
        self.members: List[Optional[CohortMember]] = [None] * capacity
        self._free = list(range(capacity - 1, -1, -1))
        # per-group strong refs to built executables, keyed (kind, Lb):
        # the zero-recompile steady state of a live cohort must not
        # hinge on the shared LRU surviving eviction pressure
        self._exes: Dict[Tuple[str, int], object] = {}

    def alloc(self, member: CohortMember) -> int:
        if not self._free:
            self._grow()
        slot = self._free.pop()
        self.members[slot] = member
        member._group, member.slot = self, slot
        self.cohort._dirty.add(self.bucket)
        return slot

    def release(self, slot: int) -> None:
        """Free a slot and reset its state/watermark rows to fresh
        init, so the slot is inert (masked no-op) until reused."""
        self.cohort._dirty.add(self.bucket)
        self.members[slot] = None
        self._host()
        for name, arr in self.state.items():
            arr[slot] = self._slot_init[name]
        self.wm_ts[slot] = sst._FAR_PAST
        self.wm_seq[slot] = -np.inf
        self.wm_side[slot] = 0
        self._free.append(slot)

    def _grow(self) -> None:
        """Double the slot capacity (stays a multiple of the mesh's
        stream-axis size).  A capacity change is a new program shape —
        admission-time, never steady-state — so the pinned executables
        reset."""
        add = self.capacity
        self._host()
        tail = sst.cohort_state_init(self.cfg, add)
        self.state = {k: np.concatenate([self.state[k], tail[k]], axis=0)
                      for k in self.state}
        self.wm_ts = np.concatenate(
            [self.wm_ts, np.full((add, self.bucket), sst._FAR_PAST,
                                 np.int64)])
        self.wm_seq = np.concatenate(
            [self.wm_seq, np.full((add, self.bucket), -np.inf,
                                  np.float64)])
        self.wm_side = np.concatenate(
            [self.wm_side, np.zeros((add, self.bucket), np.int8)])
        self.members.extend([None] * add)
        self._free.extend(range(self.capacity + add - 1,
                                self.capacity - 1, -1))
        self.capacity += add
        self._exes = {}
        self.cohort._dirty.add(self.bucket)

    def _host(self) -> None:
        """Materialize the state block on host (numpy, writable) for
        slot-level surgery (alloc-reset, growth, migration,
        snapshot)."""
        out = {}
        for k, v in self.state.items():
            a = np.asarray(v)
            if not a.flags.writeable:   # device arrays view read-only
                a = np.array(a)
            out[k] = a
        self.state = out

    _BUILDERS = {
        "push": sst.cohort_push_executable,
        "query": sst.cohort_query_executable,
        # block kinds: the second key is the pow2 TICK-count bucket Nb,
        # not a per-series row bucket (the block step always runs at
        # the singles lane width, state.block_lanes())
        "block_push": sst.cohort_block_push_executable,
        "block_query": sst.cohort_block_query_executable,
    }

    def executable(self, kind: str, Lb: int):
        exe = self._exes.get((kind, Lb))
        if exe is None:
            exe = self._BUILDERS[kind](
                self.cfg, self.capacity, Lb,
                self.cohort.mesh, self.cohort.stream_axis)
            self._exes[(kind, Lb)] = exe
        return exe

    def n_members(self) -> int:
        return sum(m is not None for m in self.members)


class StreamCohort:
    """See module docstring.  Shared shape config (``value_cols``,
    ``skip_nulls``, ``max_lookback``, window, ``ema_alpha``) fixes the
    operator set for every member; ``add_stream`` admits streams with
    arbitrary series sets, grouped by shape bucket.  ``mesh`` (with
    ``stream_axis``) shards every bucket's stream axis across devices;
    slot capacities are rounded up to the axis size.  ``slots`` is the
    initial per-bucket slot capacity (default
    ``TEMPO_TPU_SERVE_COHORT_SLOTS``); groups grow by doubling.
    ``diff_snapshots`` (default ``TEMPO_TPU_SERVE_COHORT_DIFF``) makes
    automatic snapshots differential — only dirty bucket groups,
    chained to the last full artifact by CRC'd manifests — with every
    ``full_every``-th automatic snapshot full."""

    def __init__(self, value_cols: Sequence[str], *,
                 skip_nulls: bool = True, max_lookback: int = 0,
                 window_secs=None, window_rows_bound: int = 64,
                 ema_alpha=None, mesh=None, stream_axis: str = "streams",
                 slots: Optional[int] = None,
                 checkpoint_dir: Optional[str] = None,
                 ckpt_every: Optional[int] = None, keep_last: int = 3,
                 diff_snapshots: Optional[bool] = None,
                 full_every: int = 16,
                 spill_dir: Optional[str] = None,
                 resident_budget: Optional[int] = None):
        self.value_cols = [str(c) for c in value_cols]
        self.skip_nulls = bool(skip_nulls)
        self.max_lookback = int(max_lookback)
        self.window_ns = (None if window_secs is None
                          else sst.window_ns(window_secs))
        self.rows_bound = int(window_rows_bound)
        self.ema_alpha = (None if ema_alpha is None else float(ema_alpha))
        self.mesh = mesh
        self.stream_axis = str(stream_axis)
        if slots is None:
            slots = config.get_int("TEMPO_TPU_SERVE_COHORT_SLOTS", 1024)
        self._slots = max(1, int(slots))
        if mesh is not None:
            n_axis = int(mesh.shape[self.stream_axis])
            self._slots = -(-self._slots // n_axis) * n_axis
        self._groups: Dict[int, _Group] = {}
        self._members: Dict[str, CohortMember] = {}
        self.acked_total = 0
        self.dispatches = 0
        self.checkpoint_dir = checkpoint_dir
        self.keep_last = int(keep_last)
        if ckpt_every is None:
            ckpt_every = config.get_int(
                "TEMPO_TPU_SERVE_COHORT_CKPT_EVERY", 0)
        self.ckpt_every = int(ckpt_every or 0)
        self._next_ckpt = self.ckpt_every or None
        self._emit_cache: Dict[tuple, list] = {}
        # -- incremental failover state: buckets whose stacked state /
        # watermarks / capacity changed since the previous snapshot
        # (ANY kind), the chain anchors, and the auto-snapshot policy
        if diff_snapshots is None:
            diff_snapshots = config.get_bool(
                "TEMPO_TPU_SERVE_COHORT_DIFF", False)
        self.diff_snapshots = bool(diff_snapshots)
        self.full_every = max(1, int(full_every))
        self._dirty: set = set()
        self._last_snapshot: Optional[str] = None
        self._last_full: Optional[str] = None
        self._diffs_since_full = 0
        # -- tiered member state: with a spill_dir, cold members live
        # as CRC'd kind="cohort_member" artifacts instead of slots —
        # "millions registered, resident_budget hot".  0 = unlimited
        # (no LRU eviction; explicit spill() still works).
        self.spill_dir = spill_dir
        if resident_budget is None:
            resident_budget = config.get_int(
                "TEMPO_TPU_SERVE_COHORT_RESIDENT", 0)
        self.resident_budget = max(0, int(resident_budget))
        if self.resident_budget and not self.spill_dir:
            raise ValueError(
                "a resident_budget needs a spill_dir to evict into")
        self._spilled: Dict[str, str] = {}   # member name -> artifact
        self._lru: Dict[str, None] = {}      # resident members, LRU order
        self._resident = 0
        self.spills = 0
        self.restores = 0

    # -- membership ----------------------------------------------------

    @property
    def cfg_has_window(self) -> bool:
        return self.window_ns is not None

    def _member_cfg(self, bucket: int) -> sst.StreamConfig:
        cfg = sst.StreamConfig(
            n_series=bucket, n_cols=len(self.value_cols),
            skip_nulls=self.skip_nulls, max_lookback=self.max_lookback,
            window_ns=self.window_ns, rows_bound=self.rows_bound,
            ema_alpha=self.ema_alpha)
        return cfg

    def _group(self, bucket: int) -> _Group:
        g = self._groups.get(bucket)
        if g is None:
            g = self._groups[bucket] = _Group(self, bucket, self._slots)
            self._dirty.add(bucket)
        return g

    def add_stream(self, name: str, series: Sequence) -> CohortMember:
        """Admit a stream: allocate a slot in its shape bucket's group
        (creating/growing the group as needed) and return its handle.

        With a ``resident_budget``, admission past the budget registers
        the stream COLD: no slot, no artifact (a fresh slot IS the init
        state, so nothing needs persisting) — it faults into a slot on
        its first tick.  Registration is O(1) regardless of fleet
        size."""
        name = str(name)
        if name in self._members:
            raise ValueError(f"stream {name!r} already exists")
        member = CohortMember(self, name, series)
        bucket = row_bucket(len(member.series))
        if self.resident_budget and self._resident >= self.resident_budget:
            member._spill_bucket = bucket
        else:
            self._group(bucket).alloc(member)
            self._resident += 1
            self._lru[name] = None
        self._members[name] = member
        return member

    def stream(self, name: str) -> CohortMember:
        return self._members[str(name)]

    @property
    def n_streams(self) -> int:
        return len(self._members)

    @property
    def acked(self) -> Dict[str, int]:
        """Per-stream acknowledged-event counts (the replay cursors a
        resumed server restarts its event sources from)."""
        return {name: m.acked for name, m in self._members.items()}

    @property
    def clipped(self) -> int:
        if not self.cfg_has_window:
            return 0
        total = 0
        for g in self._groups.values():
            plane = np.asarray(g.state["clipped"])
            for m in g.members:
                if m is not None:
                    total += int(plane[m.slot, :len(m.series)].sum())
        for name in self._spilled:
            total += self._members[name].clipped
        return total

    def _grow_member(self, member: CohortMember,
                     new_series: Sequence) -> None:
        if member._group is None:    # spilled: surgery needs a slot
            self._fault_in(member)
        new_k = len(member.series) + len(new_series)
        old_g, old_slot = member._group, member.slot
        target = row_bucket(new_k)
        if target == old_g.bucket:
            # in-bucket growth: the new rows are untouched init rows of
            # the same slot — already bit-fresh, nothing to move; the
            # SERIES SET changed though, and it rides snapshot
            # manifests, so the bucket is snapshot-dirty
            member.series.extend(new_series)
            member._row = {s: k for k, s in enumerate(member.series)}
            self._dirty.add(old_g.bucket)
            return
        new_g = self._group(target)
        slot = new_g.alloc(member)   # re-pins member._group/.slot
        old_g._host()
        new_g._host()
        k_old = old_g.bucket
        for name in new_g.state:
            src = old_g.state[name][old_slot]
            dst = new_g.state[name][slot]
            dst[_k_slice(dst.ndim, name, k_old)] = \
                src[_k_slice(src.ndim, name, k_old)]
        new_g.wm_ts[slot, :k_old] = old_g.wm_ts[old_slot, :k_old]
        new_g.wm_seq[slot, :k_old] = old_g.wm_seq[old_slot, :k_old]
        new_g.wm_side[slot, :k_old] = old_g.wm_side[old_slot, :k_old]
        old_g.release(old_slot)
        member.series.extend(new_series)
        member._row = {s: k for k, s in enumerate(member.series)}

    # -- the cohort step -----------------------------------------------

    def dispatch(self, side: str, items: List[tuple]) -> List[object]:
        """Run ONE cohort step per touched bucket group over a tick
        list ``[(member, series_key, ts, seq_or_None, values_or_None)]``
        (arrival order; ``side`` 'right' = data pushes, 'left' = AS-OF
        queries).  Returns a list parallel to ``items``: the per-tick
        emission dict, or the exception that rejected that member's
        sub-batch — **per-stream isolation**: a late tick (or bad
        payload) zeroes only its own member's rows out of the step,
        every other member's results and state are bit-identical to a
        dispatch that never contained the offender."""
        if side not in ("right", "left"):
            raise ValueError(f"side must be 'right' or 'left', got "
                             f"{side!r}")
        side_i = _SIDE_RIGHT if side == "right" else _SIDE_LEFT
        right = side_i == _SIDE_RIGHT
        results: List[object] = [None] * len(items)
        # first occurrence stored as a bare int (the fleet regime is
        # one tick per member — no per-tick list allocation), demoted
        # to an index list on a second tick from the same member
        by_member: Dict[int, object] = {}
        for i, it in enumerate(items):
            key = id(it[0])
            prev = by_member.get(key)
            if prev is None:
                by_member[key] = i
            elif type(prev) is int:
                by_member[key] = [prev, i]
            else:
                prev.append(i)

        # spill tier: fault cold members back into slots BEFORE
        # admission — per-member isolation holds here too: a corrupt or
        # foreign member artifact rejects only that member's ticks (the
        # refusal delivered by name as their result), never the
        # dispatch
        dead: set = set()
        touched: List[CohortMember] = []
        if self.spill_dir is not None:
            for key, idxs in by_member.items():
                member = items[idxs if type(idxs) is int else idxs[0]][0]
                if member.cohort is not self:
                    continue       # admission loop raises, as ever
                touched.append(member)
                if member._group is not None:
                    continue
                try:
                    self._fault_in(member)
                except Exception as e:  # noqa: BLE001 - per member
                    dead.add(key)
                    for i in ([idxs] if type(idxs) is int else idxs):
                        results[i] = e

        # per-member admission: validate payloads + watermark order,
        # assign lanes; a failing member is recorded and EXCLUDED.
        # Single-tick members take a deferred path: payloads validated
        # here (python scalars), the watermark predicate evaluated
        # VECTORIZED against the group's [S, K] planes inside
        # _dispatch_group — per-member numpy work is the aggregate
        # throughput bottleneck otherwise
        groups: Dict[int, List] = {}
        singles: Dict[int, "_Singles"] = {}
        n_cols = len(self.value_cols)
        for key, idxs in by_member.items():
            if key in dead:
                continue
            if type(idxs) is int:
                i = idxs
                member, skey, ts, sq, vals = items[i]
                if member.cohort is not self:
                    raise ValueError(
                        f"stream {member.name!r} belongs to a "
                        f"different cohort")
                try:
                    k, ts, sqf, row = self._admit_tick(
                        member, skey, ts, sq, vals, right)
                except Exception as e:  # noqa: BLE001 - per tick
                    results[i] = e
                    continue
                bucket = member._group.bucket
                sg = singles.get(bucket)
                if sg is None:
                    sg = singles[bucket] = _Singles(n_cols)
                sg.members.append(member)
                sg.idxs.append(i)
                sg.slots.append(member.slot)
                sg.rows.append(k)
                sg.ts.append(ts)
                sg.sqf.append(sqf)
                if row is not None:
                    planes = sg.planes
                    for c in range(n_cols):
                        planes[c].append(row[c])
                continue
            member = items[idxs[0]][0]
            if member.cohort is not self:
                raise ValueError(
                    f"stream {member.name!r} belongs to a different "
                    f"cohort")
            try:
                rec = self._admit_member(member, items, idxs, side_i)
            except Exception as e:  # noqa: BLE001 - delivered per tick
                for i in idxs:
                    results[i] = e
                continue
            groups.setdefault(member._group.bucket, []).append(
                (member, idxs, rec))

        for bucket in set(groups) | set(singles):
            self._dispatch_group(self._groups[bucket], side_i,
                                 groups.get(bucket, ()),
                                 singles.get(bucket), results)
            self._dirty.add(bucket)
        self.dispatches += 1
        # spill tier: everything that dispatched is hot (move to MRU),
        # then evict coldest residents past the budget — never a member
        # of THIS dispatch
        if self.spill_dir is not None and self.resident_budget:
            for m in touched:
                if m._group is not None:
                    self._lru.pop(m.name, None)
                    self._lru[m.name] = None
            self._enforce_budget({m.name for m in touched})
        self._maybe_snapshot()
        return results

    def _admit_tick(self, member: CohortMember, skey, ts, sq, vals,
                    right: bool):
        """Scalar per-tick validation shared by the singles fast path
        and the multi-tick ``_admit_member`` loop — ONE copy of the
        series-row lookup, the NULLS-FIRST seq normalization (None and
        ANY NaN, numpy scalars included, map to -inf — the
        ``StreamingTSDF._seq_array`` rule; an un-normalized NaN would
        poison the watermark and silently stop rejecting late ticks),
        and the payload check.  Returns ``(k, ts, sqf, row)``."""
        k = member._row.get(skey)
        if k is None:
            raise ValueError(
                f"unknown series {skey!r} on stream {member.name!r}: "
                f"a cohort stream's series set grows only through "
                f"add_series")
        ts = int(ts)
        if sq is None:
            sqf = -np.inf
        else:
            sqf = float(sq)
            if sqf != sqf:               # NaN of any flavour
                sqf = -np.inf            # NULLS FIRST
        row = None
        if right:
            if vals is None:
                raise ValueError(
                    f"right tick on stream {member.name!r} has no "
                    f"values")
            # python float(): validates per member (a bad payload
            # rejects only its own sub-batch); the f32 cast lands at
            # the batch-array build, bit-equal to a per-tick
            # np.float32() cast
            row = [float(vals[col]) if col in vals else
                   self._missing_col(member, col)
                   for col in self.value_cols]
        return k, ts, sqf, row

    def _missing_col(self, member, col):
        raise ValueError(
            f"push on stream {member.name!r} is missing value column "
            f"{col!r} (cohort columns: {self.value_cols})")

    def _admit_member(self, member: CohortMember, items, idxs,
                      side_i: int):
        """Validate one member's sub-batch (payloads first, then the
        merged-stream watermark rule — the same ordering predicate as
        ``stream.admit_batch``, evaluated against this member's rows
        of the group's watermark planes) — any failure rejects the
        whole sub-batch atomically, exactly like a standalone
        ``StreamingTSDF`` push.  Scalar-path implementation: the fleet
        regime is thousands of members with a tick or two each per
        dispatch, so per-member numpy allocation is the aggregate
        bottleneck — everything here is python scalars and lists until
        the group-level scatter."""
        g, slot = member._group, member.slot
        gw_ts, gw_seq, gw_side = g.wm_ts, g.wm_seq, g.wm_side
        n_cols = len(self.value_cols)
        right = side_i == _SIDE_RIGHT
        rows, lanes, ts_l = [], [], []
        planes = [[] for _ in range(n_cols)] if right else None
        cand: Dict[int, tuple] = {}     # candidate watermark per row
        lane_ct: Dict[int, int] = {}
        for i in idxs:
            _, skey, ts, sq, vals = items[i]
            k, ts, sqf, row = self._admit_tick(member, skey, ts, sq,
                                               vals, right)
            key = (ts, sqf, side_i)
            wm = cand.get(k)
            if wm is None:
                wm = (gw_ts[slot, k].item(), gw_seq[slot, k].item(),
                      gw_side[slot, k].item())
            if key < wm:
                raise LateTickError(
                    f"{member.name}/{member.series[k]!r}", ts, sqf,
                    side_i, wm)
            cand[k] = key
            if right:
                for c in range(n_cols):
                    planes[c].append(row[c])
            rows.append(k)
            lane = lane_ct.get(k, 0)
            lane_ct[k] = lane + 1
            lanes.append(lane)
            ts_l.append(ts)
        return dict(rows=rows, lanes=lanes, lane_ct=lane_ct, wm=cand,
                    ts=ts_l, planes=planes)

    def _put(self, group: _Group, a):
        if self.mesh is None:
            return a
        import jax

        from tempo_tpu import dist

        return jax.device_put(
            a, dist.stream_shardings(self.mesh, self.stream_axis, a))

    def _emit_fields(self, keys) -> List[Tuple[str, str, int]]:
        """Flattened per-tick output fields ``(out_name, emit_key,
        col_index)`` for an emission-key set, cached — dict keys are
        rebuilt per tick, their NAMES are not."""
        cache_key = tuple(keys)
        fields = self._emit_cache.get(cache_key)
        if fields is None:
            fields = [(f"{col}_{key}", key, c)
                      for key in cache_key
                      for c, col in enumerate(self.value_cols)]
            self._emit_cache[cache_key] = fields
        return fields

    def _dispatch_group(self, g: _Group, side_i: int, recs, sg, results):
        """Scatter the admitted sub-batches into one ``[S, K, Lb]``
        cohort batch, run the bucket's step program once, commit each
        admitted member's watermarks, and fan the emissions back out
        per tick.  Single-tick members (``sg``) are admitted here with
        ONE vectorized watermark check; everything is one numpy
        scatter in and one gather per emission plane out, so per-tick
        python work is bounded by the result-dict build."""
        S, K, C = g.capacity, g.bucket, len(self.value_cols)
        max_rows = 1
        n_total = 0
        spans = []                     # (member, idxs, rec, pos0)
        slots_l: List[int] = []
        rows_l: List[int] = []
        lanes_l: List[int] = []
        ts_l: List[int] = []
        for member, idxs, rec in recs:
            m = max(rec["lane_ct"].values())
            if m > max_rows:
                max_rows = m
            spans.append((member, idxs, rec, n_total))
            n_total += len(idxs)
            slot = member.slot
            slots_l.extend([slot] * len(rec["rows"]))
            rows_l.extend(rec["rows"])
            lanes_l.extend(rec["lanes"])
            ts_l.extend(rec["ts"])
        sl = np.asarray(slots_l, np.int64)
        rw = np.asarray(rows_l, np.int64)
        ln = np.asarray(lanes_l, np.int64)
        tsv = np.asarray(ts_l, np.int64)

        # ---- singles: ONE vectorized admission over the [S, K]
        # watermark planes (key < wm, lexicographic on (ts, seq, side))
        s_members, s_idxs = [], []
        s_sl = s_rw = s_ts = s_sq = None
        s_planes = None
        if sg is not None and sg.idxs:
            s_sl = np.asarray(sg.slots, np.int64)
            s_rw = np.asarray(sg.rows, np.int64)
            s_ts = np.asarray(sg.ts, np.int64)
            s_sq = np.asarray(sg.sqf, np.float64)
            s_members, s_idxs = sg.members, sg.idxs
            wts = g.wm_ts[s_sl, s_rw]
            wsq = g.wm_seq[s_sl, s_rw]
            wsd = g.wm_side[s_sl, s_rw]
            late = (s_ts < wts) | (
                (s_ts == wts) & ((s_sq < wsq) |
                                 ((s_sq == wsq) & (side_i < wsd))))
            if side_i == _SIDE_RIGHT:
                s_planes = [np.asarray(p, np.float32)
                            for p in sg.planes]
            if late.any():
                for j in np.nonzero(late)[0]:
                    m = s_members[j]
                    results[s_idxs[j]] = LateTickError(
                        f"{m.name}/{m.series[int(s_rw[j])]!r}",
                        int(s_ts[j]), float(s_sq[j]), side_i,
                        (int(wts[j]), float(wsq[j]), int(wsd[j])))
                keep = np.nonzero(~late)[0]
                s_members = [s_members[j] for j in keep]
                s_idxs = [s_idxs[j] for j in keep]
                s_sl, s_rw = s_sl[keep], s_rw[keep]
                s_ts, s_sq = s_ts[keep], s_sq[keep]
                if s_planes is not None:
                    s_planes = [p[keep] for p in s_planes]
            if len(s_idxs):
                sl = np.concatenate([sl, s_sl])
                rw = np.concatenate([rw, s_rw])
                ln = np.concatenate([ln, np.zeros(len(s_idxs),
                                                  np.int64)])
                tsv = np.concatenate([tsv, s_ts])
        if not len(sl):          # every member of this bucket rejected
            return
        Lb = stream_mod._bucket(max_rows)
        counts = np.zeros((S, K), np.int64)
        for member, _, rec, _ in spans:
            slot = member.slot
            for k, c in rec["lane_ct"].items():
                counts[slot, k] = c
        if len(s_idxs):
            counts[s_sl, s_rw] = 1

        if side_i == _SIDE_RIGHT:
            ts_p = np.full((S, K, Lb), TS_PAD, np.int64)
            xs = np.full((S, C, K, Lb), np.nan, np.float32)
            mask = np.zeros((S, K, Lb), bool)
            ts_p[sl, rw, ln] = tsv
            mask[sl, rw, ln] = True
            for c in range(C):
                col = [v for _, _, rec, _ in spans
                       for v in rec["planes"][c]]
                colv = np.asarray(col, np.float32)
                if len(s_idxs):
                    colv = np.concatenate([colv, s_planes[c]])
                xs[sl, c, rw, ln] = colv
            exe = g.executable("push", Lb)
            args = [self._put(g, v) for v in g.state.values()]
            new_state, emits = exe(*args, self._put(g, ts_p),
                                   self._put(g, xs), self._put(g, mask),
                                   self._put(g, counts))
            g.state = dict(zip(g.cfg.state_names(), new_state))
            # one gather per emission plane: [N, C] per key, then one
            # bounded dict build per tick
            fields = self._emit_fields(emits.keys())
            gathered = {key: np.asarray(plane)[sl, :, rw, ln]
                        for key, plane in emits.items()}
            flat = [(name, gathered[key][:, c])
                    for name, key, c in fields]
            for member, idxs, rec, pos0 in spans:
                self._commit(member, rec, len(idxs))
                for j, i in enumerate(idxs):
                    p = pos0 + j
                    results[i] = {name: arr[p] for name, arr in flat}
            for j, i in enumerate(s_idxs):
                p = n_total + j
                results[i] = {name: arr[p] for name, arr in flat}
        else:
            exe = g.executable("query", Lb)
            args = [self._put(g, g.state[n]) for n in sst._QUERY_STATE]
            new_n_merged, (vals, found, idx) = exe(*args,
                                                   self._put(g, counts))
            g.state["n_merged"] = new_n_merged
            v_g = np.asarray(vals)[sl, :, rw, ln]      # [N, C]
            f_g = np.asarray(found)[sl, :, rw, ln]
            i_g = np.asarray(idx)[sl, rw, ln]
            flat = [(col, v_g[:, c])
                    for c, col in enumerate(self.value_cols)]
            flat += [(f"{col}_found", f_g[:, c])
                     for c, col in enumerate(self.value_cols)]
            for member, idxs, rec, pos0 in spans:
                self._commit(member, rec, len(idxs))
                for j, i in enumerate(idxs):
                    p = pos0 + j
                    out = {name: arr[p] for name, arr in flat}
                    out["right_row_idx"] = i_g[p]
                    results[i] = out
            for j, i in enumerate(s_idxs):
                p = n_total + j
                out = {name: arr[p] for name, arr in flat}
                out["right_row_idx"] = i_g[p]
                results[i] = out

        # singles commit: vectorized watermark advance + acked
        if len(s_idxs):
            g.wm_ts[s_sl, s_rw] = s_ts
            g.wm_seq[s_sl, s_rw] = s_sq
            g.wm_side[s_sl, s_rw] = side_i
            for m in s_members:
                m.acked += 1
            self.acked_total += len(s_idxs)

    def _commit(self, member: CohortMember, rec, n_ticks: int) -> None:
        g, slot = member._group, member.slot
        wm_ts, wm_seq, wm_side = g.wm_ts, g.wm_seq, g.wm_side
        for k, (t, sq, sd) in rec["wm"].items():
            wm_ts[slot, k] = t
            wm_seq[slot, k] = sq
            wm_side[slot, k] = sd
        member.acked += n_ticks
        self.acked_total += n_ticks

    # -- batched native dispatch ---------------------------------------

    def dispatch_block(self, kinds, members, series_ids, ts, seq=None,
                       values=None):
        """Dispatch a columnar tick BLOCK: parallel arrays instead of a
        per-tick item list, and (for the single-tick-per-(member,
        series) majority) ONE device program per side that scatters the
        whole block into the padded batch on device, steps, and gathers
        the emissions back compact (``state.cohort_block_push/
        query_executable``) — the host never builds or reads an
        ``[S, ...]`` array, which is the per-tick path's dispatch
        floor.

        ``kinds`` is ``'right'``/``'left'`` for a side-homogeneous
        block, or a per-tick array (booleans, True = left/query, or the
        side strings).  ``series_ids`` is one key applied to every tick
        or a per-tick sequence; ``ts`` int64 per tick; ``seq`` optional
        per-tick floats (NaN = no sequence number, NULLS FIRST);
        ``values`` maps every cohort value column to a float32 array
        (required when the block has data ticks).

        Returns ``(out, errors)``: ``out`` maps each emission field to
        a full-length column (rows of the other side, or rejected
        ticks, keep the fill value — NaN / False / -1), ``errors`` maps
        tick index to the exception that rejected it (late tick,
        unknown series, ...).  Everything else about the contract is
        :meth:`dispatch`'s, bitwise: ticks that need per-tick machinery
        — duplicate (member, series) ticks in one block (lane
        assignment and strict arrival order), spilled/tiered members,
        members of other shape buckets, or any mesh-sharded cohort —
        fall back to :meth:`dispatch` internally, in arrival order per
        member.  Single-tick members may legally reorder around each
        other (each member's own merged-stream order is the only
        contract), which is what lets a mixed block run as one push
        program plus one query program."""
        n = len(members)
        out: Dict[str, np.ndarray] = {}
        errors: Dict[int, Exception] = {}
        if n == 0:
            return out, errors
        ts = np.asarray(ts, np.int64)
        if ts.shape != (n,):
            raise ValueError(
                f"members and ts are parallel arrays: got {n} members "
                f"but ts of shape {ts.shape}")
        if isinstance(kinds, str):
            if kinds not in ("right", "left"):
                raise ValueError(f"kinds must be 'right' or 'left', "
                                 f"got {kinds!r}")
            is_left = np.full(n, kinds == "left")
        else:
            ka = np.asarray(kinds)
            is_left = (ka == "left") if ka.dtype.kind in "UO" \
                else ka.astype(bool)
            if is_left.shape != (n,):
                raise ValueError(
                    f"per-tick kinds must align with members: "
                    f"{is_left.shape} != ({n},)")
        skeys = None
        if isinstance(series_ids, (list, tuple, np.ndarray)):
            if len(series_ids) != n:
                raise ValueError(
                    f"per-tick series_ids must align with members: "
                    f"{len(series_ids)} != {n}")
            skeys = series_ids
        if seq is None:
            sq_arr = np.full(n, -np.inf)
        else:
            sq_arr = np.asarray(seq, np.float64)
            if sq_arr.shape != (n,):
                raise ValueError(
                    f"seq must align with members: {sq_arr.shape} != "
                    f"({n},)")
            sq_arr = np.where(np.isnan(sq_arr), -np.inf, sq_arr)
        colv_full = None
        if not is_left.all():
            if values is None:
                raise ValueError(
                    "block has data (right) ticks but no values")
            cols = []
            for col in self.value_cols:
                if col not in values:
                    raise ValueError(
                        f"push block is missing value column {col!r} "
                        f"(cohort columns: {self.value_cols})")
            for col in self.value_cols:
                v = np.asarray(values[col], np.float32)
                if v.shape != (n,):
                    raise ValueError(
                        f"values[{col!r}] must align with members: "
                        f"{v.shape} != ({n},)")
                cols.append(v)
            colv_full = (np.stack(cols) if cols
                         else np.zeros((0, n), np.float32))

        slow = np.zeros(n, bool)
        dead = np.zeros(n, bool)
        g0 = None
        sl = np.full(n, -1, np.int64)
        rw = np.zeros(n, np.int64)
        if self.mesh is not None or self.spill_dir is not None:
            # mesh-sharded batch builds are per-shard device-resident
            # already; tiered cohorts need fault-in/LRU bookkeeping —
            # both take the per-tick path wholesale
            slow[:] = True
            for i in range(n):
                if members[i].cohort is not self:
                    raise ValueError(
                        f"stream {members[i].name!r} belongs to a "
                        f"different cohort")
        else:
            for i in range(n):
                m = members[i]
                if m.cohort is not self:
                    raise ValueError(
                        f"stream {m.name!r} belongs to a different "
                        f"cohort")
                sk = skeys[i] if skeys is not None else series_ids
                k = m._row.get(sk)
                if k is None:
                    errors[i] = ValueError(
                        f"unknown series {sk!r} on stream {m.name!r}: "
                        f"a cohort stream's series set grows only "
                        f"through add_series")
                    dead[i] = True
                    continue
                rw[i] = k
                g = m._group
                if g is None:        # not resident (shouldn't happen
                    slow[i] = True   # without spill_dir; be safe)
                    continue
                if g0 is None:
                    g0 = g
                if g is not g0:      # other shape bucket
                    slow[i] = True
                    continue
                sl[i] = m.slot
            fastable = ~dead & ~slow & (sl >= 0)
            if fastable.any():
                # duplicate (member, series) ticks need lanes and
                # strict per-member arrival order: per-tick path
                kid = sl * np.int64(g0.bucket) + rw
                fi = np.nonzero(fastable)[0]
                _, inv, cnt = np.unique(kid[fi], return_inverse=True,
                                        return_counts=True)
                dup = cnt[inv] > 1
                if dup.any():
                    slow[fi[dup]] = True
                self._dispatch_block_fast(
                    np.nonzero(~dead & ~slow & (sl >= 0))[0], is_left,
                    members, sl, rw, ts, sq_arr, colv_full, g0, out,
                    errors, n)

        s_idx = np.nonzero(slow)[0]
        if len(s_idx):
            self._dispatch_block_slow(s_idx, is_left, members, skeys,
                                      series_ids, ts, seq, sq_arr,
                                      colv_full, out, errors, n)
        self._maybe_snapshot()
        return out, errors

    def _out_col(self, out, name, n):
        a = out.get(name)
        if a is None:
            if name == "right_row_idx":
                a = out[name] = np.full(n, -1, np.int32)
            elif name.endswith("_found"):
                a = out[name] = np.zeros(n, bool)
            else:
                a = out[name] = np.full(n, np.nan, np.float32)
        return a

    def _dispatch_block_fast(self, f_idx, is_left, members, sl, rw, ts,
                             sq_arr, colv_full, g0, out, errors, n):
        """The device block path for single-tick members of one bucket
        group: per side, ONE vectorized watermark admission (the
        singles rule) and ONE compiled scatter+step+gather program."""
        if not len(f_idx):
            return
        S, C = g0.capacity, len(self.value_cols)
        for side_i in (_SIDE_RIGHT, _SIDE_LEFT):
            left = side_i == _SIDE_LEFT
            idx = f_idx[is_left[f_idx]] if left \
                else f_idx[~is_left[f_idx]]
            if not len(idx):
                continue
            isl, irw = sl[idx], rw[idx]
            its, isq = ts[idx], sq_arr[idx]
            wts = g0.wm_ts[isl, irw]
            wsq = g0.wm_seq[isl, irw]
            wsd = g0.wm_side[isl, irw]
            late = (its < wts) | ((its == wts) & (
                (isq < wsq) | ((isq == wsq) & (side_i < wsd))))
            if late.any():
                for j in np.nonzero(late)[0]:
                    i = int(idx[j])
                    m = members[i]
                    errors[i] = LateTickError(
                        f"{m.name}/{m.series[int(irw[j])]!r}",
                        int(its[j]), float(isq[j]), side_i,
                        (int(wts[j]), float(wsq[j]), int(wsd[j])))
                keep = ~late
                idx, isl, irw = idx[keep], isl[keep], irw[keep]
                its, isq = its[keep], isq[keep]
            nk = len(idx)
            if not nk:
                continue
            Nb = stream_mod._bucket(nk)
            slp = np.full(Nb, S, np.int32)   # pad: out of range, DROPPED
            slp[:nk] = isl
            rwp = np.zeros(Nb, np.int32)
            rwp[:nk] = irw
            if side_i == _SIDE_RIGHT:
                tsp = np.full(Nb, TS_PAD, np.int64)
                tsp[:nk] = its
                colp = np.full((C, Nb), np.nan, np.float32)
                if C:
                    colp[:, :nk] = colv_full[:, idx]
                exe = g0.executable("block_push", Nb)
                new_state, gath = exe(*g0.state.values(), slp, rwp,
                                      tsp, colp)
                g0.state = dict(zip(g0.cfg.state_names(), new_state))
                for name, key, c in self._emit_fields(gath.keys()):
                    self._out_col(out, name, n)[idx] = \
                        np.asarray(gath[key])[:nk, c]
            else:
                exe = g0.executable("block_query", Nb)
                args = [g0.state[nm] for nm in sst._QUERY_STATE]
                new_nm, (v, f, ii) = exe(*args, slp, rwp)
                g0.state["n_merged"] = new_nm
                v = np.asarray(v)[:nk]
                f = np.asarray(f)[:nk]
                for c, col in enumerate(self.value_cols):
                    self._out_col(out, col, n)[idx] = v[:, c]
                    self._out_col(out, col + "_found", n)[idx] = f[:, c]
                self._out_col(out, "right_row_idx", n)[idx] = \
                    np.asarray(ii)[:nk]
            # commit-after-success: vectorized watermark advance
            g0.wm_ts[isl, irw] = its
            g0.wm_seq[isl, irw] = isq
            g0.wm_side[isl, irw] = side_i
            for i in idx:
                members[i].acked += 1
            self.acked_total += nk
            self.dispatches += 1
            self._dirty.add(g0.bucket)

    def _dispatch_block_slow(self, s_idx, is_left, members, skeys,
                             series_ids, ts, seq, sq_arr, colv_full,
                             out, errors, n):
        """Per-tick fallback for the block ticks the device path cannot
        take.  Ticks are regrouped into side-homogeneous runs with the
        executor's cross-member greedy rule (a tick lands in the
        earliest side-matching run at or after its member's last run —
        only each member's OWN order is a contract), then each run is
        one :meth:`dispatch`."""
        runs: List[list] = []            # [side_is_left, [tick idx]]
        last: Dict[int, int] = {}
        for i in s_idx:
            i = int(i)
            mid = id(members[i])
            want = bool(is_left[i])
            placed = -1
            for bi in range(last.get(mid, 0), len(runs)):
                if runs[bi][0] == want:
                    placed = bi
                    break
            if placed < 0:
                runs.append([want, [i]])
                placed = len(runs) - 1
            else:
                runs[placed][1].append(i)
            last[mid] = placed
        for want, lst in runs:
            items = []
            for i in lst:
                sk = skeys[i] if skeys is not None else series_ids
                sqi = None if seq is None else float(sq_arr[i])
                row = None
                if not want:
                    row = {col: colv_full[c, i]
                           for c, col in enumerate(self.value_cols)}
                items.append((members[i], sk, int(ts[i]), sqi, row))
            res = self.dispatch("left" if want else "right", items)
            for i, r in zip(lst, res):
                if isinstance(r, Exception):
                    errors[i] = r
                    continue
                for name, val in r.items():
                    self._out_col(out, name, n)[i] = val

    # -- tiered member-state spill -------------------------------------

    def _member_artifact(self, name: str) -> str:
        safe = "".join(c if c.isalnum() or c in "-_." else "_"
                       for c in name)[:40]
        h = hashlib.sha1(name.encode()).hexdigest()[:12]
        return os.path.join(self.spill_dir, f"member_{safe}_{h}")

    def spill(self, name: str) -> str:
        """Explicitly demote one resident member to the cold tier;
        returns the artifact path.  The LRU does this automatically
        past ``resident_budget``."""
        member = self._members[str(name)]
        if member._group is None:
            raise ValueError(f"stream {name!r} is not resident")
        return self._spill(member)

    def _spill(self, member: CohortMember) -> str:
        """Persist one member's slot rows (every state plane + its
        watermark rows) as a CRC'd ``kind="cohort_member"`` artifact
        and free the slot.  The artifact is the member's EXACT state:
        faulting it back in and ticking is bitwise the never-spilled
        run."""
        if not self.spill_dir:
            raise ValueError("StreamCohort has no spill_dir")
        g, slot = member._group, member.slot
        g._host()
        arrays = {f"s.{n}": np.ascontiguousarray(a[slot])
                  for n, a in g.state.items()}
        arrays["wm_ts"] = np.ascontiguousarray(g.wm_ts[slot])
        arrays["wm_seq"] = np.ascontiguousarray(g.wm_seq[slot])
        arrays["wm_side"] = np.ascontiguousarray(g.wm_side[slot])
        meta = {"cohort_config": self._config_meta(),
                "name": member.name,
                "series_repr": [repr(s) for s in member.series],
                "acked": int(member.acked),
                "bucket": int(g.bucket)}
        path = self._member_artifact(member.name)
        ckpt.save_state(arrays, path, meta, kind="cohort_member")
        member._spill_bucket = g.bucket
        g.release(slot)
        member._group, member.slot = None, None
        self._spilled[member.name] = path
        self._lru.pop(member.name, None)
        self._resident -= 1
        self.spills += 1
        return path

    def _fault_in(self, member: CohortMember) -> None:
        """Promote a cold member into a slot.  With an artifact, its
        rows install bit-for-bit (the artifact stays on disk for any
        snapshot that references it); a never-ticked cold member just
        allocates — a fresh slot IS its state, no artifact needed.  A
        foreign, stale, or corrupt artifact is refused by name
        (CheckpointError), the member stays cold."""
        path = self._spilled.get(member.name)
        if path is None:
            bucket = int(member._spill_bucket
                         if member._spill_bucket is not None
                         else row_bucket(len(member.series)))
            self._group(bucket).alloc(member)
            member._spill_bucket = None
            self._resident += 1
            self._lru[member.name] = None
            return
        arrays, meta = ckpt.load_state(path, kind="cohort_member")
        if (meta.get("name") != member.name
                or meta.get("series_repr") != [repr(s)
                                               for s in member.series]
                or meta.get("cohort_config") != self._config_meta()):
            raise ckpt.CheckpointError(
                f"spilled member artifact {path!r} is FOREIGN to "
                f"stream {member.name!r} of this cohort (name / series "
                f"set / cohort config mismatch): refusing to install "
                f"it; delete the artifact to re-admit the stream with "
                f"fresh state")
        if int(meta["acked"]) != int(member.acked):
            # a spilled member's state is frozen, so artifact and
            # cursor agree by construction — disagreement means this
            # cohort resumed an OLD snapshot and the member re-spilled
            # NEWER state over the artifact since: installing it would
            # double-apply the replay tail
            raise ckpt.CheckpointError(
                f"spilled member artifact {path!r} holds stream "
                f"{member.name!r} at acked={meta['acked']} but this "
                f"cohort's cursor is {member.acked}: the artifact "
                f"outlived the snapshot this cohort resumed from — "
                f"resume from a newer snapshot")
        bucket = int(meta["bucket"])
        g = self._group(bucket)
        slot = g.alloc(member)
        g._host()
        for n in g.state:
            g.state[n][slot] = arrays[f"s.{n}"]
        g.wm_ts[slot] = np.asarray(arrays["wm_ts"], np.int64)
        g.wm_seq[slot] = np.asarray(arrays["wm_seq"], np.float64)
        g.wm_side[slot] = np.asarray(arrays["wm_side"], np.int8)
        member._spill_bucket = None
        # the artifact STAYS on disk: any cohort snapshot taken while
        # the member was spilled references it by name, and the
        # member's state was frozen from spill to now — the file is
        # exact for every one of those snapshots.  A later re-spill
        # overwrites it atomically.
        del self._spilled[member.name]
        self._resident += 1
        self._lru[member.name] = None
        self.restores += 1
        self._dirty.add(bucket)

    def _enforce_budget(self, protect: set) -> None:
        """Evict coldest-first until resident count fits the budget;
        members named in ``protect`` (this dispatch) are never
        evicted, so a dispatch touching more members than the budget
        temporarily exceeds it rather than thrash."""
        while self._resident > self.resident_budget:
            victim = next((n for n in self._lru if n not in protect),
                          None)
            if victim is None:
                return
            self._spill(self._members[victim])

    def _spilled_arrays(self, member: CohortMember):
        path = self._spilled.get(member.name)
        if path is None:
            return None
        arrays, _meta = ckpt.load_state(path, kind="cohort_member")
        return arrays

    @property
    def spill_stats(self) -> dict:
        """Tier occupancy and traffic counters."""
        return {"registered": len(self._members),
                "resident": self._resident,
                "spilled_artifacts": len(self._spilled),
                "spills": self.spills, "restores": self.restores}

    # -- warmup --------------------------------------------------------

    def warmup(self, max_rows: int, max_block: int = 0) -> int:
        """Pre-build every bucket group's push/query executables for
        the padded-batch ladder up to ``max_rows`` — a fresh process
        reaches the zero-recompile steady state before traffic.  With
        ``max_block`` set, also build the :meth:`dispatch_block` device
        programs for the pow2 block-size ladder up to ``max_block``
        (meshless cohorts only — a meshed cohort block-routes to the
        per-tick path, whose shapes the first ladder covers)."""
        shapes = []
        b = stream_mod._bucket(1)
        while True:
            shapes.append(b)
            if b >= max_rows:
                break
            b *= 2
        for g in self._groups.values():
            for Lb in shapes:
                g.executable("push", Lb)
                g.executable("query", Lb)
        built = len(shapes) * len(self._groups)
        if max_block and self.mesh is None:
            blocks = []
            b = stream_mod._bucket(1)
            while True:
                blocks.append(b)
                if b >= max_block:
                    break
                b *= 2
            for g in self._groups.values():
                for Nb in blocks:
                    g.executable("block_push", Nb)
                    g.executable("block_query", Nb)
            built += len(blocks) * len(self._groups)
        return built

    # -- durability ----------------------------------------------------

    def _config_meta(self) -> dict:
        return {
            "value_cols": self.value_cols,
            "skip_nulls": self.skip_nulls,
            "max_lookback": self.max_lookback,
            "window_ns": self.window_ns,
            "rows_bound": self.rows_bound,
            "ema_alpha": self.ema_alpha,
        }

    def _snapshot_arrays(self, buckets) -> Tuple[dict, list]:
        """``(arrays, groups_meta)`` for the given bucket set: every
        state plane + the watermark planes, prefixed ``g<bucket>.``."""
        arrays = {}
        groups_meta = []
        for bucket in sorted(buckets):
            g = self._groups[bucket]
            g._host()
            for name, arr in g.state.items():
                arrays[f"g{bucket}.{name}"] = arr
            arrays[f"g{bucket}.wm_ts"] = g.wm_ts
            arrays[f"g{bucket}.wm_seq"] = g.wm_seq
            arrays[f"g{bucket}.wm_side"] = g.wm_side
            groups_meta.append({"bucket": bucket,
                                "capacity": g.capacity})
        return arrays, groups_meta

    def snapshot(self, differential: bool = False) -> str:
        """CRC'd atomic cohort artifact (kind="cohort_state"), step
        number = total events acked.

        ``differential=False`` (default): every bucket group's stacked
        state + watermark planes — the standalone artifact.

        ``differential=True``: ONLY the bucket groups dirty since the
        previous snapshot (any kind), chained to it by the
        predecessor's manifest CRC-32 recorded in this manifest — so
        fleet-scale checkpoint cost is O(changed state), and a broken
        link is detected at resume, never silently skipped.  Member
        slot assignments and acked cursors (small) ride every
        manifest, so membership is exact at each link.  Falls back to
        a full snapshot when there is no predecessor in this process.
        Retention keeps every link of the last ``keep_last`` full
        snapshots' chains."""
        if not self.checkpoint_dir:
            raise ValueError("StreamCohort has no checkpoint_dir")
        if self._last_snapshot is not None and os.path.basename(
                self._last_snapshot) == f"step_{self.acked_total:010d}":
            if not self._dirty:
                # nothing acked AND nothing structurally dirty
                # (membership/capacity changes mark their bucket):
                # the artifact on disk is already exact
                return self._last_snapshot
            # same step number but changed state: the artifact must be
            # REWRITTEN in place — as a standalone full (a diff would
            # record its predecessor's manifest CRC and then replace
            # that very predecessor, breaking its own chain link)
            differential = False
        differential = differential and self._last_snapshot is not None
        buckets = (sorted(b for b in self._dirty if b in self._groups)
                   if differential else sorted(self._groups))
        arrays, groups_meta = self._snapshot_arrays(buckets)
        members_meta = []
        for m in self._members.values():
            mm = {"name": m.name, "series": list(m.series),
                  "acked": m.acked}
            if m._group is not None:
                mm["bucket"] = m._group.bucket
                mm["slot"] = m.slot
            else:
                # cold member: no slot; its artifact (if any — a
                # never-ticked member has none) is referenced by name
                # so resume reattaches the SAME spilled state
                mm["bucket"] = m._spill_bucket
                mm["slot"] = None
                mm["spilled"] = True
                ap = self._spilled.get(m.name)
                if ap is not None:
                    mm["artifact"] = os.path.basename(ap)
            members_meta.append(mm)
        meta = {"cohort_config": self._config_meta(),
                "groups": groups_meta, "members": members_meta,
                "acked_total": self.acked_total}
        if differential:
            prev = self._last_snapshot
            meta["snapshot"] = {
                "mode": "differential",
                "prev": os.path.basename(prev),
                "prev_manifest_crc": ckpt._file_crc(
                    os.path.join(self._resolved_dir(prev),
                                 "manifest.json")),
                "base": os.path.basename(self._last_full),
            }
        else:
            meta["snapshot"] = {"mode": "full"}
        path = os.path.join(self.checkpoint_dir,
                            f"step_{self.acked_total:010d}")
        resilience.retrying(resilience.DEFAULT_IO_POLICY,
                            label="cohort-snapshot")(ckpt.save_state)(
            arrays, path, meta, kind="cohort_state")
        self._last_snapshot = path
        if differential:
            self._diffs_since_full += 1
        else:
            self._last_full = path
            self._diffs_since_full = 0
        self._dirty.clear()
        self._prune_chain()
        return path

    @staticmethod
    def _resolved_dir(path: str) -> str:
        """The directory a load would actually read: ``path``, or its
        ``.bak`` survivor after a crash mid-swap (load_state's rule)."""
        if not os.path.exists(os.path.join(path, "manifest.json")) \
                and os.path.exists(os.path.join(path + ".bak",
                                                "manifest.json")):
            return path + ".bak"
        return path

    @staticmethod
    def _snapshot_mode(path: str) -> dict:
        man = ckpt._manifest(path)
        return (man.get("meta") or {}).get("snapshot") \
            or {"mode": "full"}

    def _prune_chain(self) -> None:
        """Chain-aware retention: keep the last ``keep_last`` FULL
        snapshots and every differential link newer than the oldest
        kept full — a plain keep-last-K would sever a live chain from
        its base.  Pre-chain snapshots (no ``snapshot`` meta) count as
        full, so all-full histories degrade to exactly the old
        keep-last-K behaviour."""
        steps = ckpt.list_steps(self.checkpoint_dir)   # newest first
        fulls = 0
        cut = None
        for step, path in steps:
            try:
                mode = self._snapshot_mode(
                    self._resolved_dir(path))["mode"]
            except ckpt.CheckpointError:
                continue            # unreadable: neither full nor kept
            if mode != "differential":
                fulls += 1
                if fulls >= max(1, self.keep_last):
                    cut = step
                    break
        if cut is None:
            return
        for step, path in steps:
            if step < cut:
                logger.info("pruning old cohort snapshot %s "
                            "(keep_last=%d fulls)", path, self.keep_last)
                shutil.rmtree(path, ignore_errors=True)
                shutil.rmtree(path + ".bak", ignore_errors=True)

    def _maybe_snapshot(self) -> None:
        if self._next_ckpt is not None and self.checkpoint_dir \
                and self.acked_total >= self._next_ckpt:
            diff = (self.diff_snapshots
                    and self._last_snapshot is not None
                    and self._diffs_since_full < self.full_every - 1)
            self.snapshot(differential=diff)
            self._next_ckpt = self.acked_total + self.ckpt_every

    # -- failover ------------------------------------------------------

    @classmethod
    def _resolve_chain(cls, checkpoint_dir: str, verify: bool = True):
        """Newest intact snapshot chain under ``checkpoint_dir``, as
        ``[(arrays, meta), ...]`` base-full first.  A differential head
        is walked back link by link — each link's recorded predecessor
        manifest CRC must match the predecessor on disk — down to its
        full base; ANY broken/corrupt/missing link disqualifies the
        whole head and the next-older candidate is tried (the
        fall-back-to-older discipline of ``checkpoint.latest``)."""
        candidates = ckpt.list_steps(checkpoint_dir)
        last_err: Optional[str] = None
        for _, head in candidates:
            entries = []
            path = head
            try:
                while True:
                    resolved = cls._resolved_dir(path)
                    ckpt.verify_checkpoint(resolved,
                                           verify_arrays=verify)
                    arrays, meta = ckpt.load_state(
                        resolved, verify=verify, kind="cohort_state")
                    snap = meta.get("snapshot") or {"mode": "full"}
                    entries.append((arrays, meta))
                    if snap["mode"] != "differential":
                        return list(reversed(entries))
                    prev = os.path.join(checkpoint_dir, snap["prev"])
                    prev_resolved = cls._resolved_dir(prev)
                    got = ckpt._file_crc(
                        os.path.join(prev_resolved, "manifest.json"))
                    if got != int(snap["prev_manifest_crc"]):
                        raise ckpt.CheckpointError(
                            f"differential chain broken at "
                            f"{path!r}: predecessor {snap['prev']!r} "
                            f"manifest crc32 {got} != recorded "
                            f"{snap['prev_manifest_crc']}")
                    path = prev
            except (ckpt.CheckpointError, OSError) as e:
                last_err = f"{head}: {e}"
                logger.warning(
                    "cohort snapshot chain headed at %s unusable (%s); "
                    "trying an older head", head, e)
        raise ckpt.CheckpointError(
            f"no intact cohort snapshot chain under "
            f"{checkpoint_dir!r}"
            + (f" (last failure: {last_err})" if last_err else ""))

    def _install_link(self, arrays: dict, meta: dict, mesh,
                      stream_axis: str) -> None:
        """Apply one chain link: replace/create every bucket group it
        carries (full arrays per carried bucket), then rebuild the
        whole membership from its manifest (membership is exact at
        every link)."""
        for gm in meta["groups"]:
            bucket, cap = int(gm["bucket"]), int(gm["capacity"])
            if mesh is not None:
                n_axis = int(mesh.shape[stream_axis])
                if cap % n_axis:
                    raise ckpt.CheckpointError(
                        f"cohort snapshot group bucket={bucket} has "
                        f"capacity {cap}, not divisible by the mesh's "
                        f"{stream_axis!r} axis ({n_axis}): resume onto "
                        f"a mesh whose stream axis divides it")
            g = _Group(self, bucket, cap)
            for name in g.state:
                g.state[name] = np.ascontiguousarray(
                    arrays[f"g{bucket}.{name}"])
            g.wm_ts = np.asarray(arrays[f"g{bucket}.wm_ts"], np.int64)
            g.wm_seq = np.asarray(arrays[f"g{bucket}.wm_seq"],
                                  np.float64)
            g.wm_side = np.asarray(arrays[f"g{bucket}.wm_side"], np.int8)
            self._groups[bucket] = g
        self._members.clear()
        self._spilled.clear()
        for g in self._groups.values():
            g.members = [None] * g.capacity
        for mm in meta["members"]:
            member = CohortMember(self, mm["name"], mm["series"])
            member.acked = int(mm["acked"])
            self._members[member.name] = member
            if mm.get("spilled"):
                member._spill_bucket = (None if mm["bucket"] is None
                                        else int(mm["bucket"]))
                art = mm.get("artifact")
                if art is not None:
                    if not self.spill_dir:
                        raise ckpt.CheckpointError(
                            f"cohort snapshot records stream "
                            f"{member.name!r} spilled to artifact "
                            f"{art!r} but this cohort has no "
                            f"spill_dir: resume with the original "
                            f"spill_dir, or that member's state is "
                            f"unreachable")
                    self._spilled[member.name] = os.path.join(
                        self.spill_dir, art)
                continue
            g = self._groups[int(mm["bucket"])]
            slot = int(mm["slot"])
            g.members[slot] = member
            member._group, member.slot = g, slot
        for g in self._groups.values():
            g._free = [i for i in range(g.capacity - 1, -1, -1)
                       if g.members[i] is None]
        self._resident = sum(1 for m in self._members.values()
                             if m._group is not None)
        self._lru = {m.name: None for m in self._members.values()
                     if m._group is not None}
        self.acked_total = int(meta["acked_total"])

    @classmethod
    def resume(cls, checkpoint_dir: str, verify: bool = True,
               mesh=None, stream_axis: str = "streams",
               **overrides) -> "StreamCohort":
        """Restore the newest intact cohort snapshot — a standalone
        full artifact, or a differential chain replayed base-first
        (each link CRC-verified against its predecessor).  The
        returned cohort's per-stream ``acked`` dict tells the caller
        where each stream's event source restarts — replay every
        stream's tail after its own cursor and the output is
        byte-identical to a run that never died."""
        chain = cls._resolve_chain(checkpoint_dir, verify=verify)
        scfg = chain[-1][1]["cohort_config"]
        cohort = cls(
            scfg["value_cols"], skip_nulls=scfg["skip_nulls"],
            max_lookback=scfg["max_lookback"], window_secs=None,
            window_rows_bound=scfg["rows_bound"],
            ema_alpha=scfg["ema_alpha"], mesh=mesh,
            stream_axis=stream_axis,
            checkpoint_dir=overrides.pop("checkpoint_dir",
                                         checkpoint_dir),
            **overrides)
        # reconstruct the exact folded integer width (window_secs
        # would re-floor; the snapshot already holds the int)
        cohort.window_ns = scfg["window_ns"]
        for arrays, meta in chain:
            cohort._install_link(arrays, meta, mesh, stream_axis)
        # the resumed process continues the SAME chain: its first
        # differential snapshot links to the restored head
        head = os.path.join(checkpoint_dir,
                            f"step_{cohort.acked_total:010d}")
        base_meta = chain[0][1]
        cohort._last_snapshot = head
        cohort._last_full = os.path.join(
            checkpoint_dir, f"step_{int(base_meta['acked_total']):010d}")
        cohort._diffs_since_full = len(chain) - 1
        cohort._dirty.clear()
        if cohort.ckpt_every:
            cohort._next_ckpt = cohort.acked_total + cohort.ckpt_every
        return cohort
