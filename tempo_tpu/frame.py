"""TSDF: the core time-series frame of tempo-tpu.

Capability parity with the reference TSDF (/root/reference/python/tempo/
tsdf.py:22-64 ctor & validation; scala/.../TSDF.scala:168-518 BaseTSDF),
re-designed for TPU execution:

* the reference wraps a *lazy Spark DataFrame* and builds Window
  expressions; tempo-tpu wraps *host columnar data* (pandas/numpy) plus a
  cached packed device representation ([num_series, padded_len] jax
  arrays, see ``tempo_tpu.packing``) that all ops consume.
* ops are eager jitted kernels instead of lazy logical plans; chaining is
  cheap because the packed cache is reused and results stay on device
  until materialised.

Column nullability follows Spark semantics via explicit validity masks
(float NaN is also treated as null at ingest, matching Spark's
FloatType/DoubleType null handling in the reference's tests).
"""

from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np
import pandas as pd

from tempo_tpu import packing
from tempo_tpu.packing import FlatLayout

logger = logging.getLogger(__name__)

# Numeric dtypes the reference summarizes ('int','bigint','float','double'
# per tsdf.py:697); here: any numpy integer or float column.
_SUMMARIZABLE_KINDS = ("i", "u", "f")

DEFAULT_SEQ_COLNAME = "sequence_num"  # parity: scala TSDF.scala:529


def _strict_sql(strict: Optional[bool]) -> bool:
    """Resolve the strict-SQL escape hatch: an explicit argument wins,
    else ``TEMPO_TPU_SQL_STRICT`` (the compiled-surface knob), else the
    legacy ``TEMPO_TPU_STRICT_SQL`` alias (both default off)."""
    if strict is not None:
        return bool(strict)
    from tempo_tpu import config

    return (config.get_bool("TEMPO_TPU_SQL_STRICT")
            or config.get_bool("TEMPO_TPU_STRICT_SQL"))


def _split_alias(raw: str):
    """Split ``expr as alias`` at the LAST top-level ``as``/``AS``
    (outside single/double quotes and backticks) for the selectExpr
    fallback path; the naive first-occurrence split mis-parsed string
    literals and identifiers containing " as " (VERDICT r2 weak #5).
    Returns (expr, alias) or None when no plausible alias exists."""
    import re

    low = raw.lower()
    in_q = None
    last = -1
    i = 0
    while i < len(raw):
        ch = raw[i]
        if in_q:
            if ch == in_q:
                in_q = None
        elif ch in ("'", '"', "`"):
            in_q = ch
        elif low.startswith(" as ", i):
            last = i
        i += 1
    if last < 0:
        return None
    expr, alias = raw[:last].strip(), raw[last + 4:].strip()
    if re.fullmatch(r"`[^`]+`", alias):
        return expr, alias[1:-1]
    if re.fullmatch(r"[A-Za-z_][A-Za-z0-9_]*", alias):
        return expr, alias
    return None


def _is_numeric(dtype) -> bool:
    return (
        pd.api.types.is_numeric_dtype(dtype)
        and not pd.api.types.is_bool_dtype(dtype)
        and not pd.api.types.is_complex_dtype(dtype)
    )


class TSDF:
    """A time-series frame: (data, ts_col, partition_cols, sequence_col).

    ``df`` may be a pandas DataFrame or another TSDF's data dict.  The
    constructor validates columns exactly like the reference
    (tsdf.py:45-64): case-insensitive presence check, typed errors.
    """

    def __init__(
        self,
        df: pd.DataFrame,
        ts_col: str = "event_ts",
        partition_cols: Optional[Union[str, List[str]]] = None,
        sequence_col: Optional[str] = None,
    ):
        if not isinstance(df, pd.DataFrame):
            raise TypeError(
                f"TSDF expects a pandas DataFrame; got {type(df)} instead!"
            )
        self.ts_col = self.__validated_column(df, ts_col)
        self.partitionCols = (
            [] if partition_cols is None else self.__validated_columns(df, partition_cols)
        )
        self.sequence_col = "" if sequence_col is None else sequence_col
        if self.sequence_col:
            self.__validated_column(df, self.sequence_col)
        self.df = df.reset_index(drop=True)
        self._layout: Optional[FlatLayout] = None
        self._packed: Dict[str, object] = {}

    # ------------------------------------------------------------------
    # Validation helpers (parity: tsdf.py:45-75)
    # ------------------------------------------------------------------

    def __validated_column(self, df: pd.DataFrame, colname: str) -> str:
        if not isinstance(colname, str):
            raise TypeError(
                f"Column names must be of type str; found {type(colname)} instead!"
            )
        lowered = [c.lower() for c in df.columns]
        if colname.lower() not in lowered:
            raise ValueError(f"Column {colname} not found in Dataframe")
        return colname

    def __validated_columns(self, df, colnames) -> List[str]:
        if isinstance(colnames, str):
            colnames = [colnames]
        if colnames is None:
            colnames = []
        elif not isinstance(colnames, list):
            raise TypeError(
                f"Columns must be of type list, str, or None; found {type(colnames)} instead!"
            )
        for col in colnames:
            self.__validated_column(df, col)
        return colnames

    # ------------------------------------------------------------------
    # Lazy query planning (tempo_tpu/plan/; TEMPO_TPU_PLAN=1)
    # ------------------------------------------------------------------

    def _plan_record(self, op: str, others=(), params=None, objs=None):
        """Record a deferred plan node over this frame instead of
        executing (planning on, ``TEMPO_TPU_PLAN=1``).  Returns the
        lazy wrapper the planned chain continues on; ``collect``/
        ``.df``-style terminals optimize + execute it through the
        executable cache."""
        from tempo_tpu.plan import lazy as plan_lazy

        return plan_lazy.record(self, op, others, params, objs)

    def explain(self, cost: bool = False) -> str:
        """Render this frame's query plan.  On an eager frame there is
        nothing deferred — the plan is a bare source; under
        ``TEMPO_TPU_PLAN=1`` the lazy wrappers' ``explain`` shows the
        recorded logical plan, the optimizer's rewrites, per-node
        engine choices and barriers (the analog of the reference's
        ``explain cost``, tsdf.py display path)."""
        from tempo_tpu.plan import ir, render

        text = render.explain_text(ir.Node("source", payload=self),
                                   cost=cost)
        print(text)
        return text

    def _check_partition_cols_match(self, other: "TSDF") -> None:
        for lc, rc in zip(self.partitionCols, other.partitionCols):
            if lc != rc:
                raise ValueError(
                    "left and right dataframe partition columns should have same name in same order"
                )

    def _validate_ts_col_match(self, other: "TSDF") -> None:
        lk = self.df[self.ts_col].dtype.kind
        rk = other.df[other.ts_col].dtype.kind
        if lk != rk:
            raise ValueError(
                "left and right dataframe timestamp index columns should have same type"
            )

    # ------------------------------------------------------------------
    # Schema-derived column classes (parity: scala TSDF.scala:193-205)
    # ------------------------------------------------------------------

    @property
    def columns(self) -> List[str]:
        return list(self.df.columns)

    @property
    def structuralColumns(self) -> List[str]:
        """ts col + partition cols (scala TSDF.scala:193)."""
        cols = [self.ts_col] + self.partitionCols
        if self.sequence_col:
            cols.append(self.sequence_col)
        return cols

    @property
    def observationColumns(self) -> List[str]:
        """All non-structural columns (scala TSDF.scala:198-199)."""
        structural = set(self.structuralColumns)
        return [c for c in self.df.columns if c not in structural]

    @property
    def measureColumns(self) -> List[str]:
        """Numeric observation columns (scala TSDF.scala:204-205)."""
        return [c for c in self.observationColumns if _is_numeric(self.df[c].dtype)]

    def summarizable_columns(self) -> List[str]:
        """Numeric cols excluding ts + partition cols (tsdf.py:691-701)."""
        prohibited = {self.ts_col.lower()}
        prohibited.update(pc.lower() for pc in self.partitionCols)
        return [
            c
            for c in self.df.columns
            if _is_numeric(self.df[c].dtype) and c.lower() not in prohibited
        ]

    # ------------------------------------------------------------------
    # Packed layout accessors (the device-side representation)
    # ------------------------------------------------------------------

    @property
    def layout(self) -> FlatLayout:
        if self._layout is None:
            self._layout = packing.build_flat_layout(
                self.df, self.ts_col, self.partitionCols, self.sequence_col or None
            )
        return self._layout

    def sorted_flat(self, col: str) -> np.ndarray:
        """Column values in the sorted flat layout (host)."""
        return self.df[col].to_numpy()[self.layout.order]

    def numeric_flat(self, col: str):
        """(values float64, valid bool) in sorted flat layout."""
        series = self.df[col]
        vals = pd.to_numeric(series, errors="coerce").to_numpy(dtype=np.float64)
        valid = ~pd.isna(series).to_numpy()
        valid &= ~np.isnan(vals)
        return vals[self.layout.order], valid[self.layout.order]

    def packed_len(self) -> int:
        return packing.pad_length(int(self.layout.lengths.max(initial=0)))

    def packed_ts(self) -> np.ndarray:
        """[K, L] int64 ns timestamps, padded with TS_PAD."""
        key = "__ts__"
        if key not in self._packed:
            self._packed[key] = packing.pack_column(
                self.layout.ts_ns, self.layout, self.packed_len(), fill=packing.TS_PAD
            )
        return self._packed[key]

    def packed_numeric(self, col: str):
        """([K, L] float values with NaN padding, [K, L] valid bool).

        Values are in ``packing.compute_dtype()`` — float32 on TPU
        (float64 is emulated there), float64 on CPU."""
        dt = packing.compute_dtype()
        key = f"num:{col}:{dt}"
        if key not in self._packed:
            vals, valid = self.numeric_flat(col)
            L = self.packed_len()
            pv = packing.pack_column(vals.astype(dt), self.layout, L, fill=np.nan)
            pm = packing.pack_column(valid, self.layout, L, fill=False)
            self._packed[key] = (pv, pm)
        return self._packed[key]

    def packed_seq(self) -> Optional[np.ndarray]:
        if not self.sequence_col:
            return None
        key = "__seq__"
        if key not in self._packed:
            seq = pd.to_numeric(self.df[self.sequence_col]).to_numpy(dtype=np.float64)
            self._packed[key] = packing.pack_column(
                seq[self.layout.order], self.layout, self.packed_len(), fill=np.inf
            )
        return self._packed[key]

    def packed_mask(self) -> np.ndarray:
        key = "__mask__"
        if key not in self._packed:
            self._packed[key] = packing.row_mask(self.layout, self.packed_len())
        return self._packed[key]

    def ts_dtype(self):
        return self.df[self.ts_col].dtype

    # ------------------------------------------------------------------
    # DataFrame-mirror operations (parity: scala TSDF.scala:218-293)
    # ------------------------------------------------------------------

    def _with_df(self, df: pd.DataFrame) -> "TSDF":
        return TSDF(df, self.ts_col, self.partitionCols, self.sequence_col or None)

    def select(self, *cols) -> "TSDF":
        """Parity: tsdf.py:319-343 - structural columns must be retained."""
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("select", params=dict(cols=tuple(cols)))
        if "*" in cols:
            cols = tuple(self.df.columns)
        seq_stub = [self.sequence_col] if self.sequence_col else []
        mandatory = [self.ts_col] + self.partitionCols + seq_stub
        if set(mandatory).issubset(set(cols)):
            return self._with_df(self.df[list(cols)])
        raise Exception(
            "In TSDF's select statement original ts_col, partitionCols and "
            "seq_col_stub(optional) must be present"
        )

    def selectExpr(self, *exprs, strict: Optional[bool] = None) -> "TSDF":
        """Spark-style SQL projections (parity: TSDF.scala:226-229) via
        the vectorized expression engine (``tempo_tpu.sql``): arithmetic,
        CASE WHEN, CAST, IN/BETWEEN/LIKE, and the common function
        library, with ``expr AS alias`` naming.  Under plan recording
        the parsed expressions lower into a ``sql_project`` IR node
        (plan/sql_compile.py), so text projections flow through the
        optimizer and the executable cache like method chains do.
        Expressions the SQL grammar rejects fall back to pandas ``eval``
        syntax (backward compat with the pre-SQL implementation, e.g.
        ``price ** 2``) — the switch is LOGGED (the two engines differ
        on NULL semantics and function surface), and ``strict=True``
        (or ``TEMPO_TPU_SQL_STRICT=1`` / the legacy
        ``TEMPO_TPU_STRICT_SQL=1``) raises ``StrictSqlFallback``
        instead of silently changing evaluation semantics."""
        from tempo_tpu import plan, sql

        strict = _strict_sql(strict)
        if plan.recording():
            from tempo_tpu.plan import sql_compile

            try:
                lowered, objs = sql_compile.lower_select_exprs(
                    exprs, columns=list(self.df.columns))
            except sql.SqlError as e:
                if strict:
                    raise sql.StrictSqlFallback(
                        f"selectExpr{tuple(exprs)!r} left the compiled "
                        f"SQL surface ({e}); strict mode forbids the "
                        f"host-pandas fallback") from e
                logger.debug("selectExpr%r: outside the SQL grammar "
                             "(%s); evaluating eagerly", tuple(exprs), e)
            else:
                return self._plan_record("sql_project", params=dict(
                    exprs=lowered["exprs"], aliases=lowered["aliases"],
                    asts=lowered["asts"], cols=lowered["cols"],
                    strict=strict), objs=objs)
        out = {}
        for raw in exprs:
            try:
                out.update(sql.select_exprs(self.df, [raw]))
                logger.debug("selectExpr(%r): evaluated by the SQL "
                             "engine", raw)
            except sql.SqlError as e:
                if strict:
                    raise sql.StrictSqlFallback(
                        f"selectExpr({raw!r}) left the compiled SQL "
                        f"surface ({e}); strict mode forbids the "
                        f"pandas-eval fallback") from e
                logger.warning(
                    "selectExpr(%r): SQL engine rejected the expression "
                    "(%s); falling back to pandas eval semantics — pass "
                    "strict=True (or set TEMPO_TPU_SQL_STRICT=1) to "
                    "re-raise instead", raw, e)
                split = _split_alias(raw)
                if split is not None:
                    src, alias = split
                    out[alias] = (self.df[src] if src in self.df.columns
                                  else self.df.eval(src))
                else:
                    out[raw.strip()] = self.df[raw.strip()]
        return self._with_df(pd.DataFrame(out))

    def filter(self, condition, strict: Optional[bool] = None) -> "TSDF":
        """Row filter (parity: TSDF.scala:232-238).  String predicates
        parse as SQL (three-valued logic: NULL rows drop, like Spark);
        under plan recording they lower into a ``sql_filter`` IR node
        (plan/sql_compile.py) that executes on the jitted plane backend
        when the predicate's schema allows.  Non-SQL strings fall back
        to pandas ``query`` syntax for backward compat — logged, because
        the engines disagree on NULL handling, and turned into a
        ``StrictSqlFallback`` error by ``strict=True`` /
        ``TEMPO_TPU_SQL_STRICT=1`` (legacy ``TEMPO_TPU_STRICT_SQL``)."""
        from tempo_tpu import plan

        if plan.recording() and isinstance(condition, str):
            from tempo_tpu import sql
            from tempo_tpu.plan import sql_compile

            try:
                lowered, objs = sql_compile.lower_filter(
                    condition, columns=list(self.df.columns))
            except sql.SqlError as e:
                if _strict_sql(strict):
                    raise sql.StrictSqlFallback(
                        f"filter({condition!r}) left the compiled SQL "
                        f"surface ({e}); strict mode forbids the "
                        f"host-pandas fallback") from e
                logger.debug("filter(%r): outside the SQL grammar (%s); "
                             "evaluating eagerly", condition, e)
            else:
                return self._plan_record("sql_filter", params=dict(
                    condition=condition, ast=lowered["ast"],
                    cols=lowered["cols"],
                    strict=_strict_sql(strict)), objs=objs)
        if callable(condition):
            mask = condition(self.df)
        elif isinstance(condition, str):
            from tempo_tpu import sql

            try:
                mask = sql.filter_mask(self.df, condition)
                logger.debug("filter(%r): evaluated by the SQL engine",
                             condition)
            except sql.SqlError as e:
                if _strict_sql(strict):
                    raise sql.StrictSqlFallback(
                        f"filter({condition!r}) left the compiled SQL "
                        f"surface ({e}); strict mode forbids the "
                        f"pandas-query fallback") from e
                logger.warning(
                    "filter(%r): SQL engine rejected the predicate "
                    "(%s); falling back to pandas query semantics — "
                    "pass strict=True (or set TEMPO_TPU_SQL_STRICT=1) "
                    "to re-raise instead", condition, e)
                return self._with_df(self.df.query(condition))
        else:
            mask = condition
        return self._with_df(self.df[mask])

    where = filter

    def limit(self, n: int) -> "TSDF":  # plan-ok: eager-only
        return self._with_df(self.df.head(n))

    def union(self, other: "TSDF") -> "TSDF":  # plan-ok: eager-only
        return self._with_df(
            pd.concat([self.df, other.df[self.df.columns]], ignore_index=True)
        )

    unionAll = union

    def withColumn(self, colName: str, values) -> "TSDF":
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record(
                "with_column", params=dict(colName=colName, values=values),
                objs=dict(values=values))
        df = self.df.copy()
        df[colName] = values(df) if callable(values) else values
        return self._with_df(df)

    def withColumnRenamed(self, existing: str, new: str) -> "TSDF":  # plan-ok: eager-only
        df = self.df.rename(columns={existing: new})
        ts_col = new if existing == self.ts_col else self.ts_col
        pcols = [new if c == existing else c for c in self.partitionCols]
        seq = new if existing == self.sequence_col else (self.sequence_col or None)
        return TSDF(df, ts_col, pcols, seq)

    def drop(self, *cols) -> "TSDF":  # plan-ok: eager-only
        return self._with_df(self.df.drop(columns=list(cols)))

    def withPartitionCols(self, partitionCols) -> "TSDF":  # plan-ok: eager-only
        """Parity: tsdf.py:583-590 (note: drops sequence_col, as reference does)."""
        return TSDF(self.df, self.ts_col, partitionCols)

    # Scala front-end spellings (TSDF.scala:89 partitionedBy, :72 rangeStats)
    partitionedBy = withPartitionCols

    def rangeStats(self, colsToSummarise=None, rangeBackWindowSecs: int = 1000):
        return self.withRangeStats(
            colsToSummarize=colsToSummarise,
            rangeBackWindowSecs=rangeBackWindowSecs,
        )

    def show(self, n: int = 20, truncate: bool = True, vertical: bool = False):
        """Parity: tsdf.py:345-382 - renders via pandas instead of Spark."""
        view = self.df.head(n)
        if vertical:
            for i, row in view.iterrows():
                print(f"-RECORD {i}-")
                for c in view.columns:
                    print(f" {c}: {row[c]}")
        else:
            with pd.option_context(
                "display.max_colwidth", 20 if truncate else None
            ):
                print(view.to_string(index=False))

    def count(self) -> int:
        return len(self.df)

    def to_pandas(self) -> pd.DataFrame:
        return self.df

    def to_arrow(self):
        """The frame as a pyarrow Table (zero-copy where pandas allows)."""
        import pyarrow as pa

        return pa.Table.from_pandas(self.df, preserve_index=False)

    @classmethod
    def from_arrow(
        cls,
        table,
        ts_col: str = "event_ts",
        partition_cols: Optional[Union[str, List[str]]] = None,
        sequence_col: Optional[str] = None,
    ) -> "TSDF":
        """Build a TSDF from a pyarrow Table (e.g. a Parquet/Flight read)."""
        return cls(table.to_pandas(), ts_col, partition_cols, sequence_col)

    @classmethod
    def from_spark(
        cls,
        spark_df,
        ts_col: str = "event_ts",
        partition_cols: Optional[Union[str, List[str]]] = None,
        sequence_col: Optional[str] = None,
    ) -> "TSDF":
        """Build a TSDF from a Spark DataFrame — the hand-off point when
        migrating from the reference (its TSDF wraps exactly this,
        tsdf.py:22-36).  Collects through Arrow when the session allows.
        """
        return cls(spark_df.toPandas(), ts_col, partition_cols, sequence_col)

    def to_spark(self, spark=None):
        """The frame as a Spark DataFrame (via Arrow) — the return leg
        of the migration hand-off, so pipelines can move data *back* to
        the reference's world (two-way interop; the reference's writer
        feeds Spark-queryable tables, io.py:10-43).  For Spark-readable
        *files* without a live session, use
        ``write(..., format="delta")``."""
        try:
            from pyspark.sql import SparkSession
        except ImportError as e:  # pragma: no cover - pyspark optional
            raise RuntimeError(
                "to_spark() needs pyspark installed; alternatively "
                "export files with write(..., format='delta') or "
                "to_arrow()"
            ) from e
        spark = spark or SparkSession.builder.getOrCreate()
        spark.conf.set("spark.sql.execution.arrow.pyspark.enabled", "true")
        return spark.createDataFrame(self.df)

    def on_mesh(self, mesh=None, time_axis=None, series_axis: str = "series",
                halo_fraction: float = 0.5):
        """Distribute this frame over a device mesh: packs the columns
        once into sharded ``jax.Array``s and returns a
        :class:`~tempo_tpu.dist.DistributedTSDF` whose ops (asofJoin,
        withRangeStats, EMA, resample) execute distributed and chain
        device-resident.  With no arguments, a 1-D ``('series',)`` mesh
        over all local devices (the reference's entire distribution
        model, SURVEY.md §2.3); pass a 2-D mesh + ``time_axis`` for
        sequence parallelism with halo exchange.  On a single device
        this is the device-residency fast path for chained pipelines."""
        from tempo_tpu import plan

        if plan.recording():
            from tempo_tpu.plan import ir as plan_ir

            return self._plan_record("on_mesh", params=dict(
                time_axis=time_axis, series_axis=series_axis,
                halo_fraction=halo_fraction,
                mesh=plan_ir._mesh_state(mesh)), objs=dict(mesh=mesh))
        from tempo_tpu.dist import DistributedTSDF

        return DistributedTSDF.from_tsdf(
            self, mesh, series_axis=series_axis, time_axis=time_axis,
            halo_fraction=halo_fraction,
        )

    # ------------------------------------------------------------------
    # Time-series operations (implementations live in sibling modules)
    # ------------------------------------------------------------------

    def asofJoin(
        self,
        right_tsdf: "TSDF",
        left_prefix: Optional[str] = None,
        right_prefix: str = "right",
        tsPartitionVal=None,
        fraction: float = 0.5,
        skipNulls: bool = True,
        sql_join_opt: bool = False,
        suppress_null_warning: bool = False,
        maxLookback: int = 0,
    ) -> "TSDF":
        """AS-OF join (parity: tsdf.py:463-560; maxLookback from scala
        asofJoin.scala:64-88)."""
        from tempo_tpu import join, plan

        if plan.recording():
            return self._plan_record("asof_join", (right_tsdf,), dict(
                left_prefix=left_prefix, right_prefix=right_prefix,
                tsPartitionVal=tsPartitionVal, fraction=fraction,
                skipNulls=skipNulls, sql_join_opt=sql_join_opt,
                suppress_null_warning=suppress_null_warning,
                maxLookback=maxLookback))
        return join.asof_join(
            self,
            right_tsdf,
            left_prefix=left_prefix,
            right_prefix=right_prefix,
            tsPartitionVal=tsPartitionVal,
            fraction=fraction,
            skipNulls=skipNulls,
            sql_join_opt=sql_join_opt,
            suppress_null_warning=suppress_null_warning,
            maxLookback=maxLookback,
        )

    def fourier_transform(self, timestep: float, valueCol: str) -> "TSDF":  # plan-ok: eager-only
        """Frequency-domain representation per series (parity:
        tsdf.py:828-902, scipy-via-applyInPandas replaced by batched
        on-device FFT)."""
        from tempo_tpu import spectral

        return spectral.fourier_transform(self, timestep, valueCol)

    def autocorr(self, col: str, lag: int = 1) -> pd.DataFrame:
        """Autocorrelation at a given lag per series (parity:
        tsdf.py:192-316; returns a bare DataFrame like the reference)."""
        from tempo_tpu import spectral

        return spectral.autocorr(self, col, lag)

    def describe(self) -> pd.DataFrame:
        """Global + per-column summary table (parity: tsdf.py:384-431)."""
        from tempo_tpu import describe as describe_mod

        return describe_mod.describe(self)

    def write(self, tabName=None, optimizationCols=None, spark=None,
              base_dir=None, format: str = "parquet") -> str:
        """Optimized columnar persistence (parity: tsdf.py:761-762 /
        io.py:10-43).  Accepts the reference's ``write(spark, tabName,
        optimizationCols)`` calling convention as well.
        ``format="delta"`` additionally writes a Delta transaction log
        so Spark/delta-rs readers accept the table directly."""
        from tempo_tpu.io import writer

        if not isinstance(tabName, str) and isinstance(optimizationCols, str):
            # reference-style write(spark, tabName, ...) positional call
            tabName, optimizationCols = optimizationCols, spark if isinstance(spark, list) else None
        if not isinstance(tabName, str):
            raise TypeError("write() requires a table name")
        return writer.write(self, tabName, optimizationCols, base_dir,
                            format=format)

    def resample(
        self, freq: str, func=None, metricCols=None, prefix=None, fill=None
    ):
        """Downsample by a coarser frequency (parity: tsdf.py:764-776).
        Returns a ``_ResampledTSDF`` supporting chained ``.interpolate``."""
        from tempo_tpu import plan
        from tempo_tpu import resample as rs

        if plan.recording():
            return self._plan_record("resample", params=dict(
                freq=freq, func=func,
                metricCols=tuple(metricCols) if metricCols else None,
                prefix=prefix, fill=fill))
        return rs.resample(self, freq, func, metricCols, prefix, fill)

    def calc_bars(self, freq: str, func=None, metricCols=None, fill=None) -> "TSDF":  # plan-ok: eager-only
        """OHLC bars (parity: tsdf.py:813-826)."""
        from tempo_tpu import resample as rs

        return rs.calc_bars(self, freq, func, metricCols, fill)

    def resampleEMA(self, freq: str, colName: str,
                    exp_factor: float = 0.2) -> "TSDF":
        """Fused floor-resample + exact EMA in one device pass — the
        single-read form of ``resample(freq, 'floor')`` followed by
        ``EMA(..., exact=True)`` (tempo_tpu/resample.py:resample_ema)."""
        from tempo_tpu import plan
        from tempo_tpu import resample as rs

        if plan.recording():
            return self._plan_record("resample_ema", params=dict(
                freq=freq, colName=colName, exp_factor=exp_factor))
        return rs.resample_ema(self, freq, colName, exp_factor)

    def interpolate(
        self,
        freq: str = None,
        func: str = None,
        method: str = None,
        target_cols=None,
        ts_col: str = None,
        partition_cols=None,
        show_interpolated: bool = False,
    ) -> "TSDF":
        """Resample + fill missing values (parity: tsdf.py:778-811)."""
        from tempo_tpu import interpol, plan

        if plan.recording():
            return self._plan_record("interpolate", params=dict(
                freq=freq, func=func, method=method,
                target_cols=tuple(target_cols) if target_cols else None,
                ts_col=ts_col,
                partition_cols=tuple(partition_cols) if partition_cols
                else None,
                show_interpolated=show_interpolated))
        return interpol.interpolate_frame(
            self, freq, func, method, target_cols, ts_col, partition_cols,
            show_interpolated,
        )

    def withRangeStats(
        self, type: str = "range", colsToSummarize=None, rangeBackWindowSecs: int = 1000
    ) -> "TSDF":
        """Rolling range statistics (parity: tsdf.py:673-721)."""
        from tempo_tpu import plan, rolling

        if plan.recording():
            return self._plan_record("range_stats", params=dict(
                type=type,
                colsToSummarize=tuple(colsToSummarize)
                if colsToSummarize else None,
                rangeBackWindowSecs=rangeBackWindowSecs))
        return rolling.with_range_stats(self, type, colsToSummarize, rangeBackWindowSecs)

    def withGroupedStats(self, metricCols=None, freq=None) -> "TSDF":  # plan-ok: eager-only
        """Tumbling-window grouped statistics (parity: tsdf.py:723-759)."""
        from tempo_tpu import rolling

        return rolling.with_grouped_stats(self, metricCols, freq)

    def EMA(
        self, colName: str, window: int = 30, exp_factor: float = 0.2,
        exact: bool = False, inclusive_window: bool = False,
    ) -> "TSDF":
        """Exponential moving average (parity: tsdf.py:615-635; ``exact=True``
        computes the untruncated recursive EMA via an associative scan;
        ``inclusive_window=True`` matches the Scala 0..window lag range,
        EMA.scala:31)."""
        from tempo_tpu import plan, rolling

        if plan.recording():
            return self._plan_record("ema", params=dict(
                colName=colName, window=window, exp_factor=exp_factor,
                exact=exact, inclusive_window=inclusive_window))
        return rolling.ema(self, colName, window, exp_factor, exact,
                           inclusive_window)

    def vwap(  # plan-ok: eager-only
        self, frequency: str = "m", volume_col: str = "volume", price_col: str = "price"
    ) -> "TSDF":
        """Volume-weighted average price (spec: scala TSDF.scala:378-401)."""
        from tempo_tpu import rolling

        return rolling.vwap(self, frequency, volume_col, price_col)

    def withLookbackFeatures(
        self,
        featureCols,
        lookbackWindowSize: int,
        exactSize: bool = True,
        featureColName: str = "features",
    ):
        """Trailing lookback feature tensor (parity: tsdf.py:637-671)."""
        from tempo_tpu import rolling

        return rolling.with_lookback_features(
            self, featureCols, lookbackWindowSize, exactSize, featureColName
        )

    def lookbackTensor(self, featureCols, lookbackWindowSize: int):
        """TPU-native dense [K, L, w, F] lookback tensor + validity mask."""
        from tempo_tpu import rolling

        return rolling.lookback_tensor(self, featureCols, lookbackWindowSize)

    # ------------------------------------------------------------------
    # Sequence-number constructor (parity: scala TSDF.scala:584-616)
    # ------------------------------------------------------------------

    @classmethod
    def fromOrderingColumns(
        cls,
        df: pd.DataFrame,
        ts_col: str,
        ordering_cols: Sequence[str],
        partition_cols: Optional[List[str]] = None,
        sequence_col_name: str = DEFAULT_SEQ_COLNAME,
    ) -> "TSDF":
        """Synthesize a total-order sequence column from ordering columns
        via a per-key row_number, like the Scala sequence-number ctor."""
        pcols = partition_cols or []
        sort_cols = pcols + list(ordering_cols)
        order = df.sort_values(sort_cols, kind="stable").index
        seq = np.empty(len(df), dtype=np.int64)
        if pcols:
            grouped = df.loc[order].groupby(pcols, sort=False).cumcount() + 1
            seq[order] = grouped.to_numpy()
        else:
            seq[order] = np.arange(1, len(df) + 1)
        out = df.copy()
        out[sequence_col_name] = seq
        return cls(out, ts_col, pcols, sequence_col_name)
