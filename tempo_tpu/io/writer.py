"""Optimized columnar persistence (parity: python/tempo/io.py:10-43).

The reference writes a Delta table partitioned by ``event_dt`` with a
derived ``event_time`` (HHMMSS double) column, then ZORDERs by
(partition cols + optimization cols + event_time) on Databricks.

TPU-native analog: a partitioned Parquet dataset (pyarrow) laid out the
same way - hive-partitioned by ``event_dt``, rows *sorted* within each
file by (partition cols + optimization cols + event_time), which is the
single-dimension-ordering equivalent of the Z-order data-skipping
optimisation (row-group statistics become selective for exactly those
columns).  Reading back restores the frame for device packing.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np
import pandas as pd

logger = logging.getLogger(__name__)

WAREHOUSE_ENV = "TEMPO_TPU_WAREHOUSE"
DEFAULT_WAREHOUSE = "tempo_tpu_warehouse"


def _table_path(tab_name: str, base_dir: Optional[str]) -> str:
    from tempo_tpu import config

    base = base_dir or config.get(WAREHOUSE_ENV, DEFAULT_WAREHOUSE)
    return os.path.join(base, tab_name)


def write(tsdf, tab_name: str, optimization_cols: Optional[List[str]] = None,
          base_dir: Optional[str] = None, format: str = "parquet") -> str:
    """Write the TSDF as a clustered, sort-optimized Parquet table.

    Returns the table path.  Derived columns mirror io.py:29-33:
    ``event_dt`` = date of ts, ``event_time`` = HHMMSS.fff as double.

    Overwrite semantics (v0.16): "write a new generation
    transactionally, then atomically swing a pointer" — the table is a
    :mod:`tempo_tpu.store` generation table, so the previous version
    survives ANY kill, a killed write re-issued with the same frame
    resumes with zero committed-segment re-writes, and foreign staged
    state is refused by name.  The pre-v0.16 destructive
    rmtree-then-rewrite is gone (MIGRATION.md).

    ``format="delta"`` keeps the Spark-readable root layout (hive
    partitions + ``_delta_log``) and therefore cannot use generation
    directories; it stages the whole table to a temp sibling, fsyncs,
    and atomically swaps — the old table survives a kill at any point
    (``read`` falls back to the ``.bak`` survivor of a mid-swap
    crash)."""
    if format not in ("parquet", "delta"):
        raise ValueError("format must be 'parquet' or 'delta'")
    from tempo_tpu.store import engine as store_engine

    df, sort_cols = store_engine.clustered_frame(tsdf, optimization_cols)
    path = _table_path(tab_name, base_dir)
    if format == "delta":
        df = df.sort_values(sort_cols, kind="stable") if sort_cols else df
        _replace_table_dir(path, lambda tmp: _write_delta(df, tmp))
    else:
        store_engine.Store(os.path.dirname(path)).write_table(
            tab_name, df, sort_cols,
            source_fp=store_engine.source_fingerprint(tsdf))
    logger.info("wrote %d rows to %s (sorted by %s)", len(df), path, sort_cols)
    return path


def _fsync_tree(path: str) -> None:
    """fsync every file (and directory) under ``path`` so the staged
    replacement is durable BEFORE the atomic swap makes it live."""
    for root, _dirs, files in os.walk(path):
        for f in files:
            fd = os.open(os.path.join(root, f), os.O_RDONLY)
            try:
                os.fsync(fd)
            finally:
                os.close(fd)
        fd = os.open(root, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)


def _replace_table_dir(path: str, build) -> None:
    """The data-loss fix for the seed-era overwrite: NEVER delete the
    old table before its replacement exists.  ``build(tmp)`` writes the
    new table into a temp sibling; it is fsync'd, then swapped in with
    the checkpoint three-step (old → ``.bak``, staged → live, drop
    ``.bak``) — a kill at any point leaves either the old table at
    ``path`` or, mid-swap, at ``path + ".bak"`` where ``read`` finds
    it."""
    import shutil

    tmp = path + ".staging"
    bak = path + ".bak"
    if os.path.isdir(tmp):
        shutil.rmtree(tmp)          # residue of an earlier killed write
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    try:
        build(tmp)
        _fsync_tree(tmp)
        if os.path.exists(bak):
            shutil.rmtree(bak)
        if os.path.exists(path):
            os.replace(path, bak)
        os.replace(tmp, path)
        shutil.rmtree(bak, ignore_errors=True)
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise


# Spark SQL type names for the Delta schemaString
_SPARK_TYPES = {
    "int8": "byte", "int16": "short", "int32": "integer", "int64": "long",
    "uint8": "short", "uint16": "integer", "uint32": "long",
    "uint64": "long",
    "float32": "float", "float64": "double", "bool": "boolean",
    "object": "string", "string": "string",
}


def _spark_type(dtype) -> str:
    name = str(dtype)
    if name.startswith("datetime64"):
        return "timestamp"
    if name.startswith("Int"):
        return _SPARK_TYPES.get(name.lower(), "long")
    return _SPARK_TYPES.get(name, "string")


def _write_delta(df: pd.DataFrame, path: str) -> None:
    """One parquet file per event_dt partition + a version-0 Delta
    commit (protocol, metaData with a Spark-JSON schema, add actions)."""
    import json
    import time
    import uuid

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    now_ms = int(time.time() * 1000)
    # Spark's parquet reader rejects TIMESTAMP(NANOS) and has no
    # unsigned types: coerce to micros + signed before writing
    df = df.copy()
    for c in df.columns:
        if str(df[c].dtype) == "uint64":
            if len(df) and int(df[c].max()) > np.iinfo(np.int64).max:
                raise OverflowError(
                    f"column {c!r}: uint64 values above int64 range "
                    "cannot be represented in a Spark-readable table"
                )
            df[c] = df[c].astype(np.int64)
    adds = []
    for i, (dt_val, part) in enumerate(df.groupby("event_dt", sort=True)):
        part_dir = os.path.join(path, f"event_dt={dt_val}")
        os.makedirs(part_dir, exist_ok=True)
        fname = f"part-{i:05d}-{uuid.uuid4()}.snappy.parquet"
        fpath = os.path.join(part_dir, fname)
        # Delta stores partition values in the log, not the file
        table = pa.Table.from_pandas(
            part.drop(columns=["event_dt"]), preserve_index=False
        )
        pq.write_table(table, fpath, compression="snappy",
                       coerce_timestamps="us",
                       allow_truncated_timestamps=True)
        adds.append({
            "add": {
                "path": f"event_dt={dt_val}/{fname}",
                "partitionValues": {"event_dt": str(dt_val)},
                "size": os.path.getsize(fpath),
                "modificationTime": now_ms,
                "dataChange": True,
                "stats": json.dumps({"numRecords": len(part)}),
            }
        })

    fields = [
        {"name": c, "type": _spark_type(df[c].dtype), "nullable": True,
         "metadata": {}}
        for c in df.columns if c != "event_dt"
    ] + [{"name": "event_dt", "type": "string", "nullable": True,
          "metadata": {}}]
    actions = [
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        {"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps({"type": "struct", "fields": fields}),
            "partitionColumns": ["event_dt"],
            "configuration": {},
            "createdTime": now_ms,
        }},
        *adds,
        {"commitInfo": {"timestamp": now_ms, "operation": "WRITE",
                        "operationParameters": {"mode": "Overwrite"}}},
    ]
    log_dir = os.path.join(path, "_delta_log")
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, f"{0:020d}.json"), "w") as f:
        for action in actions:
            f.write(json.dumps(action) + "\n")


def read(tab_name: str, ts_col: str = "event_ts",
         partition_cols: Optional[List[str]] = None,
         base_dir: Optional[str] = None, on_corrupt: str = "raise"):
    """Read a table written by :func:`write` back into a TSDF, through
    the hardened read path: store tables resolve their committed
    generation (torn pointer/commit state refused by name), and corrupt
    row groups surface :class:`~tempo_tpu.io.ingest.
    CorruptRowGroupError` with the exact ranges named
    (``on_corrupt="quarantine"`` reads around them) instead of an
    opaque pyarrow traceback.  Legacy (pre-v0.16) and delta-format
    tables read through the same machinery; a table caught mid-swap by
    a crash falls back to its ``.bak`` survivor."""
    from tempo_tpu.frame import TSDF
    from tempo_tpu.store import engine as store_engine

    path = _table_path(tab_name, base_dir)
    if not os.path.isdir(path) and os.path.isdir(path + ".bak"):
        path = path + ".bak"    # crash between the two swap renames
    ds_path = store_engine.resolve_dataset_path(path)
    df = store_engine.read_dataset_df(ds_path, on_corrupt=on_corrupt)
    df = df.drop(columns=[c for c in ("event_dt", "event_time") if c in df.columns])
    return TSDF(df, ts_col=ts_col, partition_cols=partition_cols)
