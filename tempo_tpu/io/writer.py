"""Optimized columnar persistence (parity: python/tempo/io.py:10-43).

The reference writes a Delta table partitioned by ``event_dt`` with a
derived ``event_time`` (HHMMSS double) column, then ZORDERs by
(partition cols + optimization cols + event_time) on Databricks.

TPU-native analog: a partitioned Parquet dataset (pyarrow) laid out the
same way - hive-partitioned by ``event_dt``, rows *sorted* within each
file by (partition cols + optimization cols + event_time), which is the
single-dimension-ordering equivalent of the Z-order data-skipping
optimisation (row-group statistics become selective for exactly those
columns).  Reading back restores the frame for device packing.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np
import pandas as pd

logger = logging.getLogger(__name__)

WAREHOUSE_ENV = "TEMPO_TPU_WAREHOUSE"
DEFAULT_WAREHOUSE = "tempo_tpu_warehouse"


def _table_path(tab_name: str, base_dir: Optional[str]) -> str:
    from tempo_tpu import config

    base = base_dir or config.get(WAREHOUSE_ENV, DEFAULT_WAREHOUSE)
    return os.path.join(base, tab_name)


def write(tsdf, tab_name: str, optimization_cols: Optional[List[str]] = None,
          base_dir: Optional[str] = None, format: str = "parquet") -> str:
    """Write the TSDF as a partitioned, sort-optimized Parquet dataset.

    Returns the table path.  Derived columns mirror io.py:29-33:
    ``event_dt`` = date of ts, ``event_time`` = HHMMSS.fff as double.

    ``format="delta"`` also commits a Delta transaction log
    (``_delta_log/...0.json`` with protocol/metaData/add actions) so the
    output is a table Spark + delta readers accept as-is — the two-way
    leg of the reference's Delta writer (io.py:10-43).
    """
    if format not in ("parquet", "delta"):
        raise ValueError("format must be 'parquet' or 'delta'")
    import pyarrow as pa
    import pyarrow.parquet as pq

    df = tsdf.df.copy()
    ts = pd.to_datetime(df[tsdf.ts_col])
    df["event_dt"] = ts.dt.date.astype(str)
    df["event_time"] = (
        ts.dt.hour * 10000 + ts.dt.minute * 100 + ts.dt.second
        + ts.dt.microsecond / 1e6
    ).astype(float)

    # column rotation parity (io.py:34-36): derived cols lead
    cols = list(df.columns)
    df = df[cols[-1:] + cols[:-1]]

    opt_cols = (optimization_cols or []) + ["event_time"]
    sort_cols = [c for c in tsdf.partitionCols + opt_cols if c in df.columns]
    if sort_cols:
        df = df.sort_values(sort_cols, kind="stable")

    path = _table_path(tab_name, base_dir)
    # full-table overwrite like the reference's write.mode("overwrite")
    # (io.py:37): stale partitions from prior writes must not survive
    import shutil

    if os.path.isdir(path):
        shutil.rmtree(path)

    if format == "delta":
        _write_delta(df, path)
    else:
        table = pa.Table.from_pandas(df, preserve_index=False)
        pq.write_to_dataset(
            table,
            root_path=path,
            partition_cols=["event_dt"],
        )
    logger.info("wrote %d rows to %s (sorted by %s)", len(df), path, sort_cols)
    return path


# Spark SQL type names for the Delta schemaString
_SPARK_TYPES = {
    "int8": "byte", "int16": "short", "int32": "integer", "int64": "long",
    "uint8": "short", "uint16": "integer", "uint32": "long",
    "uint64": "long",
    "float32": "float", "float64": "double", "bool": "boolean",
    "object": "string", "string": "string",
}


def _spark_type(dtype) -> str:
    name = str(dtype)
    if name.startswith("datetime64"):
        return "timestamp"
    if name.startswith("Int"):
        return _SPARK_TYPES.get(name.lower(), "long")
    return _SPARK_TYPES.get(name, "string")


def _write_delta(df: pd.DataFrame, path: str) -> None:
    """One parquet file per event_dt partition + a version-0 Delta
    commit (protocol, metaData with a Spark-JSON schema, add actions)."""
    import json
    import time
    import uuid

    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    now_ms = int(time.time() * 1000)
    # Spark's parquet reader rejects TIMESTAMP(NANOS) and has no
    # unsigned types: coerce to micros + signed before writing
    df = df.copy()
    for c in df.columns:
        if str(df[c].dtype) == "uint64":
            if len(df) and int(df[c].max()) > np.iinfo(np.int64).max:
                raise OverflowError(
                    f"column {c!r}: uint64 values above int64 range "
                    "cannot be represented in a Spark-readable table"
                )
            df[c] = df[c].astype(np.int64)
    adds = []
    for i, (dt_val, part) in enumerate(df.groupby("event_dt", sort=True)):
        part_dir = os.path.join(path, f"event_dt={dt_val}")
        os.makedirs(part_dir, exist_ok=True)
        fname = f"part-{i:05d}-{uuid.uuid4()}.snappy.parquet"
        fpath = os.path.join(part_dir, fname)
        # Delta stores partition values in the log, not the file
        table = pa.Table.from_pandas(
            part.drop(columns=["event_dt"]), preserve_index=False
        )
        pq.write_table(table, fpath, compression="snappy",
                       coerce_timestamps="us",
                       allow_truncated_timestamps=True)
        adds.append({
            "add": {
                "path": f"event_dt={dt_val}/{fname}",
                "partitionValues": {"event_dt": str(dt_val)},
                "size": os.path.getsize(fpath),
                "modificationTime": now_ms,
                "dataChange": True,
                "stats": json.dumps({"numRecords": len(part)}),
            }
        })

    fields = [
        {"name": c, "type": _spark_type(df[c].dtype), "nullable": True,
         "metadata": {}}
        for c in df.columns if c != "event_dt"
    ] + [{"name": "event_dt", "type": "string", "nullable": True,
          "metadata": {}}]
    actions = [
        {"protocol": {"minReaderVersion": 1, "minWriterVersion": 2}},
        {"metaData": {
            "id": str(uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": json.dumps({"type": "struct", "fields": fields}),
            "partitionColumns": ["event_dt"],
            "configuration": {},
            "createdTime": now_ms,
        }},
        *adds,
        {"commitInfo": {"timestamp": now_ms, "operation": "WRITE",
                        "operationParameters": {"mode": "Overwrite"}}},
    ]
    log_dir = os.path.join(path, "_delta_log")
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, f"{0:020d}.json"), "w") as f:
        for action in actions:
            f.write(json.dumps(action) + "\n")


def read(tab_name: str, ts_col: str = "event_ts",
         partition_cols: Optional[List[str]] = None,
         base_dir: Optional[str] = None):
    """Read a table written by :func:`write` back into a TSDF."""
    import pyarrow.parquet as pq

    from tempo_tpu.frame import TSDF

    path = _table_path(tab_name, base_dir)
    df = pq.read_table(path).to_pandas()
    df = df.drop(columns=[c for c in ("event_dt", "event_time") if c in df.columns])
    return TSDF(df, ts_col=ts_col, partition_cols=partition_cols)
