"""Optimized columnar persistence (parity: python/tempo/io.py:10-43).

The reference writes a Delta table partitioned by ``event_dt`` with a
derived ``event_time`` (HHMMSS double) column, then ZORDERs by
(partition cols + optimization cols + event_time) on Databricks.

TPU-native analog: a partitioned Parquet dataset (pyarrow) laid out the
same way - hive-partitioned by ``event_dt``, rows *sorted* within each
file by (partition cols + optimization cols + event_time), which is the
single-dimension-ordering equivalent of the Z-order data-skipping
optimisation (row-group statistics become selective for exactly those
columns).  Reading back restores the frame for device packing.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional

import numpy as np
import pandas as pd

logger = logging.getLogger(__name__)

WAREHOUSE_ENV = "TEMPO_TPU_WAREHOUSE"
DEFAULT_WAREHOUSE = "tempo_tpu_warehouse"


def _table_path(tab_name: str, base_dir: Optional[str]) -> str:
    base = base_dir or os.environ.get(WAREHOUSE_ENV, DEFAULT_WAREHOUSE)
    return os.path.join(base, tab_name)


def write(tsdf, tab_name: str, optimization_cols: Optional[List[str]] = None,
          base_dir: Optional[str] = None) -> str:
    """Write the TSDF as a partitioned, sort-optimized Parquet dataset.

    Returns the table path.  Derived columns mirror io.py:29-33:
    ``event_dt`` = date of ts, ``event_time`` = HHMMSS.fff as double.
    """
    import pyarrow as pa
    import pyarrow.parquet as pq

    df = tsdf.df.copy()
    ts = pd.to_datetime(df[tsdf.ts_col])
    df["event_dt"] = ts.dt.date.astype(str)
    df["event_time"] = (
        ts.dt.hour * 10000 + ts.dt.minute * 100 + ts.dt.second
        + ts.dt.microsecond / 1e6
    ).astype(float)

    # column rotation parity (io.py:34-36): derived cols lead
    cols = list(df.columns)
    df = df[cols[-1:] + cols[:-1]]

    opt_cols = (optimization_cols or []) + ["event_time"]
    sort_cols = [c for c in tsdf.partitionCols + opt_cols if c in df.columns]
    if sort_cols:
        df = df.sort_values(sort_cols, kind="stable")

    path = _table_path(tab_name, base_dir)
    # full-table overwrite like the reference's write.mode("overwrite")
    # (io.py:37): stale partitions from prior writes must not survive
    import shutil

    if os.path.isdir(path):
        shutil.rmtree(path)
    table = pa.Table.from_pandas(df, preserve_index=False)
    pq.write_to_dataset(
        table,
        root_path=path,
        partition_cols=["event_dt"],
    )
    logger.info("wrote %d rows to %s (sorted by %s)", len(df), path, sort_cols)
    return path


def read(tab_name: str, ts_col: str = "event_ts",
         partition_cols: Optional[List[str]] = None,
         base_dir: Optional[str] = None):
    """Read a table written by :func:`write` back into a TSDF."""
    import pyarrow.parquet as pq

    from tempo_tpu.frame import TSDF

    path = _table_path(tab_name, base_dir)
    df = pq.read_table(path).to_pandas()
    df = df.drop(columns=[c for c in ("event_dt", "event_time") if c in df.columns])
    return TSDF(df, ts_col=ts_col, partition_cols=partition_cols)
