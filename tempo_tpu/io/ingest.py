"""Chunked / out-of-core Parquet ingest (VERDICT r1 gap #2).

Spark streams arbitrarily large inputs through executors; the packed
layout previously required the whole dataset in one process's host
memory (`packing.build_flat_layout`).  This module packs a Parquet
dataset *straight into device-sharded arrays* with bounded host
memory:

* **pass 1** — stream only (partition cols, ts) column batches to
  build the key census: per-key row counts, the padded series length
  L, and the deterministic key order (lexicographic — independent of
  file layout, unlike the in-memory first-appearance order).
* **pass 2** — one series *shard* at a time (the mesh's own ingest
  unit, `process_series_range` analog): stream row batches filtered to
  that shard's keys (predicate pushdown prunes row groups when the
  dataset was written sort-clustered by `io.writer`), sort, pack each
  numeric column to [K_shard, L], and `device_put` the per-device
  blocks.  The global sharded `jax.Array` is assembled from the
  single-device blocks, so no host ever holds more than one shard of
  one column (+ one streaming batch).

Host working-set bound: ``K_shard x L`` values for one column at a
time.  ``budget_bytes`` enforces it — ingest *fails loudly* rather
than silently ballooning past the cap (the test runs a dataset >= 2x
the cap to prove the path really streams).

Non-numeric columns cannot ride an out-of-core frame (they would need
host materialisation) and are skipped with a log notice; sequence
columns are not supported here.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tempo_tpu import packing, resilience

logger = logging.getLogger(__name__)


def _dataset(path: str):
    import pyarrow.dataset as pads

    return pads.dataset(path, partitioning="hive")


def _validate_dataset(ds, path: str, ts_col: str,
                      partition_cols: List[str]) -> None:
    """Fail fast, naming the offending column, instead of surfacing a
    downstream shape/KeyError after two streaming passes."""
    names = set(ds.schema.names)
    missing = [c for c in [ts_col, *partition_cols] if c not in names]
    if missing:
        raise ValueError(
            f"from_parquet: dataset at {path!r} has no column(s) "
            f"{', '.join(repr(c) for c in missing)}; schema columns are "
            f"{sorted(names)}"
        )
    if ds.count_rows() == 0:
        raise ValueError(
            f"from_parquet: dataset at {path!r} is empty (0 rows) — "
            "nothing to pack"
        )


def _census(ds, ts_col: str, partition_cols: List[str], batch_rows: int):
    """Pass 1: per-key row counts + global max series length."""
    counts: Dict[Tuple, int] = {}
    for batch in ds.to_batches(columns=partition_cols + [ts_col],
                               batch_size=batch_rows):
        if batch.num_rows == 0:
            continue
        dfb = batch.to_pandas()
        if partition_cols:
            grp = dfb.groupby(partition_cols, sort=False, dropna=False).size()
            for key, n in grp.items():
                key = key if isinstance(key, tuple) else (key,)
                counts[key] = counts.get(key, 0) + int(n)
        else:
            counts[()] = counts.get((), 0) + len(dfb)
    if not counts:
        counts[tuple([None] * len(partition_cols))] = 0
    keys = sorted(counts, key=lambda t: tuple(str(v) for v in t))
    key_frame = pd.DataFrame(
        [list(k) for k in keys] if partition_cols else None,
        columns=partition_cols or None,
        index=range(len(keys)),
    )
    lengths = np.asarray([counts[k] for k in keys], dtype=np.int64)
    return key_frame, lengths


def _numeric_schema_cols(ds, ts_col: str, partition_cols: List[str],
                         columns: Optional[List[str]]):
    import pyarrow as pa

    skip = {ts_col, *partition_cols, "event_dt", "event_time"}
    out = []
    for field in ds.schema:
        if field.name in skip:
            continue
        if columns is not None and field.name not in columns:
            continue
        if (pa.types.is_integer(field.type) or pa.types.is_floating(field.type)):
            out.append(field.name)
        else:
            logger.info(
                "out-of-core ingest skips non-numeric column %r", field.name
            )
    return out


def from_parquet(
    path: str,
    ts_col: str = "event_ts",
    partition_cols: Optional[List[str]] = None,
    mesh=None,
    time_axis: Optional[str] = None,
    series_axis: str = "series",
    columns: Optional[List[str]] = None,
    batch_rows: int = 1 << 18,
    budget_bytes: Optional[int] = None,
    halo_fraction: float = 0.5,
    retry_policy: Optional["resilience.RetryPolicy"] = None,
):
    """Stream a Parquet dataset into a :class:`DistributedTSDF` with
    bounded host memory (see module docstring).

    Both streaming passes are read-only, so transient IO faults (flaky
    network filesystems, connection resets) are retried at pass
    granularity under ``retry_policy`` (default
    :data:`tempo_tpu.resilience.DEFAULT_IO_POLICY`); budget violations
    and schema errors are permanent and surface immediately."""
    from tempo_tpu.dist import DistCol, DistributedTSDF
    from tempo_tpu.parallel.mesh import make_mesh

    pcols = list(partition_cols or [])
    mesh = mesh if mesh is not None else make_mesh()
    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1

    retry = resilience.retrying(
        retry_policy or resilience.DEFAULT_IO_POLICY, label="parquet-ingest")
    ds = retry(_dataset)(path)
    _validate_dataset(ds, path, ts_col, pcols)
    key_frame, lengths = retry(_census)(ds, ts_col, pcols, batch_rows)
    K = len(lengths)
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    L = packing.pad_length(int(lengths.max(initial=0)), multiple=8 * n_t)
    num_cols = _numeric_schema_cols(ds, ts_col, pcols, columns)

    blk = K_dev // n_s
    dt = packing.compute_dtype()
    shard_bytes = blk * L * max(np.dtype(dt).itemsize, 8)
    if budget_bytes is not None and shard_bytes > budget_bytes:
        raise MemoryError(
            f"one series shard needs {shard_bytes} host bytes "
            f"({blk} series x {L} slots) > budget {budget_bytes}; use a "
            "mesh with more series shards"
        )

    # device placement map: mesh coordinates -> device, per (si, ti)
    ax_s = mesh.axis_names.index(series_axis)
    devs = np.moveaxis(np.asarray(mesh.devices), ax_s, 0).reshape(n_s, -1)
    if time_axis:
        ax_t = mesh.axis_names.index(time_axis)
        order = np.moveaxis(
            np.asarray(mesh.devices), (ax_s, ax_t), (0, 1)
        ).reshape(n_s, n_t)
    else:
        order = devs.reshape(n_s, n_t)

    Lt = L // n_t
    spec = P(*([series_axis, time_axis] if time_axis else [series_axis, None]))
    sharding = NamedSharding(mesh, spec)

    # per-column per-device block lists, filled shard by shard
    blocks: Dict[str, List] = {"__ts__": [], "__mask__": []}
    for c in num_cols:
        blocks[c] = []
        blocks[c + "/valid"] = []

    import pyarrow.compute as pc

    read_cols = pcols + [ts_col] + num_cols
    for si in range(n_s):
        k0, k1 = si * blk, min((si + 1) * blk, K)
        if k1 <= k0:
            # padding shard past the real key range: all-pad blocks
            _scatter_shard(blocks["__ts__"],
                           np.full((blk, L), packing.TS_PAD, np.int64),
                           order[si], Lt)
            _scatter_shard(blocks["__mask__"],
                           np.zeros((blk, L), np.bool_), order[si], Lt)
            for c in num_cols:
                _scatter_shard(blocks[c], np.full((blk, L), np.nan, dt),
                               order[si], Lt)
                _scatter_shard(blocks[c + "/valid"],
                               np.zeros((blk, L), np.bool_), order[si], Lt)
            continue
        shard_keys = key_frame.iloc[k0:k1] if pcols else None
        # stream this shard's rows: pushdown on the first partition col
        filt = None
        if pcols:
            vals = shard_keys[pcols[0]].unique().tolist()
            filt = pc.field(pcols[0]).isin(vals)
        shard_df = retry(_stream_shard)(
            ds, read_cols, batch_rows, filt, shard_keys, pcols,
            budget_bytes, si,
        )

        # local layout for this shard's keys (ids relative to k0)
        if pcols and len(shard_df):
            kid = shard_df.merge(
                shard_keys.reset_index().rename(columns={"index": "__kid__"}),
                on=pcols, how="left",
            )["__kid__"].to_numpy(np.int64) - k0
        else:
            kid = np.zeros(len(shard_df), dtype=np.int64)
        ts_ns = (
            packing.series_to_ns(shard_df[ts_col])
            if len(shard_df) else np.zeros(0, np.int64)
        )
        order_idx = np.lexsort((ts_ns, kid))
        kid, ts_ns = kid[order_idx], ts_ns[order_idx]
        starts = np.zeros(blk + 1, dtype=np.int64)
        np.cumsum(np.bincount(kid, minlength=blk), out=starts[1:])
        pos = np.arange(len(kid), dtype=np.int64) - starts[kid]

        def pack(vals, fill, dtype):
            out = np.full((blk, L), fill, dtype=dtype)
            if len(vals):
                out[kid, pos] = vals
            return out

        local_lens = starts[1:] - starts[:-1]
        ts_p = pack(ts_ns, packing.TS_PAD, np.int64)
        mask_p = np.arange(L)[None, :] < local_lens[:, None]
        _scatter_shard(blocks["__ts__"], ts_p, order[si], Lt)
        _scatter_shard(blocks["__mask__"], mask_p, order[si], Lt)
        for c in num_cols:
            raw = (
                pd.to_numeric(shard_df[c], errors="coerce")
                .to_numpy(np.float64)[order_idx]
                if len(shard_df) else np.zeros(0, np.float64)
            )
            valid = ~np.isnan(raw)
            _scatter_shard(blocks[c], pack(raw.astype(dt), np.nan, dt),
                           order[si], Lt)
            _scatter_shard(blocks[c + "/valid"],
                           pack(valid, False, np.bool_), order[si], Lt)
        del shard_df

    def assemble(name):
        shape = (K_dev, L)
        return jax.make_array_from_single_device_arrays(
            shape, sharding, blocks.pop(name)
        )

    ts_d = assemble("__ts__")
    mask_d = assemble("__mask__")
    cols = {
        c: DistCol(assemble(c), assemble(c + "/valid")) for c in num_cols
    }

    layout = packing.FlatLayout(
        key_ids=np.zeros(0, np.int64), ts_ns=np.zeros(0, np.int64),
        order=np.zeros(0, np.int64),
        starts=np.concatenate([[0], np.cumsum(lengths)]).astype(np.int64),
        key_frame=key_frame,
    )
    frame = DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout, ts_col,
        pcols, np.dtype("datetime64[ns]"), None, {}, halo_fraction,
    )
    # count as one logical pack event for the residency accounting
    from tempo_tpu import dist as dist_mod

    dist_mod._PACK_EVENTS += 1
    return frame


def _stream_shard(ds, read_cols: List[str], batch_rows: int, filt,
                  shard_keys, pcols: List[str],
                  budget_bytes: Optional[int], si: int) -> pd.DataFrame:
    """Pass 2 unit of work: stream one series shard's row batches into
    a host frame.  Pure read (local ``parts`` rebuilt on every call),
    so the caller can retry it wholesale on transient IO faults."""
    parts = []
    held = 0
    for batch in ds.to_batches(columns=read_cols, batch_size=batch_rows,
                               filter=filt):
        if batch.num_rows == 0:
            continue
        dfb = batch.to_pandas()
        if pcols:
            # exact membership for compound keys
            marked = dfb.merge(
                shard_keys.assign(__in__=True), on=pcols, how="left"
            )
            dfb = dfb[marked["__in__"].fillna(False).to_numpy(bool)]
        if len(dfb) == 0:
            continue
        held += int(dfb.memory_usage(deep=False).sum())
        if budget_bytes is not None and held > budget_bytes:
            raise MemoryError(
                f"series shard {si} exceeded the host ingest budget "
                f"({held} > {budget_bytes} bytes)"
            )
        parts.append(dfb)
    return (
        pd.concat(parts, ignore_index=True)
        if parts else pd.DataFrame(columns=read_cols)
    )


def _scatter_shard(sink: List, host_block: np.ndarray, dev_row, Lt: int):
    """Split one series-shard host block along time and place each
    piece on its device; appends in mesh device order."""
    for ti, dev in enumerate(dev_row):
        sink.append(
            jax.device_put(host_block[:, ti * Lt:(ti + 1) * Lt], dev)
        )
