"""Chunked / out-of-core Parquet ingest (VERDICT r1 gap #2).

Spark streams arbitrarily large inputs through executors; the packed
layout previously required the whole dataset in one process's host
memory (`packing.build_flat_layout`).  This module packs a Parquet
dataset *straight into device-sharded arrays* with bounded host
memory:

* **pass 1** — stream only (partition cols, ts) column batches to
  build the key census: per-key row counts, the padded series length
  L, and the deterministic key order (lexicographic — independent of
  file layout, unlike the in-memory first-appearance order).
* **pass 2** — one series *shard* at a time (the mesh's own ingest
  unit, `process_series_range` analog): stream row batches filtered to
  that shard's keys (predicate pushdown prunes row groups when the
  dataset was written sort-clustered by `io.writer`), sort, pack each
  numeric column to [K_shard, L], and `device_put` the per-device
  blocks.  The global sharded `jax.Array` is assembled from the
  single-device blocks, so no host ever holds more than one shard of
  one column (+ one streaming batch).

Host working-set bound: ``K_shard x L`` values for one column at a
time.  ``budget_bytes`` enforces it — ingest *fails loudly* rather
than silently ballooning past the cap (the test runs a dataset >= 2x
the cap to prove the path really streams).

Transactional ingest (the batch-plane fault domain):

* **per-shard progress manifests** — ``resume_dir`` makes the ingest
  resumable mid-run: the key census and every completed series shard's
  packed host blocks are persisted (CRC'd, atomic) as they finish, and
  a restarted ingest re-streams ONLY the shards that never committed —
  completed shards come back from their manifests without re-reading a
  byte of Parquet.  A resume directory stamped by a different
  (dataset, schema, mesh) ingest is refused by name
  (:class:`~tempo_tpu.resilience.CheckpointError`);
* **row-group quarantine** — a corrupt row group (or a torn/unreadable
  file) no longer aborts the whole ingest opaquely: the range is
  quarantined and either reported in ONE named
  :class:`CorruptRowGroupError` listing every quarantined range
  (``on_corrupt="raise"``, the default) or skipped with a warning and
  recorded on the returned frame (``on_corrupt="quarantine"``);
* **one end-to-end deadline** — ``deadline_s`` (default
  ``TEMPO_TPU_INGEST_DEADLINE_S``) is ONE wall-clock budget across
  validation, census, every shard stream and device placement, dying
  with a stage-named :class:`~tempo_tpu.resilience.DeadlineExceeded`;
* **per-file circuit breaker** — ``breaker`` quarantines a flapping
  file after ``TEMPO_TPU_BREAKER_THRESHOLD`` consecutive failures
  instead of letting it burn the whole pass's retry budget.

Non-numeric columns cannot ride an out-of-core frame (they would need
host materialisation) and are skipped with a log notice; sequence
columns are not supported here.

Slab pipelining (``TEMPO_TPU_INGEST_RING``): the shard loop above and
any out-of-core slab sweep built on :func:`sweep_slabs` run as a
bounded-ring three-stage pipeline — decode/pack of slab N+1 (a
background producer thread) and the drain of slab N-1 (a background
collector thread) overlap the compute/placement of slab N (the main
thread).  The main thread still consumes slabs strictly in order, so
the pipelined result is BITWISE-identical to the serial loop by
construction; ``ring<=1`` runs the identical code fully serially.
Worst case ≈ ``ring + 1`` slab buffers are resident (one loading, up
to ``ring - 1`` queued, one computing).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import logging
import os
import zipfile
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from tempo_tpu import packing, resilience
from tempo_tpu.resilience import CheckpointError, FailureKind

logger = logging.getLogger(__name__)

_RESUME_FORMAT = 1


class CorruptRowGroupError(RuntimeError):
    """Parquet data corruption found during ingest, with every
    quarantined range listed: ``ranges`` is a tuple of dicts
    ``{"file", "row_group", "rows", "reason"}`` (``row_group`` None =
    the whole file is unreadable).  Self-describes as
    ``CORRUPTED_ARTIFACT`` for :func:`tempo_tpu.resilience.classify` —
    re-reading corrupt bytes is never the recovery."""

    failure_kind = FailureKind.CORRUPTED_ARTIFACT

    def __init__(self, message: str, ranges: Sequence[dict] = ()):
        super().__init__(message)
        self.ranges = tuple(ranges)


@dataclasses.dataclass
class _IngestCtx:
    """Fault-domain state threaded through both streaming passes: the
    one end-to-end deadline, the per-file circuit breaker, and the
    quarantine ledger (frozen across passes — a range quarantined
    during the census stays skipped in the shard pass, so the packed
    layout can never see rows the census did not count)."""

    deadline: Optional[resilience.Deadline] = None
    breaker: Optional[resilience.CircuitBreaker] = None
    on_corrupt: str = "raise"
    quarantined: List[dict] = dataclasses.field(default_factory=list)
    skip: set = dataclasses.field(default_factory=set)

    def check(self, stage: str) -> None:
        if self.deadline is not None:
            self.deadline.check(stage)

    def quarantine(self, path: str, row_group: Optional[int],
                   rows: Optional[int], reason: str) -> None:
        key = (path, row_group)
        if key in self.skip:
            return
        self.skip.add(key)
        self.quarantined.append({
            "file": path, "row_group": row_group, "rows": rows,
            "reason": reason,
        })
        logger.warning(
            "from_parquet: quarantined %s%s (%s)", path,
            "" if row_group is None else f" row group {row_group}",
            reason)

    def ledger_crc(self) -> int:
        """CRC-32 of the current quarantine ledger's key set — stamped
        into every committed shard manifest, so a resume can tell a
        shard packed under a DIFFERENT ledger (rows included that are
        now quarantined, or vice versa) from a current one."""
        import zlib

        # key=repr: the skip set mixes int and None row-group slots,
        # which plain tuple comparison cannot order
        return zlib.crc32(
            repr(sorted(self.skip, key=repr)).encode()) & 0xFFFFFFFF

    def raise_if_corrupt(self) -> None:
        """``on_corrupt="raise"``: surface ONE named error listing
        every quarantined range instead of an opaque mid-stream
        abort."""
        if self.on_corrupt == "raise" and self.quarantined:
            lst = "; ".join(
                f"{q['file']}"
                + ("" if q["row_group"] is None
                   else f"[rg {q['row_group']}]")
                + f": {q['reason']}" for q in self.quarantined)
            raise CorruptRowGroupError(
                f"from_parquet: {len(self.quarantined)} corrupt/"
                f"unreadable range(s) quarantined — {lst}.  Pass "
                f"on_corrupt='quarantine' to ingest around them "
                f"(the skipped ranges are recorded on the frame).",
                ranges=self.quarantined)


def _dataset(path: str, ctx: Optional[_IngestCtx] = None):
    import pyarrow.dataset as pads

    try:
        return pads.dataset(path, partitioning="hive")
    except (OSError, ValueError) as e:
        # discovery itself reads footers: a torn-write file (footer
        # magic gone) fails the whole dataset open before any
        # row-group quarantine can act.  Re-discover excluding
        # unreadable files and quarantine exactly the excluded set.
        if ctx is None or resilience.classify(e) is FailureKind.TRANSIENT_IO:
            raise
        ds = pads.dataset(path, partitioning="hive",
                          exclude_invalid_files=True)
        present = set(getattr(ds, "files", ()) or ())
        if present:
            on_disk = []
            for root, _dirs, files in os.walk(path):
                for f in files:
                    if not f.startswith(("_", ".")):
                        on_disk.append(os.path.join(root, f))
            for missing in sorted(set(on_disk) - present):
                ctx.quarantine(
                    missing, None, None,
                    f"unreadable file (torn write? footer does not "
                    f"parse): excluded at dataset discovery ({e})")
        if not ctx.quarantined:
            raise       # discovery failed for a reason we cannot name
        return ds


def _validate_dataset(ds, path: str, ts_col: str,
                      partition_cols: List[str]) -> None:
    """Fail fast, naming the offending column, instead of surfacing a
    downstream shape/KeyError after two streaming passes."""
    names = set(ds.schema.names)
    missing = [c for c in [ts_col, *partition_cols] if c not in names]
    if missing:
        raise ValueError(
            f"from_parquet: dataset at {path!r} has no column(s) "
            f"{', '.join(repr(c) for c in missing)}; schema columns are "
            f"{sorted(names)}"
        )
    try:
        n_rows = ds.count_rows()
    except (OSError, ValueError) as e:
        # metadata of some file is unreadable (torn footer): the
        # census pass quarantines it range-by-range; the empty check
        # just cannot run early
        logger.warning(
            "from_parquet: count_rows failed (%s); deferring the "
            "empty-dataset check to the census pass", e)
        return
    if n_rows == 0:
        raise ValueError(
            f"from_parquet: dataset at {path!r} is empty (0 rows) — "
            "nothing to pack"
        )


def _scan_fragment(frag, schema, columns, filt, batch_rows):
    """One scanner over one (row-group) fragment — module-level so the
    fault injectors and the flapping-file chaos phases can patch it."""
    import pyarrow.dataset as pads

    return pads.Scanner.from_fragment(
        frag, schema=schema, columns=columns, filter=filt,
        batch_size=batch_rows,
    ).to_batches()


def _iter_batches(ds, columns, filt, batch_rows, ctx: _IngestCtx,
                  stage: str):
    """Stream record batches row-group by row-group with the
    fault-domain contracts applied: the deadline is checked per batch
    (stage-named), transient IO errors re-raise (the pass-level retry
    wrapper owns them) after feeding the per-file breaker, an OPEN
    breaker quarantines the file instead of burning further attempts,
    and non-transient read failures quarantine exactly the corrupt
    row group (or the whole file when its footer is unreadable)."""
    ctx.check(stage)
    for frag in ds.get_fragments():
        path = getattr(frag, "path", "<fragment>")
        if (path, None) in ctx.skip:
            continue
        if ctx.breaker is not None:
            try:
                ctx.breaker.allow(path, label="ingest file")
            except resilience.QuarantinedError as e:
                ctx.quarantine(
                    path, None, None,
                    f"circuit breaker open after repeated failures "
                    f"({e})")
                continue
        try:
            rg_frags = list(frag.split_by_row_group())
        except (OSError, ValueError) as e:
            kind = resilience.classify(e)
            if kind is FailureKind.DEADLINE:
                raise           # a dead budget is never "corruption"
            if kind is FailureKind.TRANSIENT_IO:
                if ctx.breaker is not None:
                    ctx.breaker.record(path, False)
                raise
            ctx.quarantine(path, None, None,
                           f"unreadable file metadata: {e}")
            continue
        file_ok = True
        for rg in rg_frags:
            rg_id = rg.row_groups[0].id if rg.row_groups else None
            if (path, rg_id) in ctx.skip:
                continue
            try:
                for batch in _scan_fragment(rg, ds.schema, columns,
                                            filt, batch_rows):
                    ctx.check(stage)
                    yield batch
            except (OSError, ValueError) as e:
                kind = resilience.classify(e)
                if kind is FailureKind.DEADLINE:
                    # the per-batch ctx.check fired inside this try
                    # (DeadlineExceeded IS an OSError via TimeoutError)
                    # — quarantining readable data as corrupt because
                    # the BUDGET died would be silent data loss
                    raise
                if kind is FailureKind.TRANSIENT_IO:
                    file_ok = False
                    if ctx.breaker is not None:
                        ctx.breaker.record(path, False)
                    raise
                rows = rg.row_groups[0].num_rows if rg.row_groups \
                    else None
                ctx.quarantine(path, rg_id, rows,
                               f"corrupt row group: {e}")
        if file_ok and ctx.breaker is not None:
            ctx.breaker.record(path, True)


def _census(ds, ts_col: str, partition_cols: List[str], batch_rows: int,
            ctx: Optional[_IngestCtx] = None):
    """Pass 1: per-key row counts + global max series length."""
    ctx = ctx or _IngestCtx()
    counts: Dict[Tuple, int] = {}
    for batch in _iter_batches(ds, partition_cols + [ts_col], None,
                               batch_rows, ctx, stage="census"):
        if batch.num_rows == 0:
            continue
        dfb = batch.to_pandas()
        if partition_cols:
            grp = dfb.groupby(partition_cols, sort=False, dropna=False).size()
            for key, n in grp.items():
                key = key if isinstance(key, tuple) else (key,)
                counts[key] = counts.get(key, 0) + int(n)
        else:
            counts[()] = counts.get((), 0) + len(dfb)
    if not counts:
        counts[tuple([None] * len(partition_cols))] = 0
    keys = sorted(counts, key=lambda t: tuple(str(v) for v in t))
    key_frame = pd.DataFrame(
        [list(k) for k in keys] if partition_cols else None,
        columns=partition_cols or None,
        index=range(len(keys)),
    )
    lengths = np.asarray([counts[k] for k in keys], dtype=np.int64)
    return key_frame, lengths


def _numeric_schema_cols(ds, ts_col: str, partition_cols: List[str],
                         columns: Optional[List[str]]):
    import pyarrow as pa

    skip = {ts_col, *partition_cols, "event_dt", "event_time"}
    out = []
    for field in ds.schema:
        if field.name in skip:
            continue
        if columns is not None and field.name not in columns:
            continue
        if (pa.types.is_integer(field.type) or pa.types.is_floating(field.type)):
            out.append(field.name)
        else:
            logger.info(
                "out-of-core ingest skips non-numeric column %r", field.name
            )
    return out


def from_parquet(
    path: str,
    ts_col: str = "event_ts",
    partition_cols: Optional[List[str]] = None,
    mesh=None,
    time_axis: Optional[str] = None,
    series_axis: str = "series",
    columns: Optional[List[str]] = None,
    batch_rows: int = 1 << 18,
    budget_bytes: Optional[int] = None,
    halo_fraction: float = 0.5,
    retry_policy: Optional["resilience.RetryPolicy"] = None,
    deadline_s=None,
    resume_dir: Optional[str] = None,
    on_corrupt: str = "raise",
    breaker: Optional["resilience.CircuitBreaker"] = None,
    ring: Optional[int] = None,
):
    """Stream a Parquet dataset into a :class:`DistributedTSDF` with
    bounded host memory (see module docstring).

    Both streaming passes are read-only, so transient IO faults (flaky
    network filesystems, connection resets) are retried at pass
    granularity under ``retry_policy`` (default
    :data:`tempo_tpu.resilience.DEFAULT_IO_POLICY`); budget violations
    and schema errors are permanent and surface immediately.

    Fault-domain parameters (module docstring "Transactional ingest"):
    ``deadline_s`` (one stage-named wall-clock budget end to end;
    defaults to ``TEMPO_TPU_INGEST_DEADLINE_S``; a live
    :class:`~tempo_tpu.resilience.Deadline` is accepted too),
    ``resume_dir`` (per-shard CRC'd progress manifests: a killed ingest
    restarted with the same directory re-streams only uncommitted
    shards), ``on_corrupt`` (``"raise"``: one named
    :class:`CorruptRowGroupError` listing every quarantined range;
    ``"quarantine"``: skip + record on ``frame.ingest_quarantined``),
    and ``breaker`` (per-file circuit breaker: a flapping file is
    quarantined instead of burning the retry budget).

    ``ring`` (default ``TEMPO_TPU_INGEST_RING``) is the slab-buffer
    ring depth of the shard pipeline (:func:`sweep_slabs`): the
    producer thread streams + packs shard N+1 while the main thread
    places shard N on devices and commits its manifest in shard order.
    ``ring=1`` runs the identical loop serially; any depth produces
    the same bits (the main thread consumes shards in order either
    way), and the host working set grows to ≈ ``ring + 1`` packed
    shards."""
    from tempo_tpu import config
    from tempo_tpu.dist import DistCol, DistributedTSDF
    from tempo_tpu.parallel.mesh import make_mesh

    if on_corrupt not in ("raise", "quarantine"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'quarantine', got "
            f"{on_corrupt!r}")
    # store-aware: a transactional table directory (store engine
    # _CURRENT.json pointer) resolves to its committed generation — a
    # plain clustered Parquet dataset whose (series, time) sort order
    # the census pass reads back without a shuffle.  Torn pointer or
    # commit state refuses by name here, before any streaming pass.
    from tempo_tpu.store.engine import resolve_dataset_path

    path = resolve_dataset_path(path)
    pcols = list(partition_cols or [])
    mesh = mesh if mesh is not None else make_mesh()
    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1

    if deadline_s is None:
        deadline_s = config.get_float("TEMPO_TPU_INGEST_DEADLINE_S")
    ctx = _IngestCtx(
        deadline=resilience.Deadline.after(deadline_s),
        breaker=breaker, on_corrupt=on_corrupt,
    )
    retry = resilience.retrying(
        retry_policy or resilience.DEFAULT_IO_POLICY, label="parquet-ingest")
    ctx.check("dataset open")
    ds = retry(_dataset)(path, ctx)
    ctx.raise_if_corrupt()
    ctx.check("validation")
    _validate_dataset(ds, path, ts_col, pcols)

    resume = None
    if resume_dir is not None:
        resume = _ResumeLog(resume_dir, _resume_signature(
            path, ts_col, pcols, columns, mesh, series_axis, time_axis))
        resume.open(ctx)
    cached = resume.load_census() if resume is not None else None
    if cached is not None:
        key_frame, lengths = cached
        # the frozen quarantine ledger travels with the census: pass 2
        # of a resumed run must skip exactly what pass 1 skipped, or
        # rows the census never counted would overflow the layout
        for q in resume.census_quarantine():
            ctx.quarantine(q["file"], q.get("row_group"), q.get("rows"),
                           q["reason"])
        ctx.raise_if_corrupt()
        logger.info(
            "from_parquet: census restored from %s (%d keys, no "
            "Parquet re-read)", resume_dir, len(lengths))
    else:
        key_frame, lengths = retry(_census)(ds, ts_col, pcols,
                                            batch_rows, ctx)
        ctx.raise_if_corrupt()
        if int(lengths.sum()) == 0:
            raise ValueError(
                f"from_parquet: dataset at {path!r} is empty"
                + (f" after quarantining {len(ctx.quarantined)} "
                   f"range(s)" if ctx.quarantined else " (0 rows)")
                + " — nothing to pack")
        if resume is not None:
            resume.save_census(key_frame, lengths, ctx)
    K = len(lengths)
    k_mult = n_s * n_t
    K_dev = max(1, -(-K // k_mult)) * k_mult
    L = packing.pad_length(int(lengths.max(initial=0)), multiple=8 * n_t)
    num_cols = _numeric_schema_cols(ds, ts_col, pcols, columns)

    blk = K_dev // n_s
    dt = packing.compute_dtype()
    shard_bytes = blk * L * max(np.dtype(dt).itemsize, 8)
    if budget_bytes is not None and shard_bytes > budget_bytes:
        raise MemoryError(
            f"one series shard needs {shard_bytes} host bytes "
            f"({blk} series x {L} slots) > budget {budget_bytes}; use a "
            "mesh with more series shards"
        )

    # device placement map: mesh coordinates -> device, per (si, ti)
    ax_s = mesh.axis_names.index(series_axis)
    devs = np.moveaxis(np.asarray(mesh.devices), ax_s, 0).reshape(n_s, -1)
    if time_axis:
        ax_t = mesh.axis_names.index(time_axis)
        order = np.moveaxis(
            np.asarray(mesh.devices), (ax_s, ax_t), (0, 1)
        ).reshape(n_s, n_t)
    else:
        order = devs.reshape(n_s, n_t)

    Lt = L // n_t
    spec = P(*([series_axis, time_axis] if time_axis else [series_axis, None]))
    sharding = NamedSharding(mesh, spec)

    import pyarrow.compute as pc

    read_cols = pcols + [ts_col] + num_cols

    def run_shard_pass(use_manifests: bool):
        # per-column per-device block lists, filled shard by shard
        blocks: Dict[str, List] = {"__ts__": [], "__mask__": []}
        for c in num_cols:
            blocks[c] = []
            blocks[c + "/valid"] = []
        state = {"restored": 0}
        # per-key row counts as actually PACKED (quarantine may have
        # removed rows the census counted; the layout must not lie)
        true_lengths = np.zeros(K, dtype=np.int64)

        def load_slab(si: int):
            """Producer half (background thread under sweep_slabs):
            stream + decode + pack one shard — the CPU/IO-heavy work.
            The producer runs shards strictly in order, so the
            quarantine-ledger CRC captured here is the SAME one the
            serial loop would stamp (no later shard has streamed
            yet)."""
            ctx.check(f"shard {si} stream")
            k0, k1 = si * blk, min((si + 1) * blk, K)
            if k1 <= k0:
                # padding shard past the real key range: all-pad blocks
                planes = {"__ts__": np.full((blk, L), packing.TS_PAD,
                                            np.int64),
                          "__mask__": np.zeros((blk, L), np.bool_)}
                for c in num_cols:
                    planes[c] = np.full((blk, L), np.nan, dt)
                    planes[c + "/valid"] = np.zeros((blk, L), np.bool_)
                return ("pad", planes, 0, 0)
            if use_manifests and resume is not None:
                planes = resume.load_shard(si, num_cols, (blk, L),
                                           ledger_crc=ctx.ledger_crc())
                if planes is not None:
                    return ("restored", planes, 0, 0)
            shard_keys = key_frame.iloc[k0:k1] if pcols else None
            # stream this shard's rows: pushdown on the first
            # partition col
            filt = None
            if pcols:
                vals = shard_keys[pcols[0]].unique().tolist()
                filt = pc.field(pcols[0]).isin(vals)
            shard_df = retry(_stream_shard)(
                ds, read_cols, batch_rows, filt, shard_keys, pcols,
                budget_bytes, si, ctx,
            )

            # local layout for this shard's keys (ids relative to k0)
            if pcols and len(shard_df):
                kid = shard_df.merge(
                    shard_keys.reset_index().rename(
                        columns={"index": "__kid__"}),
                    on=pcols, how="left",
                )["__kid__"].to_numpy(np.int64) - k0
            else:
                kid = np.zeros(len(shard_df), dtype=np.int64)
            ts_ns = (
                packing.series_to_ns(shard_df[ts_col])
                if len(shard_df) else np.zeros(0, np.int64)
            )
            order_idx = np.lexsort((ts_ns, kid))
            kid, ts_ns = kid[order_idx], ts_ns[order_idx]
            starts = np.zeros(blk + 1, dtype=np.int64)
            np.cumsum(np.bincount(kid, minlength=blk), out=starts[1:])
            pos = np.arange(len(kid), dtype=np.int64) - starts[kid]
            overflow = pos >= L
            if overflow.any():
                # defensive: rows the census never counted (e.g. a file
                # probed back to life after pass-1 quarantined it)
                # cannot fit the padded layout — drop them loudly
                # rather than corrupt neighbouring series
                logger.warning(
                    "from_parquet: shard %d holds %d row(s) beyond the "
                    "census length L=%d (rows the census pass never "
                    "counted); dropping them", si, int(overflow.sum()),
                    L)
                keep = ~overflow
                kid, ts_ns, pos = kid[keep], ts_ns[keep], pos[keep]
                order_idx = order_idx[keep]
                starts = np.zeros(blk + 1, dtype=np.int64)
                np.cumsum(np.bincount(kid, minlength=blk),
                          out=starts[1:])

            def pack(vals, fill, dtype):
                out = np.full((blk, L), fill, dtype=dtype)
                if len(vals):
                    out[kid, pos] = vals
                return out

            ts_p = pack(ts_ns, packing.TS_PAD, np.int64)
            local_lens = starts[1:] - starts[:-1]
            mask_p = np.arange(L)[None, :] < local_lens[:, None]
            planes = {"__ts__": ts_p, "__mask__": mask_p}
            for c in num_cols:
                raw = (
                    pd.to_numeric(shard_df[c], errors="coerce")
                    .to_numpy(np.float64)[order_idx]
                    if len(shard_df) else np.zeros(0, np.float64)
                )
                valid = ~np.isnan(raw)
                planes[c] = pack(raw.astype(dt), np.nan, dt)
                planes[c + "/valid"] = pack(valid, False, np.bool_)
            return ("packed", planes, int(len(shard_df)),
                    ctx.ledger_crc())

        def place_slab(si: int, loaded):
            """Main-thread half: async device placement in shard order
            + the ordered manifest commit (commit order == shard order
            keeps the crash-consistency story of the serial loop)."""
            kind, planes, n_rows, ledger = loaded
            ctx.check(f"shard {si} place")
            k0, k1 = si * blk, min((si + 1) * blk, K)
            _scatter_shard(blocks["__ts__"], planes["__ts__"],
                           order[si], Lt)
            _scatter_shard(blocks["__mask__"], planes["__mask__"],
                           order[si], Lt)
            for c in num_cols:
                _scatter_shard(blocks[c], planes[c], order[si], Lt)
                _scatter_shard(blocks[c + "/valid"],
                               planes[c + "/valid"], order[si], Lt)
            if kind == "pad":
                return
            # mask row sums ARE the packed per-key lengths
            true_lengths[k0:k1] = \
                planes["__mask__"].sum(axis=1)[: k1 - k0]
            if kind == "restored":
                state["restored"] += 1
            elif resume is not None:
                resume.save_shard(si, planes, n_rows, ledger_crc=ledger)

        sweep_slabs(n_s, load_slab, place_slab, ring=ring)
        return blocks, state["restored"], true_lengths

    passes = 0
    while True:
        q_mark = len(ctx.quarantined)
        blocks, shards_restored, true_lengths = run_shard_pass(
            use_manifests=passes == 0)
        passes += 1
        if len(ctx.quarantined) == q_mark or ctx.on_corrupt != "quarantine":
            break       # raise mode surfaces growth via raise_if_corrupt
        if passes >= 3:
            raise CorruptRowGroupError(
                f"from_parquet: the quarantine kept growing across "
                f"{passes} shard-pass restarts ({len(ctx.quarantined)} "
                f"range(s)) — refusing to return a partially-ingested "
                f"frame", ranges=ctx.quarantined)
        # a range quarantined mid-pass (breaker trip, corruption that
        # only surfaced while streaming shards) leaves EARLIER shards
        # holding its rows while later ones lost them — re-stream every
        # shard under the now-frozen ledger (manifests bypassed: the
        # ones just written contain the quarantined rows)
        logger.warning(
            "from_parquet: %d new range(s) quarantined while streaming "
            "shards; re-streaming every shard under the frozen ledger "
            "for a consistent frame", len(ctx.quarantined) - q_mark)

    ctx.raise_if_corrupt()
    ctx.check("device placement")
    if resume is not None and ctx.quarantined:
        # future resumes must expect the FINAL ledger (shards stamped
        # under an older one are invalidated on load)
        resume.update_quarantine(ctx)
    if shards_restored:
        logger.info(
            "from_parquet: %d/%d shard(s) restored from the progress "
            "manifest at %s (no Parquet re-read)", shards_restored, n_s,
            resume_dir)

    def assemble(name):
        shape = (K_dev, L)
        return jax.make_array_from_single_device_arrays(
            shape, sharding, blocks.pop(name)
        )

    ts_d = assemble("__ts__")
    mask_d = assemble("__mask__")
    cols = {
        c: DistCol(assemble(c), assemble(c + "/valid")) for c in num_cols
    }

    # layout lengths come from what was actually PACKED, not the
    # census: quarantine may have removed rows mid-shard-pass, and a
    # layout that counts vanished rows would lie to every consumer
    layout = packing.FlatLayout(
        key_ids=np.zeros(0, np.int64), ts_ns=np.zeros(0, np.int64),
        order=np.zeros(0, np.int64),
        starts=np.concatenate(
            [[0], np.cumsum(true_lengths)]).astype(np.int64),
        key_frame=key_frame,
    )
    audits = []
    if ctx.quarantined:
        audits.append((
            "ingest: corrupt/unreadable Parquet ranges quarantined "
            "(frame.ingest_quarantined lists them)",
            np.int64(len(ctx.quarantined))))
    frame = DistributedTSDF(
        mesh, series_axis, time_axis, ts_d, mask_d, cols, layout, ts_col,
        pcols, np.dtype("datetime64[ns]"), None, {}, halo_fraction,
        audits=audits,
    )
    frame.ingest_quarantined = tuple(ctx.quarantined)
    # count as one logical pack event for the residency accounting
    from tempo_tpu import dist as dist_mod

    dist_mod._PACK_EVENTS += 1
    return frame


def _stream_shard(ds, read_cols: List[str], batch_rows: int, filt,
                  shard_keys, pcols: List[str],
                  budget_bytes: Optional[int], si: int,
                  ctx: Optional[_IngestCtx] = None) -> pd.DataFrame:
    """Pass 2 unit of work: stream one series shard's row batches into
    a host frame.  Pure read (local ``parts`` rebuilt on every call),
    so the caller can retry it wholesale on transient IO faults."""
    ctx = ctx or _IngestCtx()
    parts = []
    held = 0
    for batch in _iter_batches(ds, read_cols, filt, batch_rows, ctx,
                               stage=f"shard {si} stream"):
        if batch.num_rows == 0:
            continue
        dfb = batch.to_pandas()
        if pcols:
            # exact membership for compound keys
            marked = dfb.merge(
                shard_keys.assign(__in__=True), on=pcols, how="left"
            )
            dfb = dfb[marked["__in__"].fillna(False).to_numpy(bool)]
        if len(dfb) == 0:
            continue
        held += int(dfb.memory_usage(deep=False).sum())
        if budget_bytes is not None and held > budget_bytes:
            raise MemoryError(
                f"series shard {si} exceeded the host ingest budget "
                f"({held} > {budget_bytes} bytes)"
            )
        parts.append(dfb)
    return (
        pd.concat(parts, ignore_index=True)
        if parts else pd.DataFrame(columns=read_cols)
    )


def _scatter_shard(sink: List, host_block: np.ndarray, dev_row, Lt: int):
    """Split one series-shard host block along time and place each
    piece on its device; appends in mesh device order.  ``device_put``
    dispatches the H2D copy asynchronously, so placement of shard N
    overlaps the producer thread's decode of shard N+1 under
    :func:`sweep_slabs`."""
    for ti, dev in enumerate(dev_row):
        sink.append(
            jax.device_put(host_block[:, ti * Lt:(ti + 1) * Lt], dev)
        )


# ----------------------------------------------------------------------
# Slab pipelining: the bounded-ring three-stage sweep
# ----------------------------------------------------------------------

def sweep_slabs(n_slabs: int, load, compute, drain=None,
                ring: Optional[int] = None) -> List:
    """Run ``drain(i, compute(i, load(i)))`` for every slab, pipelined
    behind a bounded ring of slab buffers.

    ``load`` (decode/ingest, CPU- or IO-bound) runs on a producer
    thread one slab AHEAD of the main thread; ``drain`` (D2H fetch,
    digesting, spill) runs on a collector thread one slab BEHIND; the
    main thread runs ``compute`` (device dispatch / placement) on every
    slab strictly IN ORDER.  Slab N+1's load and slab N-1's drain
    overlap slab N's compute, so steady-state wall time approaches
    ``max(load, compute, drain)`` per slab instead of their sum.

    Bitwise contract: the main thread consumes load results in slab
    order and the collector drains compute results in slab order —
    exactly the serial loop's data flow — so the pipelined sweep is
    bit-identical to ``ring=1`` (the serial loop) by construction.

    ``ring`` is the slab-buffer ring depth (default
    ``TEMPO_TPU_INGEST_RING``): at most ``ring - 1`` loaded slabs
    queue ahead of compute and ``ring - 1`` computed slabs queue ahead
    of drain; ``ring <= 1`` (or a single slab) runs fully serially.
    The first failure from any stage re-raises in the caller with the
    pipeline cleanly drained (threads joined, no orphan slabs).
    Returns the per-slab results in slab order.
    """
    from tempo_tpu import config, tune

    if ring is None:
        # env knob wins, then the tuned profile's winner (tune/space.py
        # ``ingest_sweep`` class), then the built-in 2
        ring = config.get_int("TEMPO_TPU_INGEST_RING")
        if ring is None:
            ring = tune.knob_value("TEMPO_TPU_INGEST_RING",
                                   "ingest_sweep") or 2
    ring = max(1, int(ring))
    n = int(n_slabs)
    if ring <= 1 or n <= 1:
        out = []
        for i in range(n):
            y = compute(i, load(i))
            out.append(y if drain is None else drain(i, y))
        return out

    import queue as queue_mod
    import threading

    depth = ring - 1
    loaded: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
    to_drain: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
    stop = threading.Event()
    results: List = [None] * n
    fail: List[BaseException] = []    # first failure wins

    def _offer(q, item) -> bool:
        """Bounded put that never deadlocks a dying pipeline."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue_mod.Full:
                continue
        return False

    def producer():
        try:
            for i in range(n):
                if stop.is_set():
                    return
                x = load(i)
                if not _offer(loaded, (i, x)):
                    return
        # fail is appended from the producer, the collector, AND the
        # host body: list.append is atomic under the GIL, the list is
        # only append-only while threads run, and the host reads it
        # after join() (first failure wins) — a lock would add nothing
        except BaseException as e:            # noqa: BLE001
            fail.append(e)  # lint-ok: guarded-attr: GIL-atomic append-only list, read after join
            stop.set()

    def collector():
        try:
            while True:
                try:
                    item = to_drain.get(timeout=0.05)
                except queue_mod.Empty:
                    if stop.is_set():
                        return
                    continue
                if item is None:
                    return
                i, y = item
                results[i] = y if drain is None else drain(i, y)
        except BaseException as e:            # noqa: BLE001
            fail.append(e)
            stop.set()

    tp = threading.Thread(target=producer, name="slab-load", daemon=True)
    tc = threading.Thread(target=collector, name="slab-drain", daemon=True)
    tp.start()
    tc.start()
    try:
        for i in range(n):
            while True:
                try:
                    j, x = loaded.get(timeout=0.05)
                    break
                except queue_mod.Empty:
                    if stop.is_set():
                        raise fail[0] if fail else RuntimeError(
                            "slab pipeline stopped without a recorded "
                            "failure")
            assert j == i, "slab pipeline delivered out of order"
            y = compute(i, x)
            if not _offer(to_drain, (i, y)):
                break
        _offer(to_drain, None)
    except BaseException as e:                # noqa: BLE001
        if not fail:
            fail.append(e)
        stop.set()
    tp.join()
    tc.join()
    if fail:
        raise fail[0]
    return results


# ----------------------------------------------------------------------
# Transactional resume: per-shard progress manifests
# ----------------------------------------------------------------------

def _dataset_file_state(path: str) -> tuple:
    """(relpath, size, mtime_ns) of every data file under ``path`` —
    the cheap content fingerprint of the SOURCE.  Committed shard
    manifests hold packed rows of the dataset *as it was*; if the
    upstream writer rewrites a file between the kill and the resume,
    restoring them would silently stitch old and new data together —
    the same stale-restore hazard the plan barriers fingerprint their
    sources against."""
    if not os.path.isdir(path):
        st = os.stat(path)
        return ((os.path.basename(path), st.st_size, st.st_mtime_ns),)
    out = []
    for root, _dirs, files in os.walk(path):
        for f in files:
            if f.startswith(("_", ".")):
                continue
            fp = os.path.join(root, f)
            st = os.stat(fp)
            out.append((os.path.relpath(fp, path), st.st_size,
                        st.st_mtime_ns))
    return tuple(sorted(out))


def _resume_signature(path, ts_col, pcols, columns, mesh, series_axis,
                      time_axis) -> str:
    """Identity of one ingest configuration INCLUDING the dataset's
    file-level state (:func:`_dataset_file_state`).  A progress
    manifest stamped by a different (dataset content, schema, mesh)
    combination must be refused — resuming it would stitch foreign or
    stale packed blocks into this frame."""
    mesh_state = (tuple(mesh.axis_names), tuple(sorted(mesh.shape.items())))
    h = hashlib.sha1(repr((
        _RESUME_FORMAT, os.path.abspath(path), ts_col, tuple(pcols),
        tuple(columns or ()), mesh_state, series_axis, time_axis,
        _dataset_file_state(path),
    )).encode())
    return h.hexdigest()[:16]


def _array_crc(arr: np.ndarray) -> int:
    from tempo_tpu import checkpoint

    return checkpoint.array_crc(arr)


def _plane_key(name: str) -> str:
    # npz member names cannot hold '/', the valid-plane separator
    return name.replace("/", "__")


class _ResumeLog:
    """Per-shard progress manifest of one out-of-core ingest.

    Layout under ``resume_dir``: ``ingest.json`` (the stamped ingest
    signature), ``census.npz`` + ``keys.parquet`` + ``census.json``
    (the pass-1 key census, CRC'd, including the quarantine ledger so
    pass 2 of a resumed run skips exactly what pass 1 skipped), and
    per shard ``shard_NNNN.npz`` + ``shard_NNNN.json`` (the packed
    host blocks with per-array CRCs).  Every artifact is written
    ``.tmp``-then-rename, and the sidecar JSON is written LAST — its
    presence is the commit record, so a kill mid-write can never leave
    a shard that looks complete.  Corrupt artifacts are detected by
    CRC on load and silently re-streamed (the Parquet source is the
    recovery); only a *foreign signature* refuses by name."""

    def __init__(self, resume_dir: str, signature: str):
        self.dir = str(resume_dir)
        self.signature = signature

    # -- paths ----------------------------------------------------------

    def _p(self, name: str) -> str:
        return os.path.join(self.dir, name)

    @staticmethod
    def _write_json(path: str, doc: dict) -> None:
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    # -- signature ------------------------------------------------------

    def open(self, ctx: _IngestCtx) -> None:
        os.makedirs(self.dir, exist_ok=True)
        ip = self._p("ingest.json")
        if os.path.exists(ip):
            try:
                with open(ip) as f:
                    doc = json.load(f)
            except (json.JSONDecodeError, UnicodeDecodeError):
                doc = {}
            stamped = doc.get("signature")
            if stamped != self.signature:
                raise CheckpointError(
                    f"ingest resume directory {self.dir!r} was written "
                    f"by a DIFFERENT ingest (stamped signature "
                    f"{stamped!r} != this call's {self.signature!r}: "
                    f"other dataset path, changed source files, other "
                    f"schema, columns or mesh) — refusing to stitch "
                    f"foreign/stale shards; point resume_dir elsewhere "
                    f"or clear it",
                    kind=FailureKind.PERMANENT,
                )
        else:
            self._write_json(ip, {"signature": self.signature,
                                  "format": _RESUME_FORMAT})

    # -- census ---------------------------------------------------------

    def save_census(self, key_frame: pd.DataFrame, lengths: np.ndarray,
                    ctx: _IngestCtx) -> None:
        tmp = self._p("census.npz.tmp.npz")
        np.savez(tmp, lengths=lengths)
        os.replace(tmp, self._p("census.npz"))
        key_frame.to_parquet(self._p("keys.parquet.tmp"))
        os.replace(self._p("keys.parquet.tmp"), self._p("keys.parquet"))
        from tempo_tpu import checkpoint

        self._write_json(self._p("census.json"), {
            "signature": self.signature,
            "lengths_crc": _array_crc(lengths),
            "keys_crc": checkpoint.file_crc(self._p("keys.parquet")),
            "quarantined": list(ctx.quarantined),
        })

    def load_census(self):
        cp = self._p("census.json")
        if not os.path.exists(cp):
            return None
        try:
            with open(cp) as f:
                doc = json.load(f)
            lengths = np.load(self._p("census.npz"),
                              allow_pickle=False)["lengths"]
            key_frame = pd.read_parquet(self._p("keys.parquet"))
            from tempo_tpu import checkpoint

            if _array_crc(lengths) != int(doc["lengths_crc"]) or \
                    checkpoint.file_crc(self._p("keys.parquet")) \
                    != int(doc["keys_crc"]):
                raise ValueError("census CRC mismatch")
        except (OSError, ValueError, KeyError, zipfile.BadZipFile,
                EOFError, json.JSONDecodeError) as e:
            logger.warning(
                "from_parquet: cached census at %s unusable (%s); "
                "re-running the census pass", self.dir, e)
            return None
        return key_frame, lengths

    def update_quarantine(self, ctx: _IngestCtx) -> None:
        """Re-persist the quarantine ledger after it grew during the
        shard pass, so a later resume expects the FINAL ledger and
        invalidates shard manifests stamped under older ones."""
        cp = self._p("census.json")
        if not os.path.exists(cp):
            return
        try:
            with open(cp) as f:
                doc = json.load(f)
        except (OSError, ValueError, json.JSONDecodeError):
            return
        doc["quarantined"] = list(ctx.quarantined)
        self._write_json(cp, doc)

    def census_quarantine(self) -> List[dict]:
        cp = self._p("census.json")
        if not os.path.exists(cp):
            return []
        try:
            with open(cp) as f:
                return list(json.load(f).get("quarantined") or [])
        except (OSError, ValueError, json.JSONDecodeError):
            return []

    # -- shards ---------------------------------------------------------

    def save_shard(self, si: int, planes: Dict[str, np.ndarray],
                   rows: int, ledger_crc: int = 0) -> None:
        """Persist one completed shard's packed host blocks; the JSON
        sidecar (written last) commits it, stamped with the quarantine
        ledger the shard was packed under."""
        npz = self._p(f"shard_{si:04d}.npz")
        tmp = npz + ".tmp.npz"
        np.savez(tmp, **{_plane_key(k): v for k, v in planes.items()})
        os.replace(tmp, npz)
        self._write_json(self._p(f"shard_{si:04d}.json"), {
            "si": si, "rows": rows, "ledger_crc": int(ledger_crc),
            "crcs": {_plane_key(k): _array_crc(v)
                     for k, v in planes.items()},
        })

    def load_shard(self, si: int, num_cols: List[str], shape,
                   ledger_crc: int = 0
                   ) -> Optional[Dict[str, np.ndarray]]:
        """Packed host blocks of a committed shard, CRC-verified; None
        (re-stream from Parquet) when absent, corrupt, shaped for a
        different layout, or stamped with a DIFFERENT quarantine
        ledger than the current run's (a kill during a consistency
        re-stream leaves manifests packed under mixed ledgers — the
        stale ones must not be stitched in)."""
        jp = self._p(f"shard_{si:04d}.json")
        if not os.path.exists(jp):
            return None
        wanted = ["__ts__", "__mask__"] + [n for c in num_cols
                                           for n in (c, c + "/valid")]
        try:
            with open(jp) as f:
                doc = json.load(f)
            crcs = doc["crcs"]
            if int(doc.get("ledger_crc", 0)) != int(ledger_crc):
                raise ValueError(
                    "packed under a different quarantine ledger")
            with np.load(self._p(f"shard_{si:04d}.npz"),
                         allow_pickle=False) as z:
                planes = {}
                for name in wanted:
                    arr = z[_plane_key(name)]
                    if _array_crc(arr) != int(crcs[_plane_key(name)]) \
                            or tuple(arr.shape) != tuple(shape):
                        raise ValueError(
                            f"plane {name!r} CRC/shape mismatch")
                    planes[name] = arr
        except (OSError, ValueError, KeyError, zipfile.BadZipFile,
                EOFError, json.JSONDecodeError) as e:
            logger.warning(
                "from_parquet: shard %d progress manifest unusable "
                "(%s); re-streaming it from Parquet", si, e)
            return None
        return planes
