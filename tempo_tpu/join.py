"""Frame-level AS-OF join: packing, prefixing, skew bucketing, assembly.

Reference behaviour being reproduced (python/tempo/tsdf.py:463-560):

* column prefixing of non-partition columns on both sides (tsdf.py:77-94,
  529-531), ``right_prefix`` defaulting to ``"right"``;
* ``skipNulls`` / sequence-number tie-break / suppress_null_warning
  semantics via the kernels in ``tempo_tpu.ops.asof``;
* the skew variant (``tsPartitionVal``/``fraction``): overlapping
  time-bucket partitions (tsdf.py:164-190) - here realised by composing
  the partition key with a time-bracket id and replicating the trailing
  ``fraction`` of each right bracket into the next one, which bounds the
  padded series length (the packed-layout analog of Spark skew
  mitigation) and doubles as the halo pattern used for time-sharded
  series (SURVEY.md section 2.3);
* the ``sql_join_opt`` broadcast fast path (tsdf.py:482-509): taken when
  either side's estimated in-memory size is under 30MiB; its observable
  difference - it is an *inner* range join, so left rows with no
  preceding right row are dropped - is preserved;
* per-column missing-lookback warnings for the skew path
  (tsdf.py:150-159);
* Scala's ``maxLookback`` row cap on the merged stream
  (scala/.../asofJoin.scala:64-88), exposed as a keyword.
"""

from __future__ import annotations

import logging
from typing import List, Optional

import numpy as np
import pandas as pd

from tempo_tpu import packing, profiling, resilience
from tempo_tpu.ops import asof as asof_ops

logger = logging.getLogger(__name__)


def _estimate_merged_lanes(l_codes: np.ndarray, r_codes: np.ndarray,
                           n_series: int) -> int:
    """Padded merged-lane count the AS-OF kernels would materialise for
    the dense layout — the quantity whose measured ceiling (~205K lanes,
    BASELINE.md r3) OOM-kills the XLA compiler.  Host-side and O(n):
    runs before any packing so oversize joins can be rerouted."""
    max_l = int(np.bincount(l_codes, minlength=max(n_series, 1)).max(initial=0))
    max_r = int(np.bincount(r_codes, minlength=max(n_series, 1)).max(initial=0))
    return packing.pad_length(max_l) + packing.pad_length(max_r)


def _auto_bracket(l_codes, l_ts_ns, r_codes, r_ts_ns, r_seq_vals,
                  n_series, est_lanes, limit, valid_masks):
    """Exact host time-bracketing for oversize joins.

    Splits every series into (key, time-bracket) joint series — the
    same composition the explicit ``tsPartitionVal`` skew machinery
    uses — but instead of replicating a trailing *fraction* of each
    bracket (lossy beyond the lookback), it carries into each bracket
    the per-column last-non-null right row and the last right row
    overall from before the bracket's start.  Each joint series is then
    self-contained, so the bracketed join is **bit-identical** to the
    unbracketed one: the VERDICT "cannot execute at all" regime becomes
    slow-but-correct.

    ``valid_masks`` is ``[C, n_right]`` bool (per right column
    non-null), empty first axis for ``skipNulls=False`` where only the
    last-row channel is consumed.

    Returns ``(l_brackets, r_take, r_bracket_all, n_brackets, width_ns)``
    or ``None`` when the data cannot be split (zero time span)."""
    lo = min(int(l_ts_ns.min()), int(r_ts_ns.min()))
    hi = max(int(l_ts_ns.max()), int(r_ts_ns.max()))
    span = hi - lo + 1
    # enough brackets that a bracket's share of the dominant series sits
    # well under the limit (assuming rough uniformity in time; heavy
    # temporal skew degrades the bound, never correctness)
    n_brackets = int(-(-2 * est_lanes // max(limit, 1)))
    n_brackets = max(2, min(n_brackets, 1 << 16))
    width_ns = max(1, -(-span // n_brackets))
    if span <= 1:
        return None

    l_b = (l_ts_ns - lo) // width_ns
    r_b = (r_ts_ns - lo) // width_ns

    # right side in layout order (series-major, ts/seq-sorted) so
    # "last row before a boundary" is a searchsorted + prefix scan
    r_layout0 = packing.build_layout_from_codes(
        r_codes, r_ts_ns, r_seq_vals, n_series)
    rs_ts = r_layout0.ts_ns
    starts = r_layout0.starts
    n_r = len(r_codes)
    idx = np.arange(n_r, dtype=np.int64)
    last_valid = [
        np.maximum.accumulate(np.where(valid_masks[c][r_layout0.order],
                                       idx, -1))
        for c in range(valid_masks.shape[0])
    ] if n_r else []

    pairs = np.unique(
        np.stack([l_codes, l_b], axis=1), axis=0) if len(l_codes) else \
        np.zeros((0, 2), np.int64)
    carry_rows: List[int] = []
    carry_brackets: List[int] = []
    for k, b in pairs:
        s0, s1 = int(starts[k]), int(starts[k + 1])
        if s1 <= s0:
            continue
        boundary = lo + int(b) * width_ns
        p = s0 + int(np.searchsorted(rs_ts[s0:s1], boundary, side="left"))
        if p <= s0:
            continue
        carry = {p - 1}
        for lv in last_valid:
            j = int(lv[p - 1])
            if j >= s0:
                carry.add(j)
        for j in carry:
            carry_rows.append(j)
            carry_brackets.append(int(b))

    carried = np.asarray(carry_rows, dtype=np.int64)
    r_take = np.concatenate(
        [np.arange(n_r, dtype=np.int64), r_layout0.order[carried]])
    r_bracket_all = np.concatenate(
        [r_b, np.asarray(carry_brackets, dtype=np.int64)])
    return l_b, r_take, r_bracket_all, n_brackets, width_ns


def _prefixed(cols: List[str], prefix: Optional[str]) -> dict:
    if prefix is None or prefix == "":
        return {c: c for c in cols}
    return {c: f"{prefix}_{c}" for c in cols}


def _gather(values: np.ndarray, idx: np.ndarray, ok: np.ndarray):
    """Host gather with Spark-null semantics for any dtype."""
    if values.shape[0] == 0:
        # no right rows at all: every output is null
        ok = np.zeros(idx.shape, dtype=bool)
        values = np.empty(1, dtype=values.dtype)
    safe = np.where(ok, idx, 0)
    taken = values[safe]
    if values.dtype == object:
        out = taken.astype(object)
        out[~ok] = None
        return out
    if np.issubdtype(values.dtype, np.datetime64):
        out = taken.astype("datetime64[ns]")
        out[~ok] = np.datetime64("NaT")
        return out
    if np.issubdtype(values.dtype, np.floating):
        out = taken.astype(values.dtype)
        out[~ok] = np.nan
        return out
    if np.issubdtype(values.dtype, np.bool_):
        if ok.all():
            return taken
        out = pd.array(taken, dtype="boolean")
        out[~ok] = pd.NA
        return out
    # integers: keep exact dtype when fully matched, else nullable Int64
    if ok.all():
        return taken
    out = pd.array(taken.astype(np.int64), dtype="Int64")
    out[~ok] = pd.NA
    return out


def _binpack_worthwhile(l_layout, r_layout) -> bool:
    """Engage the bin-packed layout when one-series-per-row padding
    would waste most of the slot grid (Zipf-skewed key distributions).
    TEMPO_TPU_BINPACK=1/0 forces/forbids."""
    from tempo_tpu import config

    K = l_layout.n_series
    Ll = int(l_layout.lengths.max(initial=0))
    Lr = int(r_layout.lengths.max(initial=0))
    # the kernel's position payloads are exact in f32 up to 2^24 lanes:
    # a longer single series keeps the dense layout's exact int32
    # channels (this bound also caps SID_PAD collisions: series ids
    # stay far below 2^31)
    if max(Ll, Lr) >= (1 << 24) - 128:
        return False
    env = config.get("TEMPO_TPU_BINPACK")
    if env is not None:
        return env not in ("0", "false", "no")
    slots = K * (Ll + Lr)
    if slots == 0:
        return False
    return (l_layout.n_rows + r_layout.n_rows) / slots < 0.35


def _binpacked_indices(right, l_layout, r_layout, r_sorted_take,
                       valid_cols, max_lookback: int = 0,
                       r_seq_sorted=None, engine: str = "single",
                       interpret: bool = False):
    """Join indices through the bin-packed segmented kernel: short
    series share lane rows (packing.bin_pack_series), one program for
    any skew shape.  ``valid_cols`` empty = skipNulls=False (only the
    last-row channel is consumed).  ``max_lookback`` rides the
    sid-fenced windowed ladder (sortmerge._asof_merge_explicit) or the
    chunked streaming kernel.  ``r_seq_sorted`` (layout-ordered right
    sequence values) engages the tie-break — the layouts were sorted
    (ts, seq) per series so the segmented merge precondition holds
    (round-6 lift of the seq x bin-pack exclusion).  ``engine``:
    'chunked' runs the lane-chunked streaming VMEM kernel (oversize
    lane-row widths past the single-plan merge)."""
    import jax.numpy as jnp

    from tempo_tpu.ops import pallas_merge as pm
    from tempo_tpu.ops import sortmerge as sm

    Wl = packing.pad_length(
        max(int(l_layout.lengths.max(initial=0)), 1), 128)
    Wr = packing.pad_length(
        max(int(r_layout.lengths.max(initial=0)), 1), 128)
    bp = packing.bin_pack_series(
        l_layout.lengths, r_layout.lengths, Wl, Wr)
    K2 = packing.pad_length(bp.n_rows)
    # destination slots computed once, reused for every plane
    dest_l = packing.binpack_dest(l_layout.starts, bp.row, bp.l_off, Wl)
    dest_r = packing.binpack_dest(r_layout.starts, bp.row, bp.r_off, Wr)
    lt = packing.binpack_scatter(
        l_layout.ts_ns, dest_l, K2, Wl, packing.TS_PAD)
    rt = packing.binpack_scatter(
        r_layout.ts_ns, dest_r, K2, Wr, packing.TS_PAD)
    lsid = packing.binpack_scatter(
        l_layout.key_ids.astype(np.int32), dest_l, K2, Wl,
        packing.SID_PAD)
    rsid = packing.binpack_scatter(
        r_layout.key_ids.astype(np.int32), dest_r, K2, Wr,
        packing.SID_PAD)
    rv = np.stack([
        packing.binpack_scatter(
            (~pd.isna(right.df[c])).to_numpy()[r_sorted_take],
            dest_r, K2, Wr, False)
        for c in valid_cols
    ]) if valid_cols else np.zeros((0, K2, Wr), bool)
    rsq = (packing.binpack_scatter(r_seq_sorted, dest_r, K2, Wr, np.inf)
           if r_seq_sorted is not None else None)

    if engine == "chunked":
        last_idx, per_col = pm.asof_merge_indices_chunked(
            lt, rt, rv, lsid, rsid, r_seq=rsq,
            max_lookback=int(max_lookback), interpret=interpret)
    else:
        last_idx, per_col = sm.asof_indices_binpacked(
            jnp.asarray(lt), jnp.asarray(rt), jnp.asarray(rv),
            jnp.asarray(lsid), jnp.asarray(rsid),
            max_lookback=int(max_lookback),
            r_seq=jnp.asarray(rsq) if rsq is not None else None)
    return np.asarray(last_idx), np.asarray(per_col), bp


def _joint_bracket_codes(l_codes, r_codes_taken, l_brackets, r_brackets):
    """Compose (key, time-bracket) joint series ids — shared by the
    explicit ``tsPartitionVal`` skew path and the oversize auto-bracket
    fallback so the encoding can never diverge between them.

    Returns ``(l_codes_j, r_codes_j, n_series)``."""
    all_codes = np.concatenate([l_codes, r_codes_taken])
    all_brackets = np.concatenate([l_brackets, r_brackets])
    joint = all_codes * np.int64(2 ** 31) + pd.factorize(all_brackets)[0]
    joint_codes, _ = pd.factorize(joint)
    n_series = int(joint_codes.max()) + 1
    nl = len(l_brackets)
    return (joint_codes[:nl].astype(np.int64),
            joint_codes[nl:].astype(np.int64), n_series)


def _time_brackets(ts_ns: np.ndarray, ts_partition_val: float):
    """Bracket id + remainder fraction, double-seconds math mirroring
    tsdf.py:176-180 (cast to double, truncate toward zero)."""
    ts_sec = ts_ns / packing.NS_PER_S
    bracket = ts_partition_val * (ts_sec / ts_partition_val).astype(np.int64)
    remainder = (ts_sec - bracket) / ts_partition_val
    return bracket, remainder


def asof_join(
    left,
    right,
    left_prefix: Optional[str] = None,
    right_prefix: str = "right",
    tsPartitionVal: Optional[float] = None,
    fraction: float = 0.5,
    skipNulls: bool = True,
    sql_join_opt: bool = False,
    suppress_null_warning: bool = False,
    maxLookback: int = 0,
):
    from tempo_tpu.frame import TSDF

    strategy = profiling.pick_asof_strategy(
        left.df, right.df, sql_join_opt,
        has_sequence=bool(right.sequence_col),
        max_lookback=int(maxLookback or 0),
    )
    broadcast_path = strategy == "broadcast"

    if tsPartitionVal is not None:
        if not skipNulls:
            raise ValueError(
                "Disabling null skipping with a partition value is not supported yet."
            )
        logger.warning(
            "You are using the skew version of the AS OF join. This may result in "
            "null values if there are any values outside of the maximum lookback. "
            "For maximum efficiency, choose smaller values of maximum lookback, "
            "trading off performance and potential blank AS OF values for sparse keys"
        )

    left._check_partition_cols_match(right)
    left._validate_ts_col_match(right)

    pcols = left.partitionCols

    left_value_cols = [c for c in left.df.columns if c not in pcols]
    right_value_cols = [c for c in right.df.columns if c not in pcols]
    lmap = _prefixed(left_value_cols, left_prefix)
    rmap = _prefixed(right_value_cols, right_prefix)

    _valid_cache: dict = {}

    def _right_valid(c: str) -> np.ndarray:
        """Right column non-null mask in original row order, computed
        once per column (shared by the oversize-bracket carries and the
        packed validity planes)."""
        if c not in _valid_cache:
            _valid_cache[c] = (~pd.isna(right.df[c])).to_numpy()
        return _valid_cache[c]

    # --- joint key encoding over the union of both sides' keys ---------
    l_codes, r_codes, key_frame = packing.encode_keys_joint(left.df, right.df, pcols)
    l_ts_ns = packing.series_to_ns(left.df[left.ts_col])
    r_ts_ns = packing.series_to_ns(right.df[right.ts_col])

    r_seq_vals = (
        pd.to_numeric(right.df[right.sequence_col]).to_numpy(dtype=np.float64)
        if right.sequence_col
        else None
    )
    if r_seq_vals is not None:
        # Spark orders the merged stream by (ts, seq ASC NULLS FIRST,
        # rec_ind) — tsdf.py:117-121: a null-seq right row sorts before
        # tied-ts left rows (visible to them) and loses the tie to
        # non-null-seq right rows.  -inf realises NULLS FIRST in the
        # float total order both for the layout sort and the merge key.
        r_seq_vals = np.where(np.isnan(r_seq_vals), -np.inf, r_seq_vals)

    # --- skew variant: compose key with overlapping time brackets ------
    l_take = np.arange(len(left.df), dtype=np.int64)
    r_take = np.arange(len(right.df), dtype=np.int64)
    if broadcast_path:
        # the reference's sql_join_opt fast path returns before any skew
        # handling (tsdf.py:492-509) — the broadcast join never buckets
        tsPartitionVal = None
    if tsPartitionVal is not None:
        l_bracket, _ = _time_brackets(l_ts_ns, tsPartitionVal)
        r_bracket, r_rem = _time_brackets(r_ts_ns, tsPartitionVal)
        # replicate the trailing `fraction` of each right bracket forward
        spill = r_rem >= (1.0 - fraction)
        r_take = np.concatenate([r_take, r_take[spill]])
        r_bracket = np.concatenate(
            [r_bracket, r_bracket[spill] + tsPartitionVal]
        )
        # re-encode keys as (key, bracket)
        l_codes_j, r_codes_j, n_series = _joint_bracket_codes(
            l_codes, r_codes[r_take], l_bracket, r_bracket)
        r_ts_j = r_ts_ns[r_take]
        r_seq_j = r_seq_vals[r_take] if r_seq_vals is not None else None
    else:
        n_series = len(key_frame)
        l_codes_j, r_codes_j = l_codes, r_codes
        r_ts_j = r_ts_ns
        r_seq_j = r_seq_vals

    # --- oversize engine pick: single-plan -> chunked -> brackets -----
    # Past the merge-plan limit one device program cannot run: the XLA
    # sort ladder OOM-kills the compiler at ~205K merged lanes (VERDICT
    # missing #1).  Since round 6 the default oversize engine is the
    # lane-chunked streaming VMEM merge (ops/pallas_merge.py) — on-chip
    # at any length under 2^24 merged rows, every flag combination
    # including maxLookback.  Host time-bracketing remains the last
    # resort (non-TPU backends, >= 2^24 rows), selectable explicitly
    # with TEMPO_TPU_JOIN_ENGINE=bracket.
    auto_bracketed = False
    join_engine = "single"
    if tsPartitionVal is None and not broadcast_path \
            and len(left.df) and len(right.df):
        from tempo_tpu.ops import pallas_merge as pm

        limit = resilience.max_merged_lanes()
        est = _estimate_merged_lanes(l_codes, r_codes, n_series)
        # the availability probe scans the seq column (seq_kernel_form)
        # — only pay it when the engine decision actually needs it
        # (oversize, or an explicit TEMPO_TPU_JOIN_ENGINE override)
        if 0 < limit < est or profiling.join_engine_override():
            chunked_ok = pm.chunked_join_available(
                est, len(right_value_cols), r_seq_vals,
                skip_nulls=skipNulls, max_lookback=int(maxLookback or 0))
            join_engine = profiling.pick_join_engine(est, limit,
                                                    chunked_ok)
        if join_engine == "chunked" and 0 < limit < est:
            logger.info(
                "asofJoin: estimated %d merged lanes exceeds the "
                "single-program limit %d; using the lane-chunked "
                "streaming merge engine", est, limit,
            )
        if join_engine == "bracket":
            if maxLookback and int(maxLookback) > 0:
                logger.warning(
                    "asofJoin: bracket engine selected (estimated %d "
                    "merged lanes, limit %d), but maxLookback counts "
                    "rows of the full merged stream and cannot ride "
                    "the bracketing fallback — attempting the "
                    "full-size merge (may exhaust compiler memory)",
                    est, limit,
                )
                join_engine = "single"
            else:
                carry_cols = right_value_cols if skipNulls else []
                masks = np.stack([
                    _right_valid(c) for c in carry_cols
                ]) if carry_cols else np.zeros((0, len(right.df)), bool)
                plan = _auto_bracket(
                    l_codes, l_ts_ns, r_codes, r_ts_ns, r_seq_vals,
                    n_series, est, limit, masks,
                )
                if plan is not None:
                    l_b, r_take, r_bracket_all, n_brackets, width_ns = plan
                    l_codes_j, r_codes_j, n_series = _joint_bracket_codes(
                        l_codes, r_codes[r_take], l_b, r_bracket_all)
                    r_ts_j = r_ts_ns[r_take]
                    r_seq_j = (r_seq_vals[r_take]
                               if r_seq_vals is not None else None)
                    auto_bracketed = True
                    logger.warning(
                        "asofJoin: estimated %d merged lanes vs the "
                        "merge-plan limit %d; %s the host "
                        "time-bracketing path (%d brackets, width %.0fs, "
                        "%d carried rows). Results are exact but "
                        "execution is slower — deferred audit: oversize "
                        "AS-OF join rerouted instead of compiler OOM.",
                        est, limit,
                        ("degrading to" if est > limit
                         else "TEMPO_TPU_JOIN_ENGINE forced"),
                        n_brackets,
                        width_ns / packing.NS_PER_S,
                        len(r_take) - len(right.df),
                    )

    l_layout = packing.build_layout_from_codes(l_codes_j, l_ts_ns, None, n_series)
    r_layout = packing.build_layout_from_codes(r_codes_j, r_ts_j, r_seq_j, n_series)

    r_sorted_take = r_take[r_layout.order]

    # --- layout strategy: bin-pack Zipf-skewed key distributions ------
    # One-series-per-row padding pays for the LONGEST series at every
    # key (a real NBBO day is ~96% padding); when slot occupancy is low
    # the series bin-pack into shared lane rows and the segmented merge
    # kernel joins them independently (the packed-layout answer to the
    # reference's tsPartitionVal skew machinery, tsdf.py:164-190 —
    # which remains available explicitly).  Skew brackets and the
    # broadcast path keep the dense layout; a sequence tie-break rides
    # the bin-packed layout too since round 6 (the layouts sort
    # (ts, seq) per series when a seq plane is present, so the
    # segmented merge precondition holds); maxLookback rides the
    # sid-fenced windowed ladder (round 4) or the chunked streaming
    # kernel (round 6).
    import jax as _jax

    interp_chunked = _jax.default_backend() != "tpu"
    use_binpack = (
        not broadcast_path
        and tsPartitionVal is None
        and n_series > 1
        and _binpack_worthwhile(l_layout, r_layout)
    )
    if use_binpack:
        last_row_idx, per_col_idx, bp = _binpacked_indices(
            right, l_layout, r_layout, r_sorted_take,
            right_value_cols if skipNulls else [],
            max_lookback=int(maxLookback or 0),
            r_seq_sorted=(r_seq_j[r_layout.order]
                          if r_seq_j is not None else None),
            engine=join_engine, interpret=interp_chunked,
        )
        keep_mask_packed = None
    else:
        bp = None

    Ll = packing.pad_length(int(l_layout.lengths.max(initial=0)))
    Lr = packing.pad_length(int(r_layout.lengths.max(initial=0)))
    if not use_binpack:
        l_ts_p = packing.pack_column(
            l_layout.ts_ns, l_layout, Ll, fill=packing.TS_PAD)
        r_ts_p = packing.pack_column(
            r_layout.ts_ns, r_layout, Lr, fill=packing.TS_PAD)

        # validity masks per right column (order: right_value_cols)
        r_valid_packed = []
        for c in right_value_cols:
            valid = _right_valid(c)[r_sorted_take]
            r_valid_packed.append(
                packing.pack_column(valid, r_layout, Lr, fill=False)
            )
        r_valids = np.stack(r_valid_packed) if r_valid_packed else \
            np.zeros((0, n_series, Lr), bool)

    # --- kernel dispatch ----------------------------------------------
    use_merge = strategy == "merge"
    r_seq_packed = (
        packing.pack_column(
            r_seq_j[r_layout.order], r_layout, Lr, fill=np.inf
        )
        if r_seq_j is not None and not use_binpack and not broadcast_path
        else None
    )
    if use_binpack:
        pass
    elif broadcast_path:
        idx, matched = asof_ops.asof_indices_inner(l_ts_p, r_ts_p)
        last_row_idx = np.asarray(idx)
        per_col_idx = None  # broadcast path is row-level, nulls included
        keep_mask_packed = np.asarray(matched)
    elif join_engine == "chunked":
        from tempo_tpu.ops import pallas_merge as pm

        last_row_idx, per_col_idx = pm.asof_merge_indices_chunked(
            l_ts_p, r_ts_p, r_valids, r_seq=r_seq_packed,
            max_lookback=int(maxLookback or 0),
            interpret=interp_chunked,
        )
        last_row_idx = np.asarray(last_row_idx)
        per_col_idx = np.asarray(per_col_idx)
        keep_mask_packed = None
    elif use_merge:
        last_row_idx, per_col_idx = asof_ops.asof_indices_merge(
            l_ts_p, None, r_ts_p, r_seq_packed, r_valids,
            n_cols=len(right_value_cols), max_lookback=int(maxLookback),
        )
        last_row_idx = np.asarray(last_row_idx)
        per_col_idx = np.asarray(per_col_idx)
        keep_mask_packed = None
    else:
        last_row_idx, per_col_idx = asof_ops.asof_indices_searchsorted(
            l_ts_p, r_ts_p, r_valids, n_cols=len(right_value_cols)
        )
        last_row_idx = np.asarray(last_row_idx)
        per_col_idx = np.asarray(per_col_idx)
        keep_mask_packed = None

    # --- flatten back to left row coordinates --------------------------
    pos = np.arange(l_layout.n_rows) - l_layout.starts[l_layout.key_ids]
    k_ids = l_layout.key_ids

    if use_binpack:
        def flat_right_indices(packed_idx):
            # bin-packed planes are indexed by (lane row, lane offset);
            # returned positions are within-lane-row -> subtract the
            # series' right-side offset for the per-series index
            ridx = packed_idx[bp.row[k_ids], bp.l_off[k_ids] + pos]
            ok = ridx >= 0
            within = np.where(ok, ridx - bp.r_off[k_ids], 0)
            return r_layout.starts[k_ids] + within, ok
    else:
        def flat_right_indices(packed_idx):
            ridx = packed_idx[k_ids, pos]
            ok = ridx >= 0
            flat = r_layout.starts[k_ids] + np.where(ok, ridx, 0)
            return flat, ok

    out = {}
    left_sorted = left.df.iloc[l_layout.order].reset_index(drop=True)
    for c in pcols:
        out[c] = left_sorted[c].to_numpy()
    for c in left_value_cols:
        out[lmap[c]] = left_sorted[c].to_numpy()

    r_sorted_df = right.df.iloc[r_sorted_take].reset_index(drop=True)
    for ci, c in enumerate(right_value_cols):
        if skipNulls and not broadcast_path:
            flat, ok = flat_right_indices(per_col_idx[ci])
        else:
            flat, ok = flat_right_indices(last_row_idx)
        vals = r_sorted_df[c].to_numpy()
        col_out = _gather(vals, flat, ok)
        if (not skipNulls) and not broadcast_path:
            # last right row's value, nulls included (tsdf.py:123-136)
            col_valid = (~pd.isna(r_sorted_df[c])).to_numpy()
            ok2 = ok & col_valid[np.where(ok, flat, 0)]
            col_out = _gather(vals, flat, ok2)
        out[rmap[c]] = col_out
        if (
            tsPartitionVal is not None
            and not suppress_null_warning
            and logger.isEnabledFor(logging.WARNING)
        ):
            if (~ok).any():
                logger.warning(
                    "Column " + rmap[c] + " had no values within the lookback "
                    "window. Consider using a larger window to avoid missing "
                    "values. If this is the first record in the data frame, "
                    "this warning can be ignored."
                )

    res = pd.DataFrame(out)
    if broadcast_path:
        # apply the inner-join filter while rows are still in packed
        # order — keep_mask_packed is indexed by (k_ids, pos)
        keep = keep_mask_packed[k_ids, pos]
        res = res[keep].reset_index(drop=True)
    if tsPartitionVal is not None or auto_bracketed:
        # the joint (key, bracket) layout emits rows in bracket order;
        # restore the same (key, ts) order the non-skew path produces so
        # the two strategies are interchangeable row-for-row
        perm = np.lexsort(
            (l_ts_ns[l_layout.order], l_codes[l_layout.order])
        )
        res = res.iloc[perm].reset_index(drop=True)

    new_ts = lmap[left.ts_col]
    return TSDF(res, ts_col=new_ts, partition_cols=pcols)
