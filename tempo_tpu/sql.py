"""Vectorized SQL expression engine for ``selectExpr`` / ``filter``.

The reference exposes Spark SQL expression strings through
``TSDF.selectExpr`` (scala/.../TSDF.scala:226-229) and string predicates
through ``filter``/``where`` (TSDF.scala:232-238); the Python tree routes
the same strings through Spark's parser via ``f.expr``.  tempo-tpu has no
Catalyst, so this module implements the expression surface directly: a
tokenizer + Pratt parser producing a small AST that evaluates vectorized
over pandas/numpy columns (and therefore also over the packed device
columns once materialised — the expressions themselves are host-side
projections, exactly like Spark evaluates them outside the TPU analog's
kernels).

Supported grammar (Spark-compatible subset, case-insensitive keywords):

* literals: integers, floats, ``'strings'``/``"strings"``, TRUE/FALSE/NULL
* identifiers, including backquoted ``` `weird col` ```
* arithmetic ``+ - * / %``, unary ``-``/``+``, string ``||`` concat
* comparisons ``= == != <> < <= > >=``
* boolean ``AND OR NOT``
* ``IS [NOT] NULL``, ``[NOT] IN (...)``, ``[NOT] BETWEEN a AND b``,
  ``[NOT] LIKE 'pat%'``, ``RLIKE 'regex'``
* ``CASE [expr] WHEN ... THEN ... [ELSE ...] END``
* ``CAST(expr AS type)`` for int/bigint/smallint/tinyint/float/double/
  string/boolean/timestamp/date/long
* function calls from the registry below (math, string, conditional,
  datetime — the set the reference's notebooks/tests actually use)

Null semantics follow SQL three-valued logic where it is observable:
comparisons and boolean ops propagate null (represented as pandas NA /
NaN), ``filter`` keeps only rows where the predicate is exactly TRUE.
"""

from __future__ import annotations

import math
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

__all__ = ["SqlError", "StrictSqlFallback", "parse", "evaluate",
           "eval_expr", "select_exprs", "filter_mask", "split_projection",
           "resolve_column", "column_refs", "map_columns", "unparse"]


class SqlError(ValueError):
    """Raised for unparseable or unsupported SQL expressions."""


class StrictSqlFallback(SqlError):
    """Raised under strict mode (``strict=True`` / TEMPO_TPU_SQL_STRICT)
    when an expression would silently leave the compiled SQL surface and
    fall back to a host-pandas engine."""


# ----------------------------------------------------------------------
# Tokenizer
# ----------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""\s*(?:
      (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?[dDlL]?)
     |(?P<str>'(?:[^'\\]|\\.|'')*'|"(?:[^"\\]|\\.)*")
     |(?P<ident>`[^`]+`|[A-Za-z_][A-Za-z_0-9]*)
     |(?P<op><=>|<=|>=|!=|<>|==|\|\||&&|[-+*/%<>=(),.])
    )""",
    re.X,
)


class _Tok:
    __slots__ = ("kind", "text")

    def __init__(self, kind: str, text: str):
        self.kind = kind
        self.text = text

    def __repr__(self):  # pragma: no cover - debug aid
        return f"{self.kind}:{self.text}"


def _tokenize(src: str) -> List[_Tok]:
    toks: List[_Tok] = []
    pos = 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m or m.end() == pos:
            rest = src[pos:].lstrip()
            if not rest:
                break
            raise SqlError(f"cannot tokenize SQL at: {rest[:30]!r}")
        pos = m.end()
        for kind in ("num", "str", "ident", "op"):
            text = m.group(kind)
            if text is not None:
                toks.append(_Tok(kind, text))
                break
    toks.append(_Tok("end", ""))
    return toks


# ----------------------------------------------------------------------
# AST: every node is a callable env -> value (pandas Series or scalar)
# ----------------------------------------------------------------------

Env = Dict[str, pd.Series]
Node = Callable[[Env], object]

_KEYWORDS = {
    "and", "or", "not", "in", "is", "null", "like", "rlike", "between",
    "case", "when", "then", "else", "end", "as", "true", "false", "cast",
    "distinct",
}


def _is_null(v):
    if isinstance(v, pd.Series):
        return v.isna()
    return pd.isna(v)


def _to_float(v):
    if isinstance(v, pd.Series):
        return pd.to_numeric(v, errors="coerce").astype(float)
    return float(v) if v is not None and not pd.isna(v) else np.nan


def _numeric_binop(op: str, a, b):
    # int/int keeps int for + - * % (Spark); / is always fractional
    if op == "+":
        return a + b
    if op == "-":
        return a - b
    if op == "*":
        return a * b
    if op == "/":
        return _to_float(a) / _to_float(b)
    if op == "%":
        # Spark % is the truncated remainder (sign of the dividend),
        # not Python's floored modulo: -7 % 3 = -1, not 2
        r = np.fmod(np.asarray(a) if not isinstance(a, pd.Series) else a, b)
        int_in = all(
            (isinstance(x, pd.Series)
             and pd.api.types.is_integer_dtype(x))
            or isinstance(x, (int, np.integer))
            for x in (a, b)
        )
        if isinstance(r, pd.Series):
            return r.astype("int64") if int_in else r
        r = r.item() if isinstance(r, np.ndarray) else r
        return int(r) if int_in else r
    raise SqlError(f"unknown arithmetic op {op}")  # pragma: no cover


def _sql_and(a, b):
    # three-valued AND over pandas nullable booleans
    a = _as_bool(a)
    b = _as_bool(b)
    return a & b


def _sql_or(a, b):
    a = _as_bool(a)
    b = _as_bool(b)
    return a | b


def _as_bool(v):
    if isinstance(v, pd.Series):
        if v.dtype == object or str(v.dtype) in ("bool", "boolean"):
            return v.astype("boolean")
        return v.astype("boolean")
    if v is None or (np.isscalar(v) and pd.isna(v)):
        return pd.NA
    return bool(v)


def _compare(op: str, a, b):
    """SQL comparison with null propagation: null op x -> null."""
    na = _is_null(a)
    nb = _is_null(b)
    if op in ("=", "=="):
        r = a == b
    elif op in ("!=", "<>"):
        r = a != b
    elif op == "<":
        r = a < b
    elif op == "<=":
        r = a <= b
    elif op == ">":
        r = a > b
    elif op == ">=":
        r = a >= b
    elif op == "<=>":  # null-safe equal
        both_null = _null_and(na, nb)
        r = (a == b) | both_null
        if isinstance(r, pd.Series):
            return r.fillna(False).astype("boolean")
        return bool(r)
    else:  # pragma: no cover
        raise SqlError(f"unknown comparison {op}")
    anynull = _null_and(na, nb, how="or")
    if isinstance(r, (pd.Series, np.ndarray)):
        r = pd.Series(r) if not isinstance(r, pd.Series) else r
        r = r.astype("boolean")
        return r.mask(pd.Series(anynull, index=r.index)
                      if not np.isscalar(anynull) else anynull)
    if (np.isscalar(anynull) and anynull) or anynull is True:
        return pd.NA
    return r


def _null_and(na, nb, how: str = "and"):
    if how == "or":
        return na | nb
    return na & nb


# ----------------------------------------------------------------------
# Function registry (vectorized over Series or plain scalars)
# ----------------------------------------------------------------------

def _series_or_scalar(fn_series, fn_scalar):
    def wrapped(v, *a):
        if isinstance(v, pd.Series):
            return fn_series(v, *a)
        return fn_scalar(v, *a)
    return wrapped


def _f_coalesce(*args):
    args = list(args)
    out = args[0]
    if not isinstance(out, pd.Series):
        for s in args:
            if isinstance(s, pd.Series):
                out = pd.Series(out, index=s.index, dtype=object)
                break
        else:
            for v in args:
                if not pd.isna(v):
                    return v
            return None
    out = out.copy()
    for nxt in args[1:]:
        mask = out.isna()
        if not mask.any():
            break
        if isinstance(nxt, pd.Series):
            out = out.mask(mask, nxt)
        else:
            out = out.mask(mask, nxt)
    return out


def _f_concat(*args):
    out = None
    for a in args:
        s = a.astype(str) if isinstance(a, pd.Series) else str(a)
        out = s if out is None else out + s
    return out


def _f_substring(s, start, length=None):
    # SQL substring is 1-indexed; 0 behaves like 1
    start = int(start)
    py = max(start - 1, 0)
    end = None if length is None else py + int(length)
    if isinstance(s, pd.Series):
        return s.astype(str).str.slice(py, end)
    return str(s)[py:end]


def _f_round(v, nd=0):
    nd = int(nd)
    if isinstance(v, pd.Series):
        return v.round(nd)
    return round(float(v), nd)


def _f_lpad(s, n, pad):
    n = int(n)
    if isinstance(s, pd.Series):
        return s.astype(str).str.pad(n, side="left", fillchar=str(pad)[0]).str.slice(0, n)
    t = str(s).rjust(n, str(pad)[0])
    return t[:n]


def _f_rpad(s, n, pad):
    n = int(n)
    if isinstance(s, pd.Series):
        return s.astype(str).str.pad(n, side="right", fillchar=str(pad)[0]).str.slice(0, n)
    return str(s).ljust(n, str(pad)[0])[:n]


def _dt_accessor(attr):
    def fn(v):
        if isinstance(v, pd.Series):
            return getattr(pd.to_datetime(v).dt, attr)
        return getattr(pd.Timestamp(v), attr)
    return fn


_TRUNC_MAP = {
    "year": "YS", "yyyy": "YS", "yy": "YS",
    "month": "MS", "mon": "MS", "mm": "MS",
    "day": "D", "dd": "D",
    "hour": "h", "minute": "min", "second": "s", "week": "W",
}


def _f_date_trunc(unit, v):
    unit = str(unit).lower()
    if unit not in _TRUNC_MAP:
        raise SqlError(f"date_trunc: unsupported unit {unit!r}")
    freq = _TRUNC_MAP[unit]
    ts = pd.to_datetime(v) if isinstance(v, pd.Series) else pd.Timestamp(v)
    if freq in ("YS", "MS", "W"):
        per = {"YS": "Y", "MS": "M", "W": "W"}[freq]
        if isinstance(ts, pd.Series):
            return ts.dt.to_period(per).dt.start_time
        return ts.to_period(per).start_time
    return ts.dt.floor(freq) if isinstance(ts, pd.Series) else ts.floor(freq)


def _f_unix_timestamp(v):
    ts = pd.to_datetime(v)
    if isinstance(ts, pd.Series):
        # normalise the unit first: pandas 2 infers datetime64[s]/[ms]
        # for strings, and astype(int64) counts in the stored unit
        return ts.astype("datetime64[ns]").astype("int64") // 1_000_000_000
    return int(pd.Timestamp(ts).value // 1_000_000_000)


def _f_if(cond, a, b):
    cond = _as_bool(cond)
    if isinstance(cond, pd.Series):
        return pd.Series(np.where(cond.fillna(False), a, b))
    return a if (cond is not pd.NA and cond) else b


def _minmax(npf, pyf):
    """Spark greatest/least SKIP nulls (null only when all args null) —
    np.fmax/fmin give exactly that for numerics."""

    def f(*args):
        series = [a for a in args if isinstance(a, pd.Series)]
        if series:
            idx = series[0].index
            out = None
            for a in args:
                arr = (pd.to_numeric(a, errors="coerce").to_numpy(float)
                       if isinstance(a, pd.Series) else a)
                out = arr if out is None else npf(out, arr)
            return pd.Series(out, index=idx)
        vals = [a for a in args if a is not None and not pd.isna(a)]
        return pyf(vals) if vals else None
    return f


_FUNCTIONS: Dict[str, Callable] = {
    "abs": _series_or_scalar(lambda s: s.abs(), abs),
    "ceil": _series_or_scalar(lambda s: np.ceil(_to_float(s)), math.ceil),
    "ceiling": _series_or_scalar(lambda s: np.ceil(_to_float(s)), math.ceil),
    "floor": _series_or_scalar(lambda s: np.floor(_to_float(s)), math.floor),
    "round": _f_round,
    "sqrt": _series_or_scalar(lambda s: np.sqrt(_to_float(s)), math.sqrt),
    "exp": _series_or_scalar(lambda s: np.exp(_to_float(s)), math.exp),
    "ln": _series_or_scalar(lambda s: np.log(_to_float(s)), math.log),
    "log": _series_or_scalar(lambda s: np.log(_to_float(s)), math.log),
    "log10": _series_or_scalar(lambda s: np.log10(_to_float(s)), math.log10),
    "log2": _series_or_scalar(lambda s: np.log2(_to_float(s)), math.log2),
    "pow": lambda a, b: _to_float(a) ** _to_float(b),
    "power": lambda a, b: _to_float(a) ** _to_float(b),
    "sin": _series_or_scalar(lambda s: np.sin(_to_float(s)), math.sin),
    "cos": _series_or_scalar(lambda s: np.cos(_to_float(s)), math.cos),
    "tan": _series_or_scalar(lambda s: np.tan(_to_float(s)), math.tan),
    "sign": _series_or_scalar(lambda s: np.sign(_to_float(s)),
                              lambda v: float(np.sign(v))),
    "signum": _series_or_scalar(lambda s: np.sign(_to_float(s)),
                                lambda v: float(np.sign(v))),
    "greatest": _minmax(np.fmax, max),
    "least": _minmax(np.fmin, min),
    "coalesce": _f_coalesce,
    "nvl": _f_coalesce,
    "nanvl": lambda a, b: (a.where(~a.isna(), b) if isinstance(a, pd.Series)
                           else (b if pd.isna(a) else a)),
    "isnull": lambda v: _is_null(v),
    "isnotnull": lambda v: ~_is_null(v) if isinstance(v, pd.Series)
                 else not pd.isna(v),
    "isnan": _series_or_scalar(lambda s: np.isnan(_to_float(s)),
                               lambda v: math.isnan(float(v))),
    "if": _f_if,
    "concat": _f_concat,
    "upper": _series_or_scalar(lambda s: s.astype(str).str.upper(),
                               lambda v: str(v).upper()),
    "lower": _series_or_scalar(lambda s: s.astype(str).str.lower(),
                               lambda v: str(v).lower()),
    "trim": _series_or_scalar(lambda s: s.astype(str).str.strip(),
                              lambda v: str(v).strip()),
    "ltrim": _series_or_scalar(lambda s: s.astype(str).str.lstrip(),
                               lambda v: str(v).lstrip()),
    "rtrim": _series_or_scalar(lambda s: s.astype(str).str.rstrip(),
                               lambda v: str(v).rstrip()),
    "length": _series_or_scalar(lambda s: s.astype(str).str.len(),
                                lambda v: len(str(v))),
    "substring": _f_substring,
    "substr": _f_substring,
    "replace": lambda s, a, b="": (s.astype(str).str.replace(str(a), str(b),
                                                             regex=False)
                                   if isinstance(s, pd.Series)
                                   else str(s).replace(str(a), str(b))),
    "lpad": _f_lpad,
    "rpad": _f_rpad,
    "split": lambda s, pat: (s.astype(str).str.split(str(pat))
                             if isinstance(s, pd.Series)
                             else str(s).split(str(pat))),
    "year": _dt_accessor("year"),
    "month": _dt_accessor("month"),
    "day": _dt_accessor("day"),
    "dayofmonth": _dt_accessor("day"),
    "hour": _dt_accessor("hour"),
    "minute": _dt_accessor("minute"),
    "second": _dt_accessor("second"),
    "date_trunc": _f_date_trunc,
    "to_timestamp": lambda v: pd.to_datetime(v),
    "to_date": lambda v: (pd.to_datetime(v).dt.normalize()
                          if isinstance(v, pd.Series)
                          else pd.Timestamp(v).normalize()),
    "unix_timestamp": _f_unix_timestamp,
    "negative": lambda v: -v,
    "positive": lambda v: v,
}


_CAST_TYPES = {
    "int": "int32", "integer": "int32", "smallint": "int16",
    "tinyint": "int8", "bigint": "int64", "long": "int64",
    "float": "float32", "double": "float64", "string": "str",
    "boolean": "bool", "timestamp": "timestamp", "date": "date",
}


def _cast(v, typ: str):
    typ = typ.lower()
    if typ not in _CAST_TYPES:
        raise SqlError(f"CAST: unsupported type {typ!r}")
    target = _CAST_TYPES[typ]
    if target == "timestamp":
        return pd.to_datetime(v)
    if target == "date":
        t = pd.to_datetime(v)
        return t.dt.normalize() if isinstance(t, pd.Series) else t.normalize()
    if isinstance(v, pd.Series):
        if target == "str":
            return v.astype(str)
        if target == "bool":
            return v.astype("boolean")
        if target.startswith("int"):
            if pd.api.types.is_datetime64_any_dtype(v):
                return v.astype("int64") // 1_000_000_000
            # SQL casts truncate toward zero; nulls stay null
            f = pd.to_numeric(v, errors="coerce")
            out = pd.Series(np.trunc(f.astype("float64")), index=v.index)
            return out.astype("Int64" if f.isna().any() else target)
        return pd.to_numeric(v, errors="coerce").astype(target)
    if pd.isna(v):
        return None
    if target == "str":
        return str(v)
    if target == "bool":
        return bool(v)
    if target.startswith("int"):
        return int(v)
    return float(v)


def _like_to_regex(pat: str) -> str:
    """LIKE pattern -> anchored regex.  ``\\`` escapes the next char
    (Spark's default LIKE escape).  Spark only permits the escape
    before ``%``, ``_`` or another escape char and rejects a trailing
    lone escape (ParseException); the same inputs raise here so a
    migrated query fails loudly instead of silently matching
    differently."""
    out = []
    i = 0
    while i < len(pat):
        ch = pat[i]
        if ch == "\\":
            if i + 1 >= len(pat) or pat[i + 1] not in ("%", "_", "\\"):
                raise SqlError(
                    f"invalid LIKE escape sequence in {pat!r}: the "
                    "escape character must precede '%', '_' or itself"
                )
            out.append(re.escape(pat[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "^" + "".join(out) + "$"


# ----------------------------------------------------------------------
# AST node classes
# ----------------------------------------------------------------------
#
# Every node is callable ``env -> value`` (a pandas Series or scalar), so
# a parsed tree evaluates exactly like the closure engine it replaced —
# and it is introspectable: ``canon()`` renders the tree as nested
# hashable tuples (the plan IR embeds these in node params so SQL-born
# plans get stable cache signatures), ``column_refs`` collects referenced
# columns for dead-column pruning, and ``map_columns`` rewrites
# references for compile-time resolution and filter pushdown.


def resolve_column(name: str, env) -> Optional[str]:
    """THE column-resolution ladder, shared by host evaluation and plan
    compilation so the two paths cannot diverge: exact match, then the
    dotted-suffix base (``tbl.col`` -> ``col``), then Spark's
    case-insensitive scan in column order.  ``env`` is any mapping or
    iterable of column names; returns the matching key or ``None``."""
    if name in env:
        return name
    base = name.split(".")[-1]
    if base in env:
        return base
    low = name.lower()
    for k in env:
        if k.lower() == low:
            return k
    return None


def null_masked_bool(computed: pd.Series, source: pd.Series) -> pd.Series:
    """Nullable-boolean coercion with the source's NULLs restored.

    Shared by LIKE / RLIKE / IN: passing ``na=pd.NA`` into a bool-dtype
    string op raises on this image's pandas ("boolean value of NA is
    ambiguous"), so predicates are computed over stringified values and
    the source NAs masked back in afterwards — one helper so the host
    path and the compiled path use byte-identical NULL handling."""
    return computed.astype("boolean").mask(source.isna())


class Expr:
    """Base class for parsed SQL expression nodes."""

    __slots__ = ()

    def __call__(self, env: "Env"):  # pragma: no cover - abstract
        raise NotImplementedError

    def canon(self) -> tuple:  # pragma: no cover - abstract
        raise NotImplementedError

    def children(self) -> Tuple["Expr", ...]:
        return ()

    def __repr__(self):  # pragma: no cover - debug aid
        return f"{type(self).__name__}{self.canon()!r}"


class Lit(Expr):
    __slots__ = ("value",)

    def __init__(self, value):
        self.value = value

    def __call__(self, env):
        return self.value

    def canon(self):
        # the type tag keeps 2 / 2.0 / True apart: they compare equal as
        # tuple elements but evaluate differently (int preservation), so
        # they must not share a plan signature
        return ("lit", type(self.value).__name__, self.value)


class Col(Expr):
    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __call__(self, env):
        k = resolve_column(self.name, env)
        if k is None:
            raise SqlError(f"column {self.name!r} not found")
        return env[k]

    def canon(self):
        return ("col", self.name)


class Func(Expr):
    __slots__ = ("name", "args")

    def __init__(self, name: str, args: Tuple[Expr, ...]):
        self.name = name  # lowercase registry key
        self.args = tuple(args)

    def __call__(self, env):
        return _FUNCTIONS[self.name](*[a(env) for a in self.args])

    def canon(self):
        return ("func", self.name, tuple(a.canon() for a in self.args))

    def children(self):
        return self.args


class Cast(Expr):
    __slots__ = ("inner", "typ")

    def __init__(self, inner: Expr, typ: str):
        self.inner = inner
        self.typ = typ

    def __call__(self, env):
        return _cast(self.inner(env), self.typ)

    def canon(self):
        return ("cast", self.typ.lower(), self.inner.canon())

    def children(self):
        return (self.inner,)


class Neg(Expr):
    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        return -self.inner(env)

    def canon(self):
        return ("neg", self.inner.canon())

    def children(self):
        return (self.inner,)


class Arith(Expr):
    __slots__ = ("op", "left", "right")

    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def __call__(self, env):
        return _numeric_binop(self.op, self.left(env), self.right(env))

    def canon(self):
        return ("arith", self.op, self.left.canon(), self.right.canon())

    def children(self):
        return (self.left, self.right)


class Concat(Expr):
    __slots__ = ("left", "right")

    def __init__(self, left: Expr, right: Expr):
        self.left = left
        self.right = right

    def __call__(self, env):
        return _f_concat(self.left(env), self.right(env))

    def canon(self):
        return ("concat", self.left.canon(), self.right.canon())

    def children(self):
        return (self.left, self.right)


class Cmp(Expr):
    __slots__ = ("op", "left", "right")

    def __init__(self, op: str, left: Expr, right: Expr):
        self.op = op
        self.left = left
        self.right = right

    def __call__(self, env):
        return _compare(self.op, self.left(env), self.right(env))

    def canon(self):
        return ("cmp", self.op, self.left.canon(), self.right.canon())

    def children(self):
        return (self.left, self.right)


class And(Expr):
    __slots__ = ("left", "right")

    def __init__(self, left: Expr, right: Expr):
        self.left = left
        self.right = right

    def __call__(self, env):
        return _sql_and(self.left(env), self.right(env))

    def canon(self):
        return ("and", self.left.canon(), self.right.canon())

    def children(self):
        return (self.left, self.right)


class Or(Expr):
    __slots__ = ("left", "right")

    def __init__(self, left: Expr, right: Expr):
        self.left = left
        self.right = right

    def __call__(self, env):
        return _sql_or(self.left(env), self.right(env))

    def canon(self):
        return ("or", self.left.canon(), self.right.canon())

    def children(self):
        return (self.left, self.right)


class Not(Expr):
    """Three-valued NOT (both the prefix ``NOT`` and predicate negation:
    Series negate through the nullable-boolean dtype, scalar NULL stays
    NULL)."""

    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        v = self.inner(env)
        if isinstance(v, pd.Series):
            return ~_as_bool(v)
        return _scalar_not(v)

    def canon(self):
        return ("not", self.inner.canon())

    def children(self):
        return (self.inner,)


class Flip(Expr):
    """Plain two-valued complement for IS NOT NULL / IS NOT TRUE|FALSE —
    the inner result is never NULL, so no NA handling."""

    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        v = self.inner(env)
        if isinstance(v, pd.Series):
            return ~v
        return not v

    def canon(self):
        return ("flip", self.inner.canon())

    def children(self):
        return (self.inner,)


class IsNull(Expr):
    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        return _is_null(self.inner(env))

    def canon(self):
        return ("isnull", self.inner.canon())

    def children(self):
        return (self.inner,)


class IsTrue(Expr):
    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        v = self.inner(env)
        if isinstance(v, pd.Series):
            return _as_bool(v).fillna(False)
        # bool() also accepts np.bool_, which `is True` does not
        return (not pd.isna(v)) and bool(v)

    def canon(self):
        return ("istrue", self.inner.canon())

    def children(self):
        return (self.inner,)


class IsFalse(Expr):
    __slots__ = ("inner",)

    def __init__(self, inner: Expr):
        self.inner = inner

    def __call__(self, env):
        v = self.inner(env)
        if isinstance(v, pd.Series):
            return ~_as_bool(v).fillna(True)
        return (not pd.isna(v)) and not bool(v)

    def canon(self):
        return ("isfalse", self.inner.canon())

    def children(self):
        return (self.inner,)


class Between(Expr):
    __slots__ = ("inner", "lo", "hi")

    def __init__(self, inner: Expr, lo: Expr, hi: Expr):
        self.inner = inner
        self.lo = lo
        self.hi = hi

    def __call__(self, env):
        v = self.inner(env)
        return _sql_and(_compare(">=", v, self.lo(env)),
                        _compare("<=", v, self.hi(env)))

    def canon(self):
        return ("between", self.inner.canon(), self.lo.canon(),
                self.hi.canon())

    def children(self):
        return (self.inner, self.lo, self.hi)


class InList(Expr):
    __slots__ = ("inner", "items")

    def __init__(self, inner: Expr, items: Tuple[Expr, ...]):
        self.inner = inner
        self.items = tuple(items)

    def __call__(self, env):
        v = self.inner(env)
        vals = [it(env) for it in self.items]
        if isinstance(v, pd.Series):
            return null_masked_bool(v.isin(vals), v)
        if pd.isna(v):
            return pd.NA
        return v in vals

    def canon(self):
        return ("in", self.inner.canon(),
                tuple(it.canon() for it in self.items))

    def children(self):
        return (self.inner,) + self.items


class Like(Expr):
    __slots__ = ("inner", "pat")

    def __init__(self, inner: Expr, pat: Expr):
        self.inner = inner
        self.pat = pat

    def __call__(self, env):
        v, p = self.inner(env), self.pat(env)
        rx = _like_to_regex(str(p))
        if isinstance(v, pd.Series):
            return null_masked_bool(v.astype(str).str.match(rx), v)
        return bool(re.match(rx, str(v)))

    def canon(self):
        return ("like", self.inner.canon(), self.pat.canon())

    def children(self):
        return (self.inner, self.pat)


class RLike(Expr):
    __slots__ = ("inner", "pat")

    def __init__(self, inner: Expr, pat: Expr):
        self.inner = inner
        self.pat = pat

    def __call__(self, env):
        v, p = self.inner(env), self.pat(env)
        if isinstance(v, pd.Series):
            return null_masked_bool(
                v.astype(str).str.contains(str(p), regex=True), v)
        return bool(re.search(str(p), str(v)))

    def canon(self):
        return ("rlike", self.inner.canon(), self.pat.canon())

    def children(self):
        return (self.inner, self.pat)


class Case(Expr):
    __slots__ = ("subject", "branches", "default")

    def __init__(self, subject: Optional[Expr],
                 branches: Tuple[Tuple[Expr, Expr], ...],
                 default: Optional[Expr]):
        self.subject = subject
        self.branches = tuple(branches)
        self.default = default

    def __call__(self, env):
        subject, branches, default = self.subject, self.branches, self.default
        conds = []
        vals = []
        for c, v in branches:
            cv = c(env)
            if subject is not None:
                cv = _compare("=", subject(env), cv)
            cv = _as_bool(cv)
            if isinstance(cv, pd.Series):
                cv = cv.fillna(False).to_numpy(bool)
            conds.append(cv)
            vals.append(v(env))
        dv = default(env) if default is not None else None

        def numeric_branch(v):
            if v is None:
                return True
            if isinstance(v, pd.Series):
                return pd.api.types.is_numeric_dtype(v)
            return isinstance(v, (int, float, np.number)) \
                and not isinstance(v, bool)

        all_numeric = all(numeric_branch(v) for v in vals + [dv])
        # vectorized if any piece is a Series
        series = [x for x in conds + vals + [dv]
                  if isinstance(x, (pd.Series, np.ndarray))]
        if series:
            n = len(series[0])
            conds = [np.broadcast_to(np.asarray(c), (n,))
                     if not np.isscalar(c)
                     else np.full(n, bool(c)) for c in conds]
            vals = [np.asarray(v.astype(object) if isinstance(v, pd.Series)
                               else v)
                    if isinstance(v, (pd.Series, np.ndarray))
                    else np.full(n, v, dtype=object) for v in vals]
            dvv = (np.asarray(dv.astype(object)) if isinstance(dv, pd.Series)
                   else np.full(n, dv, dtype=object))
            out = pd.Series(np.select(conds, vals, default=dvv))
            if not all_numeric:
                # string/object branches keep their dtype — Spark
                # does not re-parse '01' into 1
                return out
            try:
                return pd.to_numeric(out)
            except (ValueError, TypeError):
                return out
        for c, v in zip(conds, vals):
            if c is not pd.NA and c:
                return v
        return dv

    def canon(self):
        return ("case",
                self.subject.canon() if self.subject is not None else None,
                tuple((c.canon(), v.canon()) for c, v in self.branches),
                self.default.canon() if self.default is not None else None)

    def children(self):
        kids = [] if self.subject is None else [self.subject]
        for c, v in self.branches:
            kids += [c, v]
        if self.default is not None:
            kids.append(self.default)
        return tuple(kids)


def unparse(expr: Expr) -> str:
    """Render a parsed tree back to SQL text (fully parenthesized — for
    ``explain()`` display and plan params, not for round-tripping the
    user's exact formatting)."""
    e, u = expr, unparse
    if isinstance(e, Lit):
        v = e.value
        if v is None:
            return "NULL"
        if v is True:
            return "TRUE"
        if v is False:
            return "FALSE"
        if isinstance(v, str):
            return "'" + v.replace("'", "''") + "'"
        return repr(v)
    if isinstance(e, Col):
        return e.name
    if isinstance(e, Func):
        return f"{e.name}({', '.join(u(a) for a in e.args)})"
    if isinstance(e, Cast):
        return f"CAST({u(e.inner)} AS {e.typ})"
    if isinstance(e, Neg):
        return f"(-{u(e.inner)})"
    if isinstance(e, (Arith, Cmp)):
        return f"({u(e.left)} {e.op} {u(e.right)})"
    if isinstance(e, Concat):
        return f"({u(e.left)} || {u(e.right)})"
    if isinstance(e, And):
        return f"({u(e.left)} AND {u(e.right)})"
    if isinstance(e, Or):
        return f"({u(e.left)} OR {u(e.right)})"
    if isinstance(e, Not):
        return f"(NOT {u(e.inner)})"
    if isinstance(e, Flip):
        inner = e.inner
        for cls, word in ((IsNull, "NULL"), (IsTrue, "TRUE"),
                          (IsFalse, "FALSE")):
            if isinstance(inner, cls):
                return f"({u(inner.inner)} IS NOT {word})"
        return f"(NOT {u(inner)})"
    if isinstance(e, IsNull):
        return f"({u(e.inner)} IS NULL)"
    if isinstance(e, IsTrue):
        return f"({u(e.inner)} IS TRUE)"
    if isinstance(e, IsFalse):
        return f"({u(e.inner)} IS FALSE)"
    if isinstance(e, Between):
        return f"({u(e.inner)} BETWEEN {u(e.lo)} AND {u(e.hi)})"
    if isinstance(e, InList):
        return f"({u(e.inner)} IN ({', '.join(u(i) for i in e.items)}))"
    if isinstance(e, Like):
        return f"({u(e.inner)} LIKE {u(e.pat)})"
    if isinstance(e, RLike):
        return f"({u(e.inner)} RLIKE {u(e.pat)})"
    if isinstance(e, Case):
        parts = ["CASE"]
        if e.subject is not None:
            parts.append(u(e.subject))
        for c, v in e.branches:
            parts.append(f"WHEN {u(c)} THEN {u(v)}")
        if e.default is not None:
            parts.append(f"ELSE {u(e.default)}")
        parts.append("END")
        return " ".join(parts)
    return repr(e)  # pragma: no cover - new node classes


def walk(expr: Expr):
    """Yield every node of a parsed tree (pre-order)."""
    yield expr
    for child in expr.children():
        yield from walk(child)


def column_refs(expr: Expr):
    """The set of column names an expression reads."""
    return {n.name for n in walk(expr) if isinstance(n, Col)}


def map_columns(expr: Expr, fn) -> Expr:
    """Rebuild a tree with every column reference renamed through
    ``fn(name) -> name`` (compile-time resolution, filter pushdown
    through projection aliases).  Shared subtrees are rebuilt, never
    mutated, so parsed Exprs stay immutable/cacheable."""
    if isinstance(expr, Col):
        nn = fn(expr.name)
        return expr if nn == expr.name else Col(nn)
    if isinstance(expr, Lit):
        return expr
    m = lambda e: map_columns(e, fn)  # noqa: E731
    if isinstance(expr, Func):
        return Func(expr.name, tuple(m(a) for a in expr.args))
    if isinstance(expr, Cast):
        return Cast(m(expr.inner), expr.typ)
    if isinstance(expr, (Neg, Not, Flip, IsNull, IsTrue, IsFalse)):
        return type(expr)(m(expr.inner))
    if isinstance(expr, (Arith, Cmp)):
        return type(expr)(expr.op, m(expr.left), m(expr.right))
    if isinstance(expr, (Concat, And, Or)):
        return type(expr)(m(expr.left), m(expr.right))
    if isinstance(expr, Between):
        return Between(m(expr.inner), m(expr.lo), m(expr.hi))
    if isinstance(expr, InList):
        return InList(m(expr.inner), tuple(m(i) for i in expr.items))
    if isinstance(expr, (Like, RLike)):
        return type(expr)(m(expr.inner), m(expr.pat))
    if isinstance(expr, Case):
        return Case(None if expr.subject is None else m(expr.subject),
                    tuple((m(c), m(v)) for c, v in expr.branches),
                    None if expr.default is None else m(expr.default))
    raise SqlError(f"unknown expression node {type(expr).__name__}")


# ----------------------------------------------------------------------
# Parser (precedence climbing)
# ----------------------------------------------------------------------

class _Parser:
    def __init__(self, toks: List[_Tok]):
        self.toks = toks
        self.pos = 0

    # -- token helpers --------------------------------------------------
    def peek(self) -> _Tok:
        return self.toks[self.pos]

    def next(self) -> _Tok:
        t = self.toks[self.pos]
        self.pos += 1
        return t

    def kw(self, word: str) -> bool:
        t = self.peek()
        if t.kind == "ident" and t.text.lower() == word:
            self.pos += 1
            return True
        return False

    def op(self, *texts: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "op" and t.text in texts:
            self.pos += 1
            return t.text
        return None

    def expect_op(self, text: str):
        if not self.op(text):
            raise SqlError(f"expected {text!r}, found {self.peek().text!r}")

    # -- grammar --------------------------------------------------------
    def parse_expr(self) -> Node:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.kw("or"):
            left = Or(left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.kw("and"):
            left = And(left, self.parse_not())
        return left

    def parse_not(self) -> Expr:
        if self.kw("not"):
            return Not(self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Expr:
        left = self.parse_additive()
        # IS [NOT] NULL / IS [NOT] TRUE|FALSE
        if self.kw("is"):
            negate = self.kw("not")
            if self.kw("null"):
                node = IsNull(left)
            elif self.kw("true"):
                node = IsTrue(left)
            elif self.kw("false"):
                node = IsFalse(left)
            else:
                raise SqlError("expected NULL/TRUE/FALSE after IS")
            return Flip(node) if negate else node
        negate = self.kw("not")
        if self.kw("between"):
            lo = self.parse_additive()
            if not self.kw("and"):
                raise SqlError("BETWEEN requires AND")
            hi = self.parse_additive()
            return _maybe_negate(Between(left, lo, hi), negate)
        if self.kw("in"):
            self.expect_op("(")
            items = [self.parse_expr()]
            while self.op(","):
                items.append(self.parse_expr())
            self.expect_op(")")
            return _maybe_negate(InList(left, tuple(items)), negate)
        if self.kw("like"):
            return _maybe_negate(Like(left, self.parse_additive()), negate)
        if self.kw("rlike"):
            return _maybe_negate(RLike(left, self.parse_additive()), negate)
        if negate:
            raise SqlError("dangling NOT")
        cmp = self.op("<=>", "<=", ">=", "!=", "<>", "==", "=", "<", ">")
        if cmp:
            return Cmp(cmp, left, self.parse_additive())
        return left

    def parse_additive(self) -> Expr:
        left = self.parse_multiplicative()
        while True:
            o = self.op("+", "-", "||")
            if not o:
                break
            right = self.parse_multiplicative()
            left = Concat(left, right) if o == "||" else Arith(o, left, right)
        return left

    def parse_multiplicative(self) -> Expr:
        left = self.parse_unary()
        while True:
            o = self.op("*", "/", "%")
            if not o:
                break
            left = Arith(o, left, self.parse_unary())
        return left

    def parse_unary(self) -> Expr:
        if self.op("-"):
            return Neg(self.parse_unary())
        if self.op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if self.op("("):
            inner = self.parse_expr()
            self.expect_op(")")
            return inner
        if t.kind == "num":
            self.pos += 1
            text = t.text.rstrip("dDlL")
            suffix = t.text[len(text):].lower()
            if "." in text or "e" in text.lower() or suffix == "d":
                val = float(text)
            else:
                val = int(text)
            return Lit(val)
        if t.kind == "str":
            self.pos += 1
            body = t.text[1:-1]
            if t.text[0] == "'":
                body = body.replace("''", "'")
            body = re.sub(r"\\(.)", r"\1", body)
            return Lit(body)
        if t.kind == "ident":
            low = t.text.lower()
            if low == "case":
                return self.parse_case()
            if low == "cast":
                self.pos += 1
                self.expect_op("(")
                inner = self.parse_expr()
                if not self.kw("as"):
                    raise SqlError("CAST requires AS <type>")
                typ_tok = self.next()
                if typ_tok.kind != "ident":
                    raise SqlError("CAST requires a type name")
                self.expect_op(")")
                return Cast(inner, typ_tok.text)
            if low == "true":
                self.pos += 1
                return Lit(True)
            if low == "false":
                self.pos += 1
                return Lit(False)
            if low == "null":
                self.pos += 1
                return Lit(None)
            self.pos += 1
            # function call?
            if self.peek().kind == "op" and self.peek().text == "(" \
                    and low not in _KEYWORDS:
                self.pos += 1  # consume (
                args: List[Expr] = []
                if not self.op(")"):
                    args.append(self.parse_expr())
                    while self.op(","):
                        args.append(self.parse_expr())
                    self.expect_op(")")
                if low not in _FUNCTIONS:
                    raise SqlError(
                        f"unsupported SQL function {t.text!r}; supported: "
                        + ", ".join(sorted(_FUNCTIONS)))
                return Func(low, tuple(args))
            name = t.text[1:-1] if t.text.startswith("`") else t.text
            # dotted access (`tbl.col`) resolves to the bare column
            while self.peek().kind == "op" and self.peek().text == ".":
                self.pos += 1
                nxt = self.next()
                if nxt.kind != "ident":
                    raise SqlError("expected identifier after '.'")
                name = name + "." + nxt.text
            return Col(name)
        raise SqlError(f"unexpected token {t.text!r}")

    def parse_case(self) -> Expr:
        self.pos += 1  # consume CASE
        subject: Optional[Expr] = None
        if not (self.peek().kind == "ident"
                and self.peek().text.lower() == "when"):
            subject = self.parse_expr()
        branches: List[Tuple[Expr, Expr]] = []
        while self.kw("when"):
            cond = self.parse_expr()
            if not self.kw("then"):
                raise SqlError("WHEN requires THEN")
            val = self.parse_expr()
            branches.append((cond, val))
        default: Optional[Expr] = None
        if self.kw("else"):
            default = self.parse_expr()
        if not self.kw("end"):
            raise SqlError("CASE requires END")
        if not branches:
            raise SqlError("CASE requires at least one WHEN")
        return Case(subject, tuple(branches), default)


def _scalar_not(v):
    if v is None or (np.isscalar(v) and pd.isna(v)):
        return pd.NA
    return not v


def _maybe_negate(node: Expr, negate: bool) -> Expr:
    # predicate negation is the same three-valued NOT as the prefix
    # keyword (~astype("boolean") == ~_as_bool for any Series dtype)
    return Not(node) if negate else node


# ----------------------------------------------------------------------
# Public API
# ----------------------------------------------------------------------

def parse(expr: str) -> Expr:
    """Parse one SQL expression into an evaluatable, introspectable
    ``Expr`` node."""
    p = _Parser(_tokenize(expr))
    node = p.parse_expr()
    if p.peek().kind != "end":
        raise SqlError(f"trailing tokens at {p.peek().text!r} in {expr!r}")
    return node


def evaluate(node: Expr, df: pd.DataFrame):
    """Evaluate a parsed node against a DataFrame's columns."""
    env = {c: df[c] for c in df.columns}
    out = node(env)
    if isinstance(out, pd.Series):
        out = out.reset_index(drop=True)
        out.index = df.index
    return out


def eval_expr(df: pd.DataFrame, expr: str):
    """One-shot parse + evaluate."""
    return evaluate(parse(expr), df)


_AS_SPLIT_RE = re.compile(r"\s+as\s+(`[^`]+`|[A-Za-z_][A-Za-z_0-9]*)\s*$",
                          re.IGNORECASE)


def split_projection(raw: str) -> Tuple[str, str]:
    """Split one ``selectExpr`` string into ``(alias, body)``: a trailing
    ``AS alias`` names the output column, otherwise the expression text
    itself does (bare columns keep their name)."""
    m = _AS_SPLIT_RE.search(raw)
    if m:
        alias = m.group(1)
        alias = alias[1:-1] if alias.startswith("`") else alias
        return alias, raw[: m.start()]
    return raw.strip(), raw


def select_exprs(df: pd.DataFrame, exprs: Sequence[str]) -> pd.DataFrame:
    """Spark ``selectExpr`` semantics: each string is an expression with
    an optional trailing ``AS alias``; unaliased expressions use their
    text as the output column name (bare columns keep their name)."""
    out = {}
    for raw in exprs:
        alias, body = split_projection(raw)
        val = eval_expr(df, body)
        if not isinstance(val, pd.Series):
            val = pd.Series([val] * len(df), index=df.index)
        out[alias] = val
    return pd.DataFrame(out, index=df.index)


def filter_mask(df: pd.DataFrame, predicate: str) -> pd.Series:
    """Boolean row mask for ``filter``/``where``: TRUE rows only (SQL
    three-valued logic drops NULL rows, matching Spark)."""
    v = eval_expr(df, predicate)
    if not isinstance(v, pd.Series):
        v = pd.Series([v] * len(df), index=df.index)
    return v.astype("boolean").fillna(False).astype(bool)
