"""Test-support subpackage: fault injection (:mod:`tempo_tpu.testing.faults`).

Shipped inside the library (not under tests/) so downstream users can
chaos-test their own pipelines against the same harness the ``chaos``
suite uses.
"""

from tempo_tpu.testing import faults  # noqa: F401

__all__ = ["faults"]
