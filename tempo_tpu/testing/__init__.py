"""Test-support subpackage: fault injection
(:mod:`tempo_tpu.testing.faults`) and the chaos campaign harness
(:mod:`tempo_tpu.testing.chaos` — scripted kill/flaky/delay schedules
against live serving + query planes, bench config 15's body).

Shipped inside the library (not under tests/) so downstream users can
chaos-test their own pipelines against the same harness the ``chaos``
suite uses.  ``chaos`` is imported lazily by its consumers (it pulls
the serve/service planes in); ``faults`` stays import-light.
"""

from tempo_tpu.testing import faults  # noqa: F401

__all__ = ["faults", "chaos"]
