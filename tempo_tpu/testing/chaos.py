"""Chaos campaign harness: drive the serving + query planes through
kill / flaky / delay schedules under Poisson load and prove the
fault-domain contracts on the way through.

PR-1 chaos coverage (tests/test_chaos.py) exercises the BATCH side:
``run_resumable`` chains killed mid-save.  This module is the serving
side's equivalent — a scripted campaign against a live
:class:`~tempo_tpu.serve.StreamCohort` behind a
:class:`~tempo_tpu.serve.CohortExecutor`, and against a
:class:`~tempo_tpu.service.QueryService`, asserting the four
availability invariants the fault-domain runtime promises:

* **no hung tickets** — every submit resolves with a result or a NAMED
  error (``DeadlineExceeded`` / ``QuarantinedError`` / ``Cancelled`` /
  ``ShutdownError`` / the injected fault), within a bounded wait;
* **bounded recovery** — after a :class:`SimulatedKill` of the serving
  plane, ``CohortExecutor.resume`` + warmup completes inside the
  declared bound;
* **zero recompiles after recovery** — the resumed plane's replay and
  steady state build no new executables past its warmup;
* **bitwise tails** — every stream's full emission history (including
  the replayed unacked tail) is byte-identical to an UNINJECTED twin
  cohort fed the same per-stream events.

The campaign is deterministic: injections are call-counted
(:class:`~tempo_tpu.testing.faults.FaultInjector`), latency injection
drives the deadline plane against a *known* sleep instead of racing a
wall clock, and the feeder keeps at most one in-flight event per
stream so per-stream order survives retries (an event is re-submitted
only until it is acked — the at-least-once feeder every replayable
event source implements).

Entry points: :func:`run_serving_campaign`,
:func:`run_service_campaign`, and :func:`run_campaign` (both planes,
one report — bench config 15's ``--only-chaos-serving`` body); the
BATCH plane's campaign is :func:`run_pipeline_campaign` (bench config
16's ``--only-chaos-pipeline`` body): the Parquet→mesh→planned-chain
path driven through ingest kills, row-group corruption, torn writes,
deadlines, a flapping file, a mid-chain plan-barrier kill, and the
≥1B-row out-of-core slab sweep killed and resumed mid-run — with
every resumed artifact asserted bitwise against an uninjected twin.
"""

from __future__ import annotations

import os
import time
import zlib
from typing import Dict, List, Optional

import numpy as np

from tempo_tpu import profiling
from tempo_tpu.resilience import (Cancelled, CheckpointError,
                                  CircuitBreaker, DeadlineExceeded,
                                  QuarantinedError, ShutdownError)
from tempo_tpu.testing import faults

#: per-ticket result() bound: anything still unresolved after this is a
#: HUNG ticket and fails the campaign (the invariant, not a tuning)
RESULT_TIMEOUT_S = 120.0


def _du(path: str) -> int:
    """Recursive byte size of one snapshot directory."""
    total = 0
    for root, _, files in os.walk(path):
        for f in files:
            total += os.path.getsize(os.path.join(root, f))
    return total


# ----------------------------------------------------------------------
# Event schedules (Poisson load)
# ----------------------------------------------------------------------

#: trailing streams of a campaign cohort that live in a SECOND shape
#: bucket (3 declared series -> bucket 4) and receive only a quarter
#: of the traffic: once their events run dry, their bucket goes quiet
#: and every later differential snapshot excludes it — the dirty-
#: bucket economics the acceptance measures
COLD_STREAMS = 2


def make_events(rng, n_streams: int, events_per_stream: int,
                left_frac: float = 0.2):
    """Per-stream event lists under Poisson arrivals: exponential
    inter-arrival gaps on a per-stream logical clock (strictly
    increasing, so per-stream merged order holds by construction),
    ~``left_frac`` AS-OF queries, NaN runs in the values.  ``[(kind,
    ts, value_or_None)]`` per stream; every stream's first event is a
    data push (a query against an empty carry is legal but dull).
    The last :data:`COLD_STREAMS` streams get a quarter of the
    traffic — they finish early and leave their shape bucket quiet."""
    out = []
    for s in range(n_streams):
        n_ev = events_per_stream
        if n_streams > COLD_STREAMS and s >= n_streams - COLD_STREAMS:
            n_ev = max(2, events_per_stream // 4)
        gaps = rng.exponential(scale=4e7, size=n_ev).astype(np.int64) + 1
        ts = np.cumsum(gaps) + np.int64(10**9) * (s + 1)
        kinds = rng.random(n_ev) < left_frac
        kinds[0] = False
        vals = rng.standard_normal(n_ev).astype(np.float32)
        vals[rng.random(n_ev) < 0.05] = np.nan
        out.append([("left" if kinds[i] else "right", int(ts[i]),
                     None if kinds[i] else float(vals[i]))
                    for i in range(n_ev)])
    return out


def _mk_cohort(n_streams: int, checkpoint_dir: Optional[str], ckpt_every,
               diff_snapshots: bool):
    from tempo_tpu.serve import StreamCohort

    cohort = StreamCohort(
        ("px",), window_secs=10.0, window_rows_bound=8, ema_alpha=0.2,
        max_lookback=16, slots=n_streams, checkpoint_dir=checkpoint_dir,
        ckpt_every=ckpt_every, diff_snapshots=diff_snapshots)
    members = []
    for s in range(n_streams):
        cold = (n_streams > COLD_STREAMS
                and s >= n_streams - COLD_STREAMS)
        # cold streams declare 3 series (shape bucket 4) but only feed
        # "s0": a second bucket group exists and goes quiet early
        members.append(cohort.add_stream(
            f"u{s}", ["s0", "s1", "s2"] if cold else ["s0"]))
    return cohort, members


def _golden_run(events) -> List[List[dict]]:
    """The uninjected twin: a fresh cohort fed the same per-stream
    events directly (no executor, no faults, no checkpoints) — the
    byte-level oracle for every stream's full emission history."""
    cohort, members = _mk_cohort(len(events), None, 0, False)
    out: List[List[dict]] = [[] for _ in events]
    pos = [0] * len(events)
    live = list(range(len(events)))
    while live:
        nxt = []
        for s in live:
            kind, ts, val = events[s][pos[s]]
            m = members[s]
            if kind == "right":
                r = m.push(["s0"], [ts], {"px": np.float32([val])})
            else:
                r = m.push_left(["s0"], [ts])
            out[s].append({k: np.asarray(v[0]) for k, v in r.items()})
            pos[s] += 1
            if pos[s] < len(events[s]):
                nxt.append(s)
        live = nxt
    return out


# ----------------------------------------------------------------------
# The serving-plane campaign
# ----------------------------------------------------------------------

class _Feeder:
    """At-least-once, order-preserving feeder: one in-flight event per
    stream, retried until acked; every outcome is categorized and every
    ticket must resolve inside ``RESULT_TIMEOUT_S`` (a hang fails the
    campaign on the spot)."""

    def __init__(self, events, golden):
        self.events = events
        self.golden = golden
        self.pos = [0] * len(events)
        self.emissions: List[List[Optional[dict]]] = [
            [None] * len(ev) for ev in events]
        self.outcomes = {"ok": 0, "deadline": 0, "quarantined": 0,
                         "shutdown": 0, "injected": 0, "retried": 0}
        self.resolved = 0

    def pending(self, s: int) -> bool:
        return self.pos[s] < len(self.events[s])

    def tick_of(self, s: int, members):
        kind, ts, val = self.events[s][self.pos[s]]
        return (kind, members[s], "s0", ts,
                None if val is None else {"px": np.float32(val)}, None)

    def settle(self, s_list, tickets) -> List[int]:
        """Resolve one round's tickets; returns the streams whose event
        must be RETRIED (everything else advanced or terminally
        failed the campaign)."""
        retry: List[int] = []
        for s, t in zip(s_list, tickets):
            try:
                r = t.result(timeout=RESULT_TIMEOUT_S)
            # NB: DeadlineExceeded IS a TimeoutError (and InjectedFault
            # an OSError) — the named outcomes must be caught before
            # the bare TimeoutError that means an actual HANG
            except DeadlineExceeded:
                self.outcomes["deadline"] += 1
                retry.append(s)
            except QuarantinedError:
                self.outcomes["quarantined"] += 1
                retry.append(s)
            except ShutdownError:
                self.outcomes["shutdown"] += 1
                retry.append(s)
            except faults.InjectedFault:
                self.outcomes["injected"] += 1
                retry.append(s)
            except TimeoutError as e:
                raise AssertionError(
                    f"HUNG ticket for stream {s}: {e}") from e
            else:
                i = self.pos[s]
                self.emissions[s][i] = {k: np.asarray(v)
                                        for k, v in r.items()}
                self.pos[s] += 1
                self.outcomes["ok"] += 1
            finally:
                self.resolved += 1
        self.outcomes["retried"] += len(retry)
        return retry

    def round(self, ex, members, streams=None, deadline=None) -> List[int]:
        """Submit one pending event per (given) stream as ONE
        submit_many chunk, settle, return retries."""
        s_list = [s for s in (streams if streams is not None
                              else range(len(self.events)))
                  if self.pending(s)]
        if not s_list:
            return []
        tickets = ex.submit_many([self.tick_of(s, members)
                                  for s in s_list], deadline=deadline)
        return self.settle(s_list, tickets)

    def acked_total(self) -> int:
        return sum(self.pos)

    def audit_tails(self) -> int:
        """Every stream's full emission history bitwise vs golden."""
        checked = 0
        for s, gold in enumerate(self.golden):
            assert self.pos[s] == len(self.events[s]), (
                f"stream {s} incomplete: {self.pos[s]} of "
                f"{len(self.events[s])} events acked")
            for i, want in enumerate(gold):
                got = self.emissions[s][i]
                assert got is not None, (s, i)
                assert set(got) == set(want), (s, i)
                for key in want:
                    assert got[key].tobytes() == want[key].tobytes(), (
                        f"stream {s} event {i} field {key!r}: "
                        f"{got[key]} != {want[key]}")
                checked += 1
        return checked


def run_serving_campaign(checkpoint_dir: str, *, n_streams: int = 12,
                         events_per_stream: int = 24, seed: int = 17,
                         ckpt_every: int = 40,
                         recovery_bound_s: float = 60.0,
                         delay_s: float = 0.5,
                         delay_deadline_s: float = 0.12) -> dict:
    """The serving-plane chaos campaign (see module docstring).

    Phases: clean warm-up traffic -> flaky dispatches (retried) ->
    a plane-level fault that kills the drain thread (supervised
    restart) -> latency injection against a short deadline (stage-named
    ``DeadlineExceeded``, nothing lost) -> a poison-pill member driven
    into quarantine and recovered through a half-open probe ->
    :class:`SimulatedKill` of a dispatch (plane death: every
    outstanding ticket resolves with ``ShutdownError``) ->
    ``CohortExecutor.resume`` from the differential snapshot chain ->
    replay of every unacked tail -> full bitwise tail audit vs the
    uninjected twin."""
    from tempo_tpu.serve import CohortExecutor, StreamCohort

    rng = np.random.default_rng(seed)
    events = make_events(rng, n_streams, events_per_stream)
    n_total = sum(len(ev) for ev in events)
    golden = _golden_run(events)
    feeder = _Feeder(events, golden)

    breaker = CircuitBreaker(threshold=3, cooldown_s=0.4)
    cohort, members = _mk_cohort(n_streams, checkpoint_dir, ckpt_every,
                                 diff_snapshots=True)
    ex = CohortExecutor(cohort, batch_rows=8, queue_depth=256,
                        coalesce_s=0.0, breaker=breaker)
    cohort.warmup(8)
    injected = {"flaky": 0, "supervisor_faults": 0, "delays": 0,
                "poison": 0, "kills": 0}
    t_start = time.perf_counter()

    def pump(frac):
        target = int(frac * n_total)
        guard = 0
        while feeder.acked_total() < target:
            feeder.round(ex, members)
            guard += 1
            assert guard < 10_000, "campaign feeder stopped progressing"

    # -- phase 1: clean traffic ---------------------------------------
    pump(0.15)

    # -- phase 2: flaky dispatches — the whole round fails, the feeder
    # retries, nothing is lost and nothing reorders
    with faults.FaultInjector() as fi:
        fi.flaky(StreamCohort, "dispatch", failures=2)
        pump(0.30)
        injected["flaky"] = sum(r.action == "raise" for r in fi.records)
    assert injected["flaky"] >= 2
    assert feeder.outcomes["injected"] >= 1

    # -- phase 3: a plane-level fault (escapes the worker loop, not a
    # ticket) — the supervisor fails the in-flight group and restarts
    # the drain thread; the plane keeps serving
    with faults.FaultInjector() as fi:
        fi.flaky(CohortExecutor, "_split", failures=1)
        pump(0.40)
        injected["supervisor_faults"] = sum(
            r.action == "raise" for r in fi.records)
    assert ex.restarts >= 1, "supervisor never restarted the drain"

    # -- phase 4: latency injection vs a short deadline.  Half the
    # fleet dispatches behind an injected sleep; the other half is
    # submitted with a budget strictly under it, dies IN THE QUEUE with
    # the stage-named error, and is retried after the delay clears.
    half = [s for s in range(n_streams) if feeder.pending(s)][:n_streams // 2]
    rest = [s for s in range(n_streams)
            if feeder.pending(s) and s not in half]
    with faults.FaultInjector() as fi:
        fi.delay_on_call(StreamCohort, "dispatch", seconds=delay_s,
                         call_no=1)
        a_list = [s for s in half if feeder.pending(s)]
        a_tickets = ex.submit_many([feeder.tick_of(s, members)
                                    for s in a_list])
        # wait until the delayed dispatch has STARTED, then queue the
        # doomed half behind it
        t0 = time.perf_counter()
        while not any(r.action == "delay" for r in fi.records):
            assert time.perf_counter() - t0 < 30, "delay never fired"
            time.sleep(0.002)
        retry_b = feeder.round(ex, members, streams=rest,
                               deadline=delay_deadline_s)
        feeder.settle(a_list, a_tickets)
        injected["delays"] = sum(r.action == "delay" for r in fi.records)
    assert feeder.outcomes["deadline"] >= 1, (
        "latency injection produced no DeadlineExceeded")
    if retry_b:              # nothing was folded: the retries must land
        feeder.round(ex, members, streams=retry_b)
    pump(0.55)

    # -- phase 5: poison-pill member -> quarantine -> half-open probe.
    # Three consecutive bad ticks (unknown series) open the member's
    # circuit; the next tick fails FAST with QuarantinedError; after
    # the cooldown one probe (a real event) closes it again.
    poison = members[0]
    bad = ("right", poison, "no-such-series", 1, {"px": np.float32(1)},
           None)
    for _ in range(3):
        (bad_ticket,) = ex.submit_many([bad])
        try:
            bad_ticket.result(timeout=RESULT_TIMEOUT_S)
            raise AssertionError("poison tick unexpectedly succeeded")
        except ValueError:
            pass
    injected["poison"] = 3
    assert breaker.state(poison.name) == "open"
    assert feeder.pending(0), "campaign sizing: stream 0 ran dry early"
    (q_ticket,) = ex.submit_many([feeder.tick_of(0, members)])
    try:
        q_ticket.result(timeout=RESULT_TIMEOUT_S)
        raise AssertionError("quarantined member's tick ran")
    except QuarantinedError:
        feeder.outcomes["quarantined"] += 1
    time.sleep(breaker.cooldown_s + 0.05)
    feeder.round(ex, members, streams=[0])      # the half-open probe
    assert breaker.state(poison.name) == "closed", (
        "half-open probe did not close the circuit")
    pump(0.75)

    # -- phase 6: SimulatedKill mid-dispatch — the plane dies, every
    # outstanding ticket resolves with ShutdownError, and failover is
    # resume-from-chain + replay of the unacked tails
    with faults.FaultInjector() as fi:
        fi.kill_on_call(StreamCohort, "dispatch", call_no=1)
        live = [s for s in range(n_streams) if feeder.pending(s)]
        tickets = ex.submit_many([feeder.tick_of(s, members)
                                  for s in live])
        retry = feeder.settle(live, tickets)
        assert any(r.action == "kill" for r in fi.records)
        injected["kills"] = 1
    assert retry, "the killed dispatch should have failed its tickets"
    assert ex.fatal is not None
    restarts_pre_kill = ex.restarts
    t_rec = time.perf_counter()
    ex.close(timeout=5.0)

    ex = CohortExecutor.resume(checkpoint_dir, batch_rows=8,
                               queue_depth=256, coalesce_s=0.0,
                               breaker=breaker, ckpt_every=ckpt_every,
                               diff_snapshots=True)
    cohort = ex.cohort
    members = [cohort.stream(f"u{s}") for s in range(n_streams)]
    # the snapshot's acked cursors say where each stream's source
    # restarts; successfully-emitted events past the snapshot REPLAY
    # (their bytes must come out identical — checked by the audit)
    replayed = 0
    for s in range(n_streams):
        acked = cohort.stream(f"u{s}").acked
        assert acked <= feeder.pos[s], (s, acked, feeder.pos[s])
        replayed += feeder.pos[s] - acked
        feeder.pos[s] = acked
    cohort.warmup(8)
    recovery_s = time.perf_counter() - t_rec
    assert recovery_s <= recovery_bound_s, (
        f"recovery took {recovery_s:.1f}s (bound {recovery_bound_s}s)")

    # -- phase 7: replay + finish with ZERO new builds
    builds0 = profiling.plan_cache_stats()["builds"]
    pump(1.0)
    builds1 = profiling.plan_cache_stats()["builds"]
    assert builds1 == builds0, (
        f"post-recovery steady state recompiled: builds went "
        f"{builds0} -> {builds1}")
    wall = time.perf_counter() - t_start
    ex.close(timeout=30.0)

    checked = feeder.audit_tails()

    # snapshot economics: every artifact on disk, split full vs diff
    from tempo_tpu import checkpoint as ckpt
    full_b, diff_b = [], []
    for _, path in ckpt.list_steps(checkpoint_dir):
        mode = StreamCohort._snapshot_mode(path)["mode"]
        (diff_b if mode == "differential" else full_b).append(_du(path))
    assert full_b and diff_b, (
        f"campaign wrote no full+diff chain: {len(full_b)} fulls, "
        f"{len(diff_b)} diffs under {checkpoint_dir!r}")
    # dirty-bucket economics: once the cold streams' bucket went quiet,
    # an incremental snapshot stopped carrying it — at least one diff
    # is strictly smaller than every full artifact
    assert min(diff_b) < min(full_b), (full_b, diff_b)
    assert feeder.resolved >= n_total
    return {
        "ticks_per_sec": round(feeder.outcomes["ok"] / wall, 1),
        "n_streams": n_streams,
        "n_events": n_total,
        "outcomes": dict(feeder.outcomes),
        "injected": injected,
        "restarts": restarts_pre_kill + ex.restarts,
        "recovery_s": round(recovery_s, 3),
        "replayed_ticks": replayed,
        "zero_builds_after_recovery": True,
        "no_hung_tickets": True,
        "snapshot_bytes": {
            "full": full_b, "diff": diff_b,
            "diff_vs_full": (round(min(diff_b) / max(full_b), 3)
                             if full_b and diff_b else None)},
        "tail_audit": (f"all {n_streams} streams bitwise vs uninjected "
                       f"twin ({checked} emissions, replay included)"),
    }


# ----------------------------------------------------------------------
# The query-service campaign
# ----------------------------------------------------------------------

def run_service_campaign(*, n_queries: int = 12, seed: int = 5,
                         delay_s: float = 0.4,
                         deadline_s: float = 0.1) -> dict:
    """Chaos campaign for the query-service plane: a poison-pill plan
    signature driven into quarantine (and probed half-open), a worker
    killed by a plane-level fault (supervised restart), a delayed
    execution that expires a queued query's deadline by stage name,
    and a cancellation that never reaches a worker — while a good
    tenant's queries keep completing.  Single worker: the scheduling
    is then deterministic."""
    import pandas as pd

    from tempo_tpu import TSDF
    from tempo_tpu.plan import executor as plan_executor
    from tempo_tpu.plan import ir
    from tempo_tpu.service import QueryService, lazy_frame
    from tempo_tpu.service.service import QueryService as _QS

    rng = np.random.default_rng(seed)
    n = 256
    frame = TSDF(pd.DataFrame({
        "sym": np.repeat(np.arange(4), n // 4),
        "event_ts": np.tile(np.arange(n // 4, dtype=np.int64), 4),
        "x": rng.standard_normal(n),
    }), "event_ts", ["sym"])
    good = lambda: lazy_frame(frame).EMA("x", exact=True)
    poison_root = ir.Node("chaos_poison")        # unknown op: always raises

    breaker = CircuitBreaker(threshold=3, cooldown_s=0.4)
    svc = QueryService(workers=1, breaker=breaker)
    outcomes = {"ok": 0, "poison_failed": 0, "quarantined": 0,
                "deadline": 0, "cancelled": 0}

    # steady traffic for the good tenant
    for _ in range(n_queries // 2):
        svc.submit("good", good()).result(timeout=RESULT_TIMEOUT_S)
        outcomes["ok"] += 1

    # -- poison signature -> quarantine -> half-open probe ------------
    sig = ir.signature(poison_root)
    for _ in range(3):
        t = svc.submit("evil", poison_root)
        try:
            t.result(timeout=RESULT_TIMEOUT_S)
            raise AssertionError("poison query unexpectedly succeeded")
        except ValueError:
            outcomes["poison_failed"] += 1
    assert breaker.state(sig) == "open"
    try:
        svc.submit("evil", poison_root)
        raise AssertionError("quarantined signature was admitted")
    except QuarantinedError:
        outcomes["quarantined"] += 1
    time.sleep(breaker.cooldown_s + 0.05)
    probe = svc.submit("evil", poison_root)      # the half-open probe
    try:
        probe.result(timeout=RESULT_TIMEOUT_S)
    except ValueError:
        outcomes["poison_failed"] += 1
    assert breaker.state(sig) == "open"          # failed probe re-opens

    # -- plane-level fault: the scheduler loop dies, the supervisor
    # restarts the worker and service continues
    with faults.FaultInjector() as fi:
        fi.flaky(_QS, "_pick", failures=1)
        t = svc.submit("good", good())
        t.result(timeout=RESULT_TIMEOUT_S)
        outcomes["ok"] += 1
        assert any(r.action == "raise" for r in fi.records)
    assert svc.restarts >= 1, "service supervisor never restarted"

    # -- delayed execution vs a queued query's deadline + cancel ------
    with faults.FaultInjector() as fi:
        fi.delay_on_call(plan_executor, "execute", seconds=delay_s,
                         call_no=1)
        slow = svc.submit("good", good())
        t0 = time.perf_counter()
        while not any(r.action == "delay" for r in fi.records):
            assert time.perf_counter() - t0 < 30, "delay never fired"
            time.sleep(0.002)
        doomed = svc.submit("good", good(), deadline_s=deadline_s)
        victim = svc.submit("good", good())
        assert victim.cancel(), "queued query was not cancellable"
        try:
            victim.result(timeout=RESULT_TIMEOUT_S)
            raise AssertionError("cancelled query returned a result")
        except Cancelled:
            outcomes["cancelled"] += 1
        try:
            doomed.result(timeout=RESULT_TIMEOUT_S)
            raise AssertionError("deadline query returned a result")
        except DeadlineExceeded as e:
            assert e.stage in ("admission queue", "dispatch"), e.stage
            outcomes["deadline"] += 1
        slow.result(timeout=RESULT_TIMEOUT_S)    # the delayed one lands
        outcomes["ok"] += 1

    # the plane still serves after the whole gauntlet
    for _ in range(n_queries // 2):
        svc.submit("good", good()).result(timeout=RESULT_TIMEOUT_S)
        outcomes["ok"] += 1
    st = svc.stats()
    svc.close(timeout=30.0)
    assert st["tenants"]["good"]["completed"] == outcomes["ok"]
    return {
        "outcomes": outcomes,
        "restarts": st["restarts"],
        "breaker": st["breaker"],
        "good_tenant_completed": st["tenants"]["good"]["completed"],
        "no_hung_tickets": True,
    }


def run_campaign(checkpoint_dir: str, *, n_streams: int = 12,
                 events_per_stream: int = 24, seed: int = 17,
                 recovery_bound_s: float = 60.0) -> dict:
    """Both planes, one report — the body of bench config 15
    (``--only-chaos-serving``)."""
    serving = run_serving_campaign(
        checkpoint_dir, n_streams=n_streams,
        events_per_stream=events_per_stream, seed=seed,
        recovery_bound_s=recovery_bound_s)
    service = run_service_campaign(seed=seed + 1)
    serving["service"] = service
    return serving


# ----------------------------------------------------------------------
# The batch-pipeline campaign (bench config 16)
# ----------------------------------------------------------------------

def make_parquet_dataset(path: str, *, n_rows: int, n_keys: int,
                         seed: int, n_files: int = 4,
                         row_group_rows: Optional[int] = None) -> str:
    """A real multi-file, multi-row-group Parquet dataset (columns:
    symbol, event_ts, px, qty) — several row groups per file so the
    corruption injections have sibling groups to leave intact."""
    import pandas as pd
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(seed)
    per = max(1, n_rows // n_files)
    rg = row_group_rows or max(64, per // 4)
    for i in range(n_files):
        df = pd.DataFrame({
            "symbol": rng.choice([f"s{k:03d}" for k in range(n_keys)], per),
            "event_ts": pd.to_datetime(
                (np.sort(rng.integers(0, 10 ** 6, per))
                 + np.int64(i) * 10 ** 6) * 1_000_000_000),
            "px": rng.standard_normal(per),
            "qty": rng.integers(1, 9, per).astype(float),
        })
        pq.write_table(pa.Table.from_pandas(df, preserve_index=False),
                       os.path.join(path, f"part-{i}.parquet"),
                       row_group_size=rg)
    return path


def _df_crc(df) -> int:
    """CRC-32 of a DataFrame's raw column bytes (sorted column order;
    object columns via their UTF-8 reprs) — the bitwise fingerprint
    the slab digests chain, so 'digest equal' means 'every byte of
    every slab's full output equal'."""
    c = 0
    for col in sorted(df.columns):
        arr = df[col].to_numpy()
        if arr.dtype == object:
            arr = arr.astype(str).astype("S")
        c = zlib.crc32(np.ascontiguousarray(arr).tobytes(), c)
    return c & 0xFFFFFFFF


def _sorted_df(frame_out):
    return frame_out.df.sort_values(
        ["symbol", "event_ts"], kind="stable").reset_index(drop=True)


def run_pipeline_campaign(workdir: str, *, rows_total: int = 360_000,
                          physical_rows: int = 60_000,
                          n_keys: int = 24, seed: int = 29,
                          n_windows: int = 3,
                          ckpt_every: int = 2,
                          recovery_bound_s: float = 120.0) -> dict:
    """The batch-plane chaos campaign — Parquet → resumable OOC ingest
    → mesh → planned streaming AS-OF join + packed range stats, driven
    to ``rows_total`` cumulative rows through the out-of-core slab
    sweep, under a kill/flaky/corrupt schedule.  Asserted HARD (a
    violation raises and nulls bench config 16):

    * a mid-file ingest kill resumes from the per-shard progress
      manifest: completed shards are NOT re-read, and the resumed
      frame is bitwise-identical to a fresh ingest;
    * a foreign resume directory / a foreign checkpoint signature is
      refused by name (``CheckpointError``), never silently restored;
    * a corrupt row group and a torn-write file are quarantined with
      the exact ranges named (``CorruptRowGroupError`` in raise mode);
      a flapping file trips its circuit breaker and is quarantined
      instead of burning the pass's retry budget;
    * the end-to-end ingest deadline dies with a STAGE-named
      ``DeadlineExceeded``;
    * a kill mid-chain between plan-placed checkpoint barriers
      resumes from the newest intact signed barrier: ONLY the ops
      above it re-run, ZERO new executables are built, and the final
      frame is bitwise-identical to the uninjected eager twin;
    * the slab sweep (``run_resumable`` over the same signed-barrier
      machinery) killed mid-run resumes from the newest barrier,
      replays only post-barrier slabs with ZERO new executable
      builds, and its final digest — the per-slab CRCs of every
      slab's FULL collected output — is bitwise-identical to an
      uninjected twin sweep's.
    """
    import glob
    import shutil

    import pandas as pd

    from tempo_tpu import TSDF, checkpoint, resilience
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.io import ingest
    from tempo_tpu.parallel.mesh import make_mesh
    from tempo_tpu.plan import checkpoints as plan_ckpt
    from tempo_tpu.service import lazy_frame

    t_start = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    half = physical_rows // 2
    left_path = make_parquet_dataset(
        os.path.join(workdir, "left"), n_rows=half, n_keys=n_keys,
        seed=seed)
    right_path = make_parquet_dataset(
        os.path.join(workdir, "right"), n_rows=half, n_keys=n_keys,
        seed=seed + 1)
    import jax

    n_shards = min(8, jax.device_count())
    mesh = make_mesh({"series": n_shards})
    ingest_kw = dict(ts_col="event_ts", partition_cols=["symbol"],
                     mesh=mesh, batch_rows=1 << 14)

    # -- phase 1: transactional ingest — kill mid-stream, resume from
    # the per-shard progress manifest, no completed shard re-read.
    # Needs >= 2 shards so at least one commits before the kill; a
    # 1-device backend records the phase as skipped instead of
    # asserting a kill that can never land
    resume_dir = os.path.join(workdir, "ingest_resume")
    ingest_kill = n_shards >= 2
    committed = restreamed = 0
    if ingest_kill:
        kill_shard = min(max(1, n_shards // 2), n_shards - 1)
        with faults.FaultInjector() as fi:
            fi.kill_on_call(ingest, "_stream_shard",
                            call_no=kill_shard + 1)
            try:
                ingest.from_parquet(left_path, resume_dir=resume_dir,
                                    **ingest_kw)
                raise AssertionError("ingest kill never fired")
            except faults.SimulatedKill:
                pass
        committed = len(glob.glob(os.path.join(resume_dir,
                                               "shard_*.json")))
        assert committed >= kill_shard, (committed, kill_shard)
        with faults.FaultInjector() as fi:
            fi.flaky(ingest, "_stream_shard", failures=0)  # call counter
            left_f = ingest.from_parquet(left_path,
                                         resume_dir=resume_dir,
                                         **ingest_kw)
            restreamed = len(fi.records)
        assert restreamed == n_shards - committed, (
            f"resume re-read committed shards: {restreamed} streamed, "
            f"{committed} were committed of {n_shards}")
    else:
        left_f = ingest.from_parquet(left_path, resume_dir=resume_dir,
                                     **ingest_kw)
    fresh = ingest.from_parquet(left_path, **ingest_kw)
    pd.testing.assert_frame_equal(
        _sorted_df(left_f.collect()), _sorted_df(fresh.collect()),
        check_exact=True)
    del fresh
    # foreign resume refusal: same dir, different mesh shape.  On a
    # 1-device backend there is no second mesh shape to probe with —
    # the phase is recorded as None (skipped), never a false failure
    foreign_refused = {"ingest": None if n_shards == 1 else False,
                       "plan": False, "sweep": False}
    if n_shards > 1:
        try:
            ingest.from_parquet(
                left_path, resume_dir=resume_dir, ts_col="event_ts",
                partition_cols=["symbol"],
                mesh=make_mesh({"series": max(1, n_shards // 2)}),
                batch_rows=1 << 14)
            raise AssertionError("foreign ingest resume was admitted")
        except CheckpointError:
            foreign_refused["ingest"] = True
    right_f = ingest.from_parquet(right_path, **ingest_kw)

    # -- phase 2: corrupt row group + torn write -> quarantine with
    # the exact ranges named; raise mode surfaces ONE named error
    qdir = os.path.join(workdir, "corrupt_ds")
    shutil.copytree(right_path, qdir)
    rec = faults.corrupt_parquet_row_group(
        os.path.join(qdir, "part-1.parquet"), row_group=1)
    try:
        ingest.from_parquet(qdir, **ingest_kw)
        raise AssertionError("corrupt row group was ingested silently")
    except ingest.CorruptRowGroupError as e:
        assert any(r["row_group"] == rec["row_group"]
                   and r["file"].endswith("part-1.parquet")
                   for r in e.ranges), e.ranges
    faults.tear_parquet_footer(os.path.join(qdir, "part-2.parquet"))
    q_frame = ingest.from_parquet(qdir, on_corrupt="quarantine",
                                  **ingest_kw)
    q_ranges = list(q_frame.ingest_quarantined)
    assert any(r["row_group"] == rec["row_group"] for r in q_ranges)
    assert any(r["file"].endswith("part-2.parquet")
               and r["row_group"] is None for r in q_ranges), q_ranges
    clean_rows = int(right_f.collect().df.shape[0])
    q_rows = int(q_frame.collect().df.shape[0])
    assert q_rows < clean_rows
    del q_frame

    # -- phase 3: the end-to-end ingest deadline dies stage-named
    try:
        ingest.from_parquet(left_path, deadline_s=1e-6, **ingest_kw)
        raise AssertionError("ingest deadline never fired")
    except DeadlineExceeded as e:
        assert e.stage, "DeadlineExceeded carried no stage name"
        deadline_stage = e.stage

    # -- phase 4: flapping file -> circuit breaker -> quarantined
    # instead of burning the whole retry budget
    flap_path = os.path.join(left_path, "part-1.parquet")
    flap_breaker = CircuitBreaker(threshold=2, cooldown_s=600.0)
    orig_scan = ingest._scan_fragment

    def _flapping_scan(frag, *a, **k):
        if getattr(frag, "path", "") == flap_path:
            raise faults.InjectedFault(
                f"flapping network read at {flap_path}")
        return orig_scan(frag, *a, **k)

    ingest._scan_fragment = _flapping_scan
    try:
        flap_frame = ingest.from_parquet(
            left_path, on_corrupt="quarantine", breaker=flap_breaker,
            **ingest_kw)
    finally:
        ingest._scan_fragment = orig_scan
    flap_q = [r for r in flap_frame.ingest_quarantined
              if "circuit" in r["reason"]]
    assert flap_q and flap_q[0]["file"] == flap_path, (
        flap_frame.ingest_quarantined)
    assert flap_breaker.stats()["trips"] >= 1
    del flap_frame

    # -- phase 5: plan-integrated checkpoint barriers — kill mid-chain,
    # resume from the newest intact signed barrier
    def chain():
        return (lazy_frame(left_f)
                .asofJoin(lazy_frame(right_f), right_prefix="q",
                          skipNulls=False)
                .withRangeStats(colsToSummarize=["q_px", "q_qty"],
                                rangeBackWindowSecs=60)
                .EMA("q_px", exact=True))

    eager_golden = _sorted_df(
        left_f.asofJoin(right_f, right_prefix="q", skipNulls=False)
        .withRangeStats(colsToSummarize=["q_px", "q_qty"],
                        rangeBackWindowSecs=60)
        .EMA("q_px", exact=True).collect())
    plan_dir = os.path.join(workdir, "plan_ckpt")
    with faults.FaultInjector() as fi:
        fi.kill_on_call(np, "savez", call_no=2)     # dies saving barrier 2
        try:
            with plan_ckpt.checkpointed(plan_dir, every=1):
                chain().collect()
            raise AssertionError("plan-barrier kill never fired")
        except faults.SimulatedKill:
            pass
    assert checkpoint.latest(plan_dir).endswith("step_00001")
    t_rec = time.perf_counter()
    builds0 = profiling.plan_cache_stats()["builds"]
    with faults.FaultInjector() as fi:
        fi.flaky(DistributedTSDF, "asofJoin", failures=0)
        fi.flaky(DistributedTSDF, "withRangeStats", failures=0,
                 label="stats")
        with plan_ckpt.checkpointed(plan_dir, every=1):
            resumed = _sorted_df(chain().collect())
        join_calls = sum(r.target != "stats" for r in fi.records)
        stats_calls = sum(r.target == "stats" for r in fi.records)
    builds1 = profiling.plan_cache_stats()["builds"]
    assert join_calls == 0, (
        f"resume re-ran the pre-barrier join ({join_calls} call(s))")
    assert stats_calls == 1, stats_calls
    assert builds1 == builds0, (
        f"plan-barrier resume recompiled: builds {builds0}->{builds1}")
    pd.testing.assert_frame_equal(resumed, eager_golden,
                                  check_exact=True)
    plan_recovery_s = time.perf_counter() - t_rec
    barriers = sorted(s for s, _ in checkpoint.list_steps(plan_dir))
    assert barriers == [1, 2, 3], barriers
    # foreign plan refusal: a longer chain against the same barrier dir
    try:
        with plan_ckpt.checkpointed(plan_dir, every=1):
            chain().EMA("q_qty", exact=True).collect()
        raise AssertionError("foreign plan resume was admitted")
    except CheckpointError:
        foreign_refused["plan"] = True

    # -- phase 6: the out-of-core slab sweep to rows_total, killed
    # mid-run and resumed via run_resumable (the eager wrapper over
    # the same signed-barrier machinery)
    slab_rows = int(left_f.collect().df.shape[0]
                    + right_f.collect().df.shape[0])
    n_slabs = max(2, -(-rows_total // slab_rows))
    windows = [30.0 + 15.0 * i for i in range(max(1, n_windows))]
    kill_at = max(len(windows) + 1, int(n_slabs * 0.6))
    if kill_at % ckpt_every == 0:
        # never kill exactly ON a barrier: the campaign must prove the
        # REPLAY of the slabs between the newest barrier and the kill
        kill_at += 1
    kill_at = min(kill_at, n_slabs - 1)
    if kill_at % ckpt_every == 0:       # the clamp landed on a barrier
        kill_at -= 1

    def digest_seed():
        return TSDF(pd.DataFrame({
            "event_ts": pd.to_datetime([0]),
            "slab": np.int64([-1]),
            "out_crc": np.int64([0]),
            "out_rows": np.int64([0]),
        }), "event_ts", [])

    def make_steps(ran: List[int], kill_slab: Optional[int] = None):
        killed = {"done": False}

        def mk(k):
            w = windows[k % len(windows)]

            def step(digest):
                if k == kill_slab and not killed["done"]:
                    killed["done"] = True
                    raise faults.SimulatedKill(
                        f"simulated kill at slab {k}")
                ran.append(k)
                out = (lazy_frame(left_f)
                       .asofJoin(lazy_frame(right_f), right_prefix="q",
                                 skipNulls=False)
                       .withRangeStats(
                           colsToSummarize=["q_px", "q_qty"],
                           rangeBackWindowSecs=w)
                       .collect())
                df = _sorted_df(out)
                row = pd.DataFrame({
                    "event_ts": pd.to_datetime([(k + 1) * 10 ** 9]),
                    "slab": np.int64([k]),
                    "out_crc": np.int64([_df_crc(df)]),
                    "out_rows": np.int64([len(df)]),
                })
                return TSDF(
                    pd.concat([digest.df, row], ignore_index=True),
                    "event_ts", [])

            step.__name__ = f"slab{k}"
            return step

        return [mk(k) for k in range(n_slabs)]

    sweep_dir = os.path.join(workdir, "sweep_ckpt")
    ran_killed: List[int] = []
    t_sweep = time.perf_counter()
    steps = make_steps(ran_killed, kill_slab=kill_at)
    try:
        resilience.run_resumable(digest_seed(), steps, sweep_dir,
                                 every=ckpt_every, keep_last=3)
        raise AssertionError("sweep kill never fired")
    except faults.SimulatedKill:
        pass
    assert ran_killed == list(range(kill_at)), ran_killed
    barrier_slab = (kill_at // ckpt_every) * ckpt_every
    t_rec2 = time.perf_counter()
    builds0 = profiling.plan_cache_stats()["builds"]
    ran_resume: List[int] = []
    digest = resilience.run_resumable(
        digest_seed(), make_steps(ran_resume), sweep_dir,
        every=ckpt_every, keep_last=3)
    builds_resume = profiling.plan_cache_stats()["builds"] - builds0
    sweep_recovery_s = time.perf_counter() - t_rec2
    assert ran_resume == list(range(barrier_slab, n_slabs)), (
        f"resume re-ran pre-barrier slabs: {ran_resume[:4]}... "
        f"(barrier at {barrier_slab})")
    assert kill_at > barrier_slab, (
        "campaign sizing bug: the kill landed on a barrier, so no "
        "slab replay was exercised")
    assert builds_resume == 0, (
        f"sweep resume built {builds_resume} new executable(s); every "
        f"window was compiled before the kill")
    sweep_wall = time.perf_counter() - t_sweep
    assert sweep_recovery_s <= recovery_bound_s, (
        f"sweep recovery took {sweep_recovery_s:.1f}s "
        f"(bound {recovery_bound_s}s)")

    # the uninjected twin (runs entirely on cached executables)
    ran_golden: List[int] = []
    golden = resilience.run_resumable(
        digest_seed(), make_steps(ran_golden),
        os.path.join(workdir, "sweep_golden"), every=ckpt_every,
        keep_last=3)
    assert ran_golden == list(range(n_slabs))
    pd.testing.assert_frame_equal(digest.df.reset_index(drop=True),
                                  golden.df.reset_index(drop=True),
                                  check_exact=True)
    # foreign sweep refusal: a different-length pipeline, same dir
    try:
        resilience.run_resumable(
            digest_seed(), make_steps([])[: n_slabs - 1], sweep_dir,
            every=ckpt_every, keep_last=3)
        raise AssertionError("foreign sweep resume was admitted")
    except CheckpointError:
        foreign_refused["sweep"] = True

    rows_driven = slab_rows * n_slabs
    wall = time.perf_counter() - t_start
    assert all(v for v in foreign_refused.values()
               if v is not None), foreign_refused
    return {
        "rows_per_sec": round(rows_driven / sweep_wall, 1),
        "rows_total": rows_driven,
        "physical_rows": physical_rows,
        "n_slabs": n_slabs,
        "slab_rows": slab_rows,
        "wall_s": round(wall, 1),
        "ingest_resume": {
            "kill": ingest_kill,
            "shards_total": n_shards,
            "shards_committed_before_kill": committed,
            "shards_restreamed_on_resume": restreamed,
            "reread_committed_shards": 0,
            "value_audit": "resumed ingest bitwise == fresh ingest "
                           "(assert_frame_equal check_exact)",
        },
        "quarantine": {
            "named_error": True,
            "corrupt_row_group": {"file": "part-1.parquet",
                                  "row_group": rec["row_group"],
                                  "rows": rec["rows"]},
            "torn_footer_file_quarantined": True,
            "rows_kept": q_rows,
            "rows_clean": clean_rows,
        },
        "ingest_deadline_stage": deadline_stage,
        "flapping_file": {
            "breaker_tripped": True,
            "quarantined": os.path.basename(flap_path),
        },
        "plan_barriers": {
            "placed": len(barriers),
            "resume_from_step": 1,
            "pre_barrier_ops_rerun": join_calls,
            "post_barrier_ops_rerun": stats_calls,
            "zero_builds_after_resume": True,
            "recovery_s": round(plan_recovery_s, 3),
            "value_audit": "resumed planned chain bitwise == "
                           "uninjected eager twin",
        },
        "sweep": {
            "killed_at_slab": kill_at,
            "resumed_from_barrier_slab": barrier_slab,
            "replayed_slabs": kill_at - barrier_slab,
            "new_slabs_after_kill": n_slabs - kill_at,
            "builds_after_resume": builds_resume,
            "recovery_s": round(sweep_recovery_s, 3),
        },
        "foreign_signature_refused": foreign_refused,
        "no_silent_restores": True,
        "tail_audit": (
            f"digest of all {n_slabs} slabs (per-slab CRC-32 of the "
            f"FULL collected output bytes) bitwise == uninjected twin; "
            f"plan-barrier resume bitwise == eager twin"),
    }


# ----------------------------------------------------------------------
# Storage-engine chaos (bench config 17)
# ----------------------------------------------------------------------

def run_store_campaign(workdir: str, *, rows: int = 20_000,
                       n_keys: int = 8, seed: int = 31,
                       segment_rows: int = 2_000,
                       n_streams: int = 24, resident_budget: int = 6,
                       events_per_stream: int = 14) -> dict:
    """The storage-plane chaos campaign — transactional clustered
    write-back, background compaction, and the tiered cohort-state
    spill, under a kill/corrupt schedule.  Asserted HARD (a violation
    raises and nulls bench config 17):

    * a mid-write kill resumes the staged generation with ZERO
      committed-segment re-writes (segment writes are call-counted),
      and the resumed table is bitwise-identical to an uninjected
      fresh write of the same frame; a kill between the commit record
      and the pointer swing resumes with zero segment writes at all;
    * while a write is staged or killed, readers see EXACTLY the old
      generation — and a foreign resume frame, a torn commit record, a
      corrupt pointer, and a corrupt committed segment are each
      refused BY NAME (classified PERMANENT / CORRUPTED_ARTIFACT,
      never transient);
    * the legacy ``io.writer.write`` overwrite survives kills at every
      stage: mid-build, mid-fsync, and BETWEEN the two swap renames
      (the old table is readable at every probe — the seed-era
      rmtree-then-rewrite data-loss window is gone);
    * a compaction kill leaves the table at exactly generation N; the
      re-issued compaction commits N+1; a reader holding N's dataset
      path stays bitwise-correct after N+1 commits (never a blend);
    * an over-memory cohort sweep (``resident_budget`` slots for
      ``n_streams`` streams under Poisson load) spills cold members to
      CRC'd artifacts and faults them back in on their next tick, with
      the FULL per-tick emission history bitwise-identical to a
      never-spilled twin; corrupt and foreign member artifacts are
      refused by name, rejecting only their own member's ticks.
    """
    import shutil

    import pandas as pd

    from tempo_tpu import resilience
    from tempo_tpu.io import writer
    from tempo_tpu.store import engine as store_engine
    from tempo_tpu.store.compact import compact as store_compact
    from tempo_tpu.resilience import FailureKind

    t_start = time.perf_counter()
    os.makedirs(workdir, exist_ok=True)
    rng = np.random.default_rng(seed)
    wh = os.path.join(workdir, "warehouse")
    store = store_engine.Store(wh)

    def mk_df(salt: float) -> "pd.DataFrame":
        r = np.random.default_rng(seed + int(salt * 1000))
        return pd.DataFrame({
            "symbol": r.choice([f"s{k:03d}" for k in range(n_keys)],
                               rows),
            "event_ts": pd.to_datetime(
                np.sort(r.integers(0, 10 ** 6, rows)) * 1_000_000_000),
            "px": r.standard_normal(rows),
        })

    def sorted_twin(df):
        return df.sort_values(["symbol"], kind="stable").reset_index(
            drop=True)

    # -- phase 1: write kill -> resume, zero committed re-writes ------
    df0 = mk_df(0.0)
    store.write_table("orders", df0, ["symbol"], source_fp="base",
                      segment_rows=segment_rows)
    df1 = mk_df(1.0)
    n_segments = -(-rows // segment_rows)
    kill_at = max(2, n_segments // 2)
    try:
        with faults.FaultInjector().kill_on_call(
                store_engine, "_write_segment", call_no=kill_at):
            store.write_table("orders", df1, ["symbol"],
                              source_fp="v1", segment_rows=segment_rows)
        raise AssertionError("write kill did not land")
    except faults.SimulatedKill:
        pass
    # killed mid-write: readers still see the OLD generation, bitwise
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  sorted_twin(df0))
    with faults.FaultInjector().flaky(
            store_engine, "_write_segment", failures=0) as fi:
        stats = store.write_table("orders", df1, ["symbol"],
                                  source_fp="v1",
                                  segment_rows=segment_rows)
    rewrites = len(fi.records)
    assert stats["resumed"] and stats["segments_rewritten"] == 0
    assert stats["segments_reused"] == kill_at - 1, stats
    assert rewrites == n_segments - (kill_at - 1), (rewrites, stats)
    # bitwise vs an uninjected fresh write of the same frame
    fresh = store_engine.Store(os.path.join(workdir, "wh_twin"))
    fresh.write_table("orders", df1, ["symbol"], source_fp="v1",
                      segment_rows=segment_rows)
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  fresh.read("orders", verify=True))
    # kill AFTER the commit record, before the pointer swing: the
    # re-issue verifies + swings with ZERO segment writes
    df2 = mk_df(2.0)
    try:
        with faults.FaultInjector().kill_on_call(
                store_engine, "_swing_pointer", call_no=1):
            store.write_table("orders", df2, ["symbol"],
                              source_fp="v2", segment_rows=segment_rows)
        raise AssertionError("pointer-swing kill did not land")
    except faults.SimulatedKill:
        pass
    pd.testing.assert_frame_equal(store.read("orders"),
                                  sorted_twin(df1))   # still v1
    with faults.FaultInjector().flaky(
            store_engine, "_write_segment", failures=0) as fi:
        stats2 = store.write_table("orders", df2, ["symbol"],
                                   source_fp="v2",
                                   segment_rows=segment_rows)
    assert len(fi.records) == 0, "post-commit resume rewrote segments"
    assert stats2["resumed"] and stats2["segments_reused"] == n_segments
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  sorted_twin(df2))

    # -- phase 2: refusal matrix (all BY NAME, correctly classified) --
    refusals: Dict[str, str] = {}
    df3 = mk_df(3.0)
    try:
        with faults.FaultInjector().kill_on_call(
                store_engine, "_write_segment", call_no=2):
            store.write_table("orders", df3, ["symbol"],
                              source_fp="v3", segment_rows=segment_rows)
    except faults.SimulatedKill:
        pass
    try:
        store.write_table("orders", mk_df(4.0), ["symbol"],
                          source_fp="OTHER",
                          segment_rows=segment_rows)
        raise AssertionError("foreign staged resume was admitted")
    except store_engine.StoreError as e:
        assert resilience.classify(e) is FailureKind.PERMANENT
        assert "DIFFERENT write" in str(e)
        refusals["foreign_staged_write"] = "PERMANENT"
    assert store.discard_staging("orders")
    gen, _ = store.current("orders")
    gen_dir = os.path.join(store.table_path("orders"), gen)
    commit_path = os.path.join(gen_dir, store_engine.COMMIT_NAME)
    blob = open(commit_path, "rb").read()
    with open(commit_path, "wb") as f:
        f.write(blob[: len(blob) // 2])          # torn commit record
    try:
        store.read("orders")
        raise AssertionError("torn commit record was admitted")
    except store_engine.StoreCommitError as e:
        k = resilience.classify(e)
        assert k is FailureKind.CORRUPTED_ARTIFACT, k
        refusals["torn_commit_record"] = "CORRUPTED_ARTIFACT"
    with open(commit_path, "wb") as f:
        f.write(blob)
    cur_path = os.path.join(store.table_path("orders"),
                            store_engine.CURRENT_NAME)
    cur_blob = open(cur_path, "rb").read()
    with open(cur_path, "wb") as f:
        f.write(b'{"generation": "gen_99999999", "commit_crc": 1}')
    try:
        store.read("orders")
        raise AssertionError("dangling pointer was admitted")
    except store_engine.StoreCommitError:
        refusals["corrupt_pointer"] = "CORRUPTED_ARTIFACT"
    with open(cur_path, "wb") as f:
        f.write(cur_blob)
    seg_path = os.path.join(gen_dir, store_engine._seg_name(0))
    seg_off = max(0, os.path.getsize(seg_path) // 2)
    faults.flip_byte(seg_path, offset=seg_off)
    try:
        store.read("orders", verify=True)
        raise AssertionError("corrupt committed segment passed verify")
    except store_engine.StoreCommitError as e:
        assert store_engine._seg_name(0) in str(e)
        refusals["corrupt_committed_segment"] = "CORRUPTED_ARTIFACT"
    faults.flip_byte(seg_path, offset=seg_off)   # XOR twice = restore
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  sorted_twin(df2))

    # -- phase 3: legacy writer overwrite survives every kill stage --
    from tempo_tpu.frame import TSDF
    base_dir = os.path.join(workdir, "legacy_wh")
    dfa = mk_df(5.0)
    dfb = mk_df(6.0)
    tsa = TSDF(dfa, ts_col="event_ts", partition_cols=["symbol"])
    tsb = TSDF(dfb, ts_col="event_ts", partition_cols=["symbol"])
    writer.write(tsa, "legacy", base_dir=base_dir, format="delta")
    old_px = np.sort(dfa.px.to_numpy())

    def legacy_survives(tag: str) -> None:
        got = writer.read("legacy", partition_cols=["symbol"],
                          base_dir=base_dir)
        np.testing.assert_array_equal(
            np.sort(got.df.px.to_numpy()), old_px,
            err_msg=f"old table lost after kill {tag}")

    survived = []
    try:                                     # kill mid-build
        with faults.FaultInjector().kill_on_call(
                writer, "_write_delta", call_no=1):
            writer.write(tsb, "legacy", base_dir=base_dir,
                         format="delta")
        raise AssertionError("mid-build kill did not land")
    except faults.SimulatedKill:
        pass
    legacy_survives("mid-build")
    survived.append("mid-build")
    try:                                     # kill mid-fsync
        with faults.FaultInjector().kill_on_call(
                writer, "_fsync_tree", call_no=1):
            writer.write(tsb, "legacy", base_dir=base_dir,
                         format="delta")
        raise AssertionError("mid-fsync kill did not land")
    except faults.SimulatedKill:
        pass
    legacy_survives("mid-fsync")
    survived.append("mid-fsync")
    try:                                     # kill BETWEEN the swaps
        with faults.FaultInjector().kill_on_call(
                writer.os, "replace", call_no=2):
            writer.write(tsb, "legacy", base_dir=base_dir,
                         format="delta")
        raise AssertionError("mid-swap kill did not land")
    except faults.SimulatedKill:
        pass
    legacy_survives("mid-swap (.bak fallback)")
    survived.append("mid-swap")
    writer.write(tsb, "legacy", base_dir=base_dir, format="delta")
    got = writer.read("legacy", partition_cols=["symbol"],
                      base_dir=base_dir)
    np.testing.assert_array_equal(np.sort(got.df.px.to_numpy()),
                                  np.sort(dfb.px.to_numpy()))

    # -- phase 4: compaction under live traffic, killed mid-merge ----
    gen_n, commit_n = store.current("orders")
    reader_path = store.dataset_path("orders")   # a live reader on N
    segs_before = len(commit_n["segments"])
    reader_df = store_engine.read_dataset_df(reader_path)
    try:
        with faults.FaultInjector().kill_on_call(
                store_engine, "_write_segment",
                call_no=1):
            store_compact("orders", base_dir=wh, min_segments=2)
        raise AssertionError("compaction kill did not land")
    except faults.SimulatedKill:
        pass
    # table is EXACTLY generation N (pointer untouched, reads bitwise)
    assert store.current("orders")[0] == gen_n
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  sorted_twin(df2))
    cstats = store_compact("orders", base_dir=wh, min_segments=2)
    gen_n1 = store.current("orders")[0]
    assert gen_n1 != gen_n and cstats["compacted_from"] == gen_n
    assert cstats["segments"] < segs_before
    # reader holding N's path is still bitwise-correct after N+1
    pd.testing.assert_frame_equal(
        store_engine.read_dataset_df(reader_path), reader_df)
    pd.testing.assert_frame_equal(store.read("orders", verify=True),
                                  sorted_twin(df2))

    # -- phase 5: over-memory cohort sweep under Poisson load --------
    from tempo_tpu.serve import StreamCohort

    events = make_events(rng, n_streams, events_per_stream,
                         left_frac=0.15)

    def mk(budget: int, tag: str) -> "StreamCohort":
        return StreamCohort(
            ("px",), window_secs=10.0, window_rows_bound=8,
            ema_alpha=0.2, max_lookback=16, slots=4,
            spill_dir=(os.path.join(workdir, f"spill_{tag}")
                       if budget else None),
            resident_budget=budget)

    def feed(cohort, record_lat: bool):
        for s in range(n_streams):
            cohort.add_stream(f"u{s}", ["s0"])
        history = [[] for _ in range(n_streams)]
        cold_lat, hot_lat = [], []
        pos = [0] * n_streams
        live = [s for s in range(n_streams) if events[s]]
        while live:
            nxt = []
            for s in live:
                kind, ts, val = events[s][pos[s]]
                m = cohort.stream(f"u{s}")
                was_cold = not m.resident
                t0 = time.perf_counter()
                if kind == "right":
                    r = m.push(["s0"], [ts],
                               {"px": np.float32(val)})
                else:
                    r = m.push_left(["s0"], [ts])
                dt = time.perf_counter() - t0
                if record_lat:
                    (cold_lat if was_cold else hot_lat).append(dt)
                history[s].append(
                    {k: np.asarray(v).copy() for k, v in r.items()})
                pos[s] += 1
                if pos[s] < len(events[s]):
                    nxt.append(s)
            live = nxt
        return history, cold_lat, hot_lat

    twin = mk(0, "never")
    golden, _, _ = feed(twin, record_lat=False)
    spill_t0 = time.perf_counter()
    cohort = mk(resident_budget, "lru")
    hist, cold_lat, hot_lat = feed(cohort, record_lat=True)
    spill_wall = time.perf_counter() - spill_t0
    st = cohort.spill_stats
    assert st["spills"] > 0 and st["restores"] > 0, st
    assert st["resident"] <= resident_budget, st
    for s in range(n_streams):
        assert len(hist[s]) == len(golden[s])
        for a, b in zip(hist[s], golden[s]):
            assert a.keys() == b.keys()
            for k in a:
                assert np.array_equal(a[k], b[k], equal_nan=True), \
                    (s, k)

    def p99(lat):
        return (round(float(np.percentile(lat, 99)) * 1e3, 3)
                if lat else None)

    # corrupt member artifact: refused by name, only ITS ticks fail
    victim = next(iter(cohort._spilled))
    art = cohort._spilled[victim]
    npzs = [os.path.join(art, f) for f in os.listdir(art)
            if f.endswith(".npz")]
    faults.flip_byte(npzs[0], offset=os.path.getsize(npzs[0]) // 2)
    try:
        cohort.stream(victim).push(["s0"], [np.int64(10 ** 15)],
                                   {"px": np.float32(1.0)})
        raise AssertionError("corrupt member artifact was admitted")
    except CheckpointError:
        refusals["corrupt_member_artifact"] = "CORRUPTED_ARTIFACT"
    resident_name = next(n for n, m in cohort._members.items()
                         if m.resident)
    r = cohort.stream(resident_name).push(
        ["s0"], [np.int64(10 ** 15)], {"px": np.float32(1.0)})
    assert r and not isinstance(r, Exception)
    # foreign member artifact (another member's state under this
    # member's path): refused by name
    others = [n for n in cohort._spilled if n != victim]
    shutil.rmtree(art)
    shutil.copytree(cohort._spilled[others[0]], art)
    try:
        cohort.stream(victim).push(["s0"], [np.int64(10 ** 15) + 1],
                                   {"px": np.float32(1.0)})
        raise AssertionError("foreign member artifact was admitted")
    except CheckpointError as e:
        assert "FOREIGN" in str(e)
        refusals["foreign_member_artifact"] = "PERMANENT"

    total_ticks = sum(len(h) for h in hist)
    wall = time.perf_counter() - t_start
    return {
        "rows": rows,
        "segments": n_segments,
        "wall_s": round(wall, 1),
        "write_resume": {
            "killed_at_segment": kill_at,
            "segments_reused": kill_at - 1,
            "segments_rewritten_committed": 0,
            "segments_written_on_resume": rewrites,
            "pointer_swing_resume_segment_writes": 0,
            "value_audit": "resumed write bitwise == uninjected "
                           "fresh write (assert_frame_equal "
                           "check_exact)",
        },
        "refusals_by_name": refusals,
        "legacy_overwrite": {
            "kills_survived": survived,
            "old_table_lost": False,
        },
        "compaction": {
            "killed_mid_merge": True,
            "state_after_kill": "generation N exactly",
            "segments_before": segs_before,
            "segments_after": cstats["segments"],
            "reader_on_old_generation": "bitwise after N+1 commit",
        },
        "cohort_spill": {
            "streams_registered": n_streams,
            "resident_budget": resident_budget,
            "ticks": total_ticks,
            "spills": st["spills"],
            "restores": st["restores"],
            "ticks_per_sec": round(total_ticks / spill_wall, 1),
            "cold_tick_p99_ms": p99(cold_lat),
            "hot_tick_p99_ms": p99(hot_lat),
            "value_audit": "full per-tick emission history bitwise "
                           "== never-spilled twin",
        },
        "no_silent_restores": True,
    }
