"""Fault-injection harness for chaos testing the resilience layer.

Spark pipelines get chaos coverage from the engine's own test rigs
(task kill, executor loss); tempo-tpu's equivalent is this module plus
the ``chaos``-marked test suite.  Three fault families, matching the
:class:`~tempo_tpu.resilience.FailureKind` taxonomy they exercise:

* **call-site faults** — :class:`FaultInjector` patches a function on a
  module/object for the duration of a ``with`` block and makes the
  first N calls fail (:meth:`FaultInjector.flaky`, transient-io),
  raises :class:`SimulatedKill` on the Nth call
  (:meth:`FaultInjector.kill_on_call`, modelling SIGKILL mid-save — it
  derives from ``BaseException`` precisely so retry wrappers, which
  catch ``Exception``, can never swallow it), or sleeps before the Nth
  call (:meth:`FaultInjector.delay_on_call`, the latency injection the
  deadline plane's chaos coverage drives deterministically);
* **artifact corruption** — :func:`corrupt_npz_array` (flip one byte
  inside a named npz member's data), :func:`flip_byte`,
  :func:`truncate_file` (a partially-flushed write);
* **crash residue** — :func:`make_stale_tmp` fabricates the ``<dir>.tmp``
  a hard-killed save leaves behind.

Every injection is recorded on ``FaultInjector.records`` so tests can
assert not just the outcome but that the fault actually fired.
"""

from __future__ import annotations

import dataclasses
import errno
import functools
import os
import shutil
import struct
import time
import zipfile
from typing import Callable, List, Optional

from tempo_tpu.resilience import FailureKind


class SimulatedKill(BaseException):
    """Simulated SIGKILL: uncatchable by ``except Exception`` (and by
    the retry wrappers), exactly like the real thing.  Tests catch it
    explicitly at top level and then re-run the pipeline to exercise
    resume."""


class InjectedFault(OSError):
    """A synthetic transient IO failure (default ``EIO``) that
    self-describes its :class:`FailureKind` for ``classify``."""

    def __init__(self, message: str = "injected transient IO fault",
                 kind: FailureKind = FailureKind.TRANSIENT_IO):
        super().__init__(errno.EIO, message)
        self.failure_kind = kind


@dataclasses.dataclass
class InjectionRecord:
    target: str
    call_no: int
    action: str          # "raise" | "kill" | "delay" | "pass"


class FaultInjector:
    """Context manager that patches callables with faulty wrappers and
    restores them on exit (even on :class:`SimulatedKill`).

    Usage::

        with FaultInjector() as fi:
            fi.flaky(pd, "read_parquet", failures=2)
            fi.kill_on_call(np, "savez", call_no=2)
            ... run the pipeline ...
        assert [r.action for r in fi.records] == ["raise", "raise", ...]
    """

    def __init__(self):
        self.records: List[InjectionRecord] = []
        self._patches = []

    # ------------------------------------------------------------------
    def _patch(self, obj, attr: str, make_wrapper):
        original = getattr(obj, attr)
        self._patches.append((obj, attr, original))
        setattr(obj, attr, make_wrapper(original))
        return self

    @staticmethod
    def _name(obj, attr: str, label: Optional[str]) -> str:
        base = getattr(obj, "__name__", None) or type(obj).__name__
        return label or f"{base}.{attr}"

    def flaky(self, obj, attr: str, failures: int = 2,
              exc_factory: Optional[Callable[[int], BaseException]] = None,
              label: Optional[str] = None) -> "FaultInjector":
        """Make the first ``failures`` calls to ``obj.attr`` raise
        (default: :class:`InjectedFault`, a retryable transient-io
        error); later calls pass through to the original."""
        name = self._name(obj, attr, label)
        make_exc = exc_factory or (
            lambda n: InjectedFault(f"injected transient fault #{n} at {name}")
        )
        state = {"n": 0}

        def make_wrapper(original):
            @functools.wraps(original)
            def wrapper(*args, **kwargs):
                state["n"] += 1
                if state["n"] <= failures:
                    self.records.append(
                        InjectionRecord(name, state["n"], "raise"))
                    raise make_exc(state["n"])
                self.records.append(InjectionRecord(name, state["n"], "pass"))
                return original(*args, **kwargs)

            return wrapper

        return self._patch(obj, attr, make_wrapper)

    def kill_on_call(self, obj, attr: str, call_no: int = 1,
                     partial_write: Optional[Callable] = None,
                     label: Optional[str] = None) -> "FaultInjector":
        """Raise :class:`SimulatedKill` on the ``call_no``-th call to
        ``obj.attr`` (earlier and later calls pass through).

        ``partial_write(*args, **kwargs)``, when given, runs just before
        the kill to model bytes already flushed at the moment of death —
        e.g. writing a truncated file to the target path."""
        name = self._name(obj, attr, label)
        state = {"n": 0}

        def make_wrapper(original):
            @functools.wraps(original)
            def wrapper(*args, **kwargs):
                state["n"] += 1
                if state["n"] == call_no:
                    if partial_write is not None:
                        partial_write(*args, **kwargs)
                    self.records.append(
                        InjectionRecord(name, state["n"], "kill"))
                    raise SimulatedKill(
                        f"simulated kill at {name} call #{call_no}")
                self.records.append(InjectionRecord(name, state["n"], "pass"))
                return original(*args, **kwargs)

            return wrapper

        return self._patch(obj, attr, make_wrapper)

    def delay_on_call(self, obj, attr: str, seconds: float,
                      call_no: int = 1, n_calls: int = 1,
                      label: Optional[str] = None) -> "FaultInjector":
        """Latency injection: sleep ``seconds`` before calls
        ``call_no .. call_no + n_calls - 1`` to ``obj.attr``, then pass
        through (other calls are untouched).  The deterministic lever
        for the deadline plane: a delayed dispatch makes every tick
        queued behind it overstay a budget chosen below ``seconds``,
        so stage-named ``DeadlineExceeded`` paths are exercised without
        racing a wall clock."""
        name = self._name(obj, attr, label)
        state = {"n": 0}

        def make_wrapper(original):
            @functools.wraps(original)
            def wrapper(*args, **kwargs):
                state["n"] += 1
                if call_no <= state["n"] < call_no + n_calls:
                    self.records.append(
                        InjectionRecord(name, state["n"], "delay"))
                    time.sleep(seconds)
                else:
                    self.records.append(
                        InjectionRecord(name, state["n"], "pass"))
                return original(*args, **kwargs)

            return wrapper

        return self._patch(obj, attr, make_wrapper)

    def fail_always(self, obj, attr: str,
                    exc_factory: Optional[Callable[[int], BaseException]] = None,
                    label: Optional[str] = None) -> "FaultInjector":
        """Every call to ``obj.attr`` raises — for exercising retry
        exhaustion and deadline paths."""
        return self.flaky(obj, attr, failures=1 << 30,
                          exc_factory=exc_factory, label=label)

    # ------------------------------------------------------------------
    def __enter__(self) -> "FaultInjector":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        for obj, attr, original in reversed(self._patches):
            setattr(obj, attr, original)
        self._patches.clear()
        return False


# ----------------------------------------------------------------------
# Artifact corruption
# ----------------------------------------------------------------------

def _npz_member_span(path: str, name: Optional[str] = None):
    """(member_name, data_offset, data_size) of one member of an npz
    archive — the largest by default (most likely a real data plane).
    Offsets come from the zip local header, so a flip lands inside the
    member's *stored* bytes, not container metadata."""
    with zipfile.ZipFile(path) as z:
        infos = [i for i in z.infolist() if i.file_size > 0]
        if name is not None:
            wanted = name if name.endswith(".npy") else name + ".npy"
            infos = [i for i in infos if i.filename == wanted]
        if not infos:
            raise ValueError(f"no matching member in {path!r}")
        info = max(infos, key=lambda i: i.file_size)
    with open(path, "rb") as f:
        f.seek(info.header_offset + 26)
        name_len, extra_len = struct.unpack("<HH", f.read(4))
    data_off = info.header_offset + 30 + name_len + extra_len
    return info.filename, data_off, info.compress_size


def flip_byte(path: str, offset: int) -> None:
    """XOR one byte of ``path`` in place (the minimal corruption)."""
    with open(path, "r+b") as f:
        f.seek(offset)
        b = f.read(1)
        f.seek(offset)
        f.write(bytes([b[0] ^ 0xFF]))


def corrupt_npz_array(path: str, name: Optional[str] = None) -> str:
    """Flip one byte in the middle of an npz member's stored data
    (``name`` or the largest member).  Returns the corrupted array's
    name (without the ``.npy`` suffix) so tests can assert the loader
    reports exactly that array."""
    member, off, size = _npz_member_span(path, name)
    # skip past the ~100-byte .npy header so the flip hits array bytes
    flip_byte(path, off + min(size - 1, 128 + (size - 128) // 2
                              if size > 256 else size // 2))
    return member[:-len(".npy")] if member.endswith(".npy") else member


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Cut ``path`` down to ``keep_fraction`` of its size — the shape a
    buffered write killed mid-flush leaves behind.  Returns the new
    size."""
    size = os.path.getsize(path)
    keep = max(0, int(size * keep_fraction))
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def corrupt_parquet_row_group(path: str, row_group: int = 0,
                              column: int = 0) -> dict:
    """Corrupt ONE row group of a real Parquet file in place by
    smashing its first column chunk's page header (the minimal
    corruption a reader reliably detects: byte flips inside compressed
    page *data* can decode silently when page checksums are off, but a
    garbled page header always fails deserialization).  Sibling row
    groups stay readable — exactly the shape the ingest quarantine
    must isolate.  Returns ``{"file", "row_group", "rows", "offset"}``
    so tests can assert the quarantine names this precise range."""
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    rg = pf.metadata.row_group(row_group)
    col = rg.column(column)
    off = col.data_page_offset
    if col.dictionary_page_offset is not None:
        off = min(off, col.dictionary_page_offset)
    with open(path, "r+b") as f:
        f.seek(off)
        f.write(b"\xFF" * 8)
    return {"file": path, "row_group": row_group,
            "rows": rg.num_rows, "offset": off}


def tear_parquet_footer(path: str) -> int:
    """Torn-write injection: truncate a real Parquet file just short of
    its trailing footer magic, the state a hard kill mid-flush (or a
    partial object-store upload) leaves behind.  EVERY read of the file
    then fails at open ('magic bytes not found in footer'), so the
    whole file is the quarantine unit.  Returns the new size."""
    size = os.path.getsize(path)
    keep = max(0, size - 6)
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


# ----------------------------------------------------------------------
# Crash residue
# ----------------------------------------------------------------------

def make_stale_tmp(ckpt_path: str) -> str:
    """Fabricate the ``<ckpt_path>.tmp`` directory a hard-killed save
    leaves behind (partial manifest-less content).  Returns its path."""
    tmp = ckpt_path + ".tmp"
    shutil.rmtree(tmp, ignore_errors=True)
    os.makedirs(tmp)
    with open(os.path.join(tmp, "arrays.npz"), "wb") as f:
        f.write(b"PK\x03\x04 partial write, killed mid-save")
    return tmp
