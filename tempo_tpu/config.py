"""Central registry of every ``TEMPO_TPU_*`` environment knob.

The knobs grew one module at a time (each engine added its own
override) and by round 6 two of them (``TEMPO_TPU_WAREHOUSE``,
``TEMPO_TPU_BINPACK``) had silently drifted out of BUILDING.md's knob
table.  This module is the single source of truth: every knob the
package reads is declared here with its type, default, owning module
and one-line contract, and *all* ``os.environ`` access inside
``tempo_tpu/`` goes through the accessors below.  The static analyzer
(``tools/analysis`` — the ``env-knobs`` rule) enforces both halves:

* ``os.environ`` / ``os.getenv`` anywhere in ``tempo_tpu/`` outside
  this file is a lint violation;
* the registry, the ``TEMPO_TPU_*`` string literals in the code, and
  BUILDING.md's knob table must agree exactly (no undeclared reads, no
  dead documentation).

Keep this module import-light (stdlib ``os`` only): it is imported by
``tempo_tpu/__init__`` *before* jax, while the process environment is
still being inspected.
"""

from __future__ import annotations

import os
from typing import Dict, NamedTuple, Optional


class Knob(NamedTuple):
    """One declared environment knob.

    ``type`` is documentation-grade ("bool", "int", "enum(...)",
    "path", "dtype"): the owning modules keep their historical parsing
    (tri-state bools, backend-dependent defaults), so the registry
    records intent rather than re-implementing coercion.  ``default``
    is the rendered default shown to humans; ``None`` means
    "unset = automatic choice"."""

    name: str
    type: str
    default: Optional[str]
    owner: str
    doc: str


def _knobs(*knobs: Knob) -> Dict[str, Knob]:
    return {k.name: k for k in knobs}


#: Every TEMPO_TPU_* knob the codebase reads, in BUILDING.md table
#: order.  Adding an ``os.environ`` read without declaring it here
#: fails ``python tools/analyze.py`` (env-knobs rule).
KNOBS: Dict[str, Knob] = _knobs(
    Knob("TEMPO_TPU_NATIVE", "bool", "1", "tempo_tpu/native",
         "0 forces the pure-numpy ingest path over the self-built C++ "
         "packer"),
    Knob("TEMPO_TPU_NATIVE_THREADS", "int", "cpu_count", "tempo_tpu/native",
         "thread-pool bound for the native packer"),
    Knob("TEMPO_TPU_COMPUTE_DTYPE", "dtype", None, "tempo_tpu/packing",
         "float64|float32 override of the per-backend metric-math "
         "dtype policy"),
    Knob("TEMPO_TPU_CACHE_DIR", "path", "~/.cache/tempo_tpu/jax",
         "tempo_tpu/__init__",
         "persistent XLA compilation cache location; empty disables"),
    Knob("TEMPO_TPU_SORT_KERNELS", "bool", None, "tempo_tpu/ops/sortmerge",
         "force/forbid the sort-and-scan kernel forms (default: on for "
         "TPU, off elsewhere)"),
    Knob("TEMPO_TPU_PALLAS_ASOF", "bool", "1", "tempo_tpu/ops/pallas_merge",
         "0 kills the VMEM merge-join kernels"),
    Knob("TEMPO_TPU_NAN_ASOF", "bool", "0", "tempo_tpu/ops/sortmerge",
         "opt into the NaN-encoded XLA AS-OF variant"),
    Knob("TEMPO_TPU_WINDOW_ENGINE", "enum(auto|shifted|stream|windowed|legacy)",
         "auto", "tempo_tpu/ops/rolling",
         "force one of the rolling range-stats engines"),
    Knob("TEMPO_TPU_STREAM_MAX_ROWS", "int", "16384",
         "tempo_tpu/ops/pallas_window",
         "row-extent ceiling of the streaming window engine"),
    Knob("TEMPO_TPU_DMA_BUFFERS", "int", "2",
         "tempo_tpu/ops/pallas_stream",
         "HBM->VMEM buffer depth of the streaming kernels: 2 = the "
         "implicit double-buffered BlockSpec pipeline; >2 = the "
         "explicit N-deep DMA ring (copy/semaphore scratch)"),
    Knob("TEMPO_TPU_PACK_COLS", "int", None,
         "tempo_tpu/ops/pallas_stream",
         "cap on metric columns packed into one window-kernel pass; "
         "unset = largest width the VMEM budget folding admits"),
    Knob("TEMPO_TPU_MEGACORE", "bool", "1",
         "tempo_tpu/ops/pallas_stream",
         "0 disables megacore grid partitioning (carry-free grid axes "
         "marked 'parallel' so Mosaic splits them across TensorCores)"),
    Knob("TEMPO_TPU_STRICT_SQL", "bool", "0", "tempo_tpu/frame",
         "make selectExpr/filter re-raise instead of falling back to "
         "pandas eval/query"),
    Knob("TEMPO_TPU_SQL_STRICT", "bool", "0", "tempo_tpu/frame",
         "strict compiled-SQL mode: any fallback from the compiled "
         "surface to a host-pandas engine raises StrictSqlFallback by "
         "name (supersedes the legacy TEMPO_TPU_STRICT_SQL alias; "
         "per-call strict= wins over both)"),
    Knob("TEMPO_TPU_JOIN_ENGINE", "enum(single|chunked|bracket|bitonic)",
         None, "tempo_tpu/profiling",
         "force one AS-OF merge engine; unset = auto"),
    Knob("TEMPO_TPU_JOIN_CHUNK_LANES", "int", None,
         "tempo_tpu/ops/pallas_merge",
         "merged-lane chunk width of the streaming join engine "
         "(power of two >= 256); unset = largest feasible"),
    Knob("TEMPO_TPU_MAX_MERGED_LANES", "int", "196608",
         "tempo_tpu/resilience",
         "single-program merged-lane ceiling (under the measured ~205K "
         "XLA-sort compiler OOM)"),
    Knob("TEMPO_TPU_BINPACK", "bool", None, "tempo_tpu/join",
         "force/forbid the bin-packed (segmented) join layout; unset = "
         "engage below 0.35 slot occupancy"),
    Knob("TEMPO_TPU_WAREHOUSE", "path", "tempo_tpu_warehouse",
         "tempo_tpu/io/writer",
         "base directory of the partitioned Parquet/Delta warehouse"),
    Knob("TEMPO_TPU_NO_STDERR_FILTER", "bool", "0", "__graft_entry__",
         "1 disables the benign XLA:CPU AOT stderr filter of the "
         "multichip dryrun"),
    Knob("TEMPO_TPU_PLAN", "bool", "0", "tempo_tpu/plan",
         "1 turns on the lazy query planner: recorded op chains are "
         "optimized (kernel fusion, engine hoisting, column pruning) "
         "and executed at collect(); eager is the default"),
    Knob("TEMPO_TPU_RESHARD_PLACEMENT", "enum(auto|declarative|explicit)",
         "auto", "tempo_tpu/plan/optimizer",
         "plan-placed resharding of time-sharded mesh chains: auto = "
         "explicit reshard nodes around maximal series-local op runs "
         "(interior all_to_all pairs eliminated, reshard-back sunk "
         "until a blocker); explicit = reshard around every such op, "
         "never eliminated; declarative = no plan nodes, each op keeps "
         "its internal all_to_all pair"),
    Knob("TEMPO_TPU_MESH_DEVICES", "int", None, "bench.py",
         "device-count ceiling of the --only-mesh-scaling bench sweep "
         "(the 1->2->4->8 ladder is clipped here; unset = up to 8 or "
         "the available device count)"),
    Knob("TEMPO_TPU_PLAN_CACHE_SIZE", "int", "64", "tempo_tpu/plan/cache",
         "LRU bound of the planner's compiled-executable cache "
         "(entries keyed by plan signature + shapes + mesh; 0 disables "
         "caching)"),
    Knob("TEMPO_TPU_CONTRACT_LANES", "int", "32",
         "tempo_tpu/plan/contracts",
         "compile-shape budget of the compiled-contract tier (tools/"
         "analyze.py --compiled): per-series padded row count L of the "
         "representative shapes the production-program registry is "
         "compiled at (clamped [16, 4096]; bigger = slower, closer to "
         "production extents)"),
    Knob("TEMPO_TPU_SERVE_BATCH_ROWS", "int", "64",
         "tempo_tpu/serve/executor",
         "per-series row cap of one serving micro-batch: the executor "
         "cuts a coalesced run when any series reaches it, bounding "
         "the padded-bucket ladder (and therefore the cached-"
         "executable set) the steady state cycles through"),
    Knob("TEMPO_TPU_SERVE_QUEUE_DEPTH", "int", "1024",
         "tempo_tpu/serve/executor",
         "bound of the serving executor's tick queue; a full queue "
         "blocks submit() — the backpressure signal"),
    Knob("TEMPO_TPU_SERVE_CKPT_EVERY", "int", "0",
         "tempo_tpu/serve/stream",
         "snapshot the serving StreamState every N acked events "
         "(CRC'd keep-last-K via checkpoint.save_state; 0 disables "
         "automatic snapshots — snapshot() stays available)"),
    Knob("TEMPO_TPU_SERVE_COHORT_SLOTS", "int", "1024",
         "tempo_tpu/serve/cohort",
         "initial stream-slot capacity of each cohort shape-bucket "
         "group (grown by doubling when full; rounded up to the "
         "mesh's stream-axis size on sharded cohorts — a capacity "
         "change recompiles, so size it to the expected fleet)"),
    Knob("TEMPO_TPU_SERVE_COHORT_CKPT_EVERY", "int", "0",
         "tempo_tpu/serve/cohort",
         "snapshot the whole cohort (ONE kind=\"cohort_state\" "
         "artifact, per-stream acked cursors in the manifest) every N "
         "total acked events; 0 disables automatic snapshots — "
         "StreamCohort.snapshot() stays available"),
    Knob("TEMPO_TPU_STANDING_QUEUE_DEPTH", "int", "1024",
         "tempo_tpu/query/standing",
         "bound of each standing subscription's notification queue; a "
         "full queue drops the OLDEST notification (counted on "
         "Subscription.dropped) so one slow consumer never stalls the "
         "push path — result() stays exact regardless of drops"),
    Knob("TEMPO_TPU_STANDING_REMAINDER_EVERY", "int", "64",
         "tempo_tpu/query/standing",
         "push-boundary cadence at which remainder-mode standing "
         "queries (plans with no incremental carry) re-run the full "
         "canonical plan over the unified scan and emit a refresh "
         "notification; result() always re-runs regardless"),
    Knob("TEMPO_TPU_STANDING_PUSH_PERIOD", "float", "0",
         "tempo_tpu/query/standing",
         "delivery-worker coalescing window in seconds: pushes "
         "admitted within one period merge into fewer delivery "
         "boundaries (fewer, larger cohort dispatches); 0 (default) "
         "delivers every push as its own boundary"),
    Knob("TEMPO_TPU_COST_MODEL", "bool", "1", "tempo_tpu/plan/cost",
         "0 reverts engine picks, fusion and reshard placement to the "
         "pure rule-based decisions; on (default) they are argmins "
         "over estimated cost, with the legacy thresholds demoted to "
         "feasibility priors"),
    Knob("TEMPO_TPU_SERVICE_WORKERS", "int", "4",
         "tempo_tpu/service/service",
         "worker-thread count of the multi-tenant query service "
         "(concurrent plan executions; clamped >= 1)"),
    Knob("TEMPO_TPU_SERVICE_TENANT_QUOTA", "int", "64",
         "tempo_tpu/service/service",
         "per-tenant pending-query bound: a tenant at quota blocks in "
         "submit() — the per-tenant backpressure signal (the bounded-"
         "queue pattern of serve/executor.py, applied per tenant)"),
    Knob("TEMPO_TPU_SERVICE_VMEM_BUDGET", "int", None,
         "tempo_tpu/service/admission",
         "per-query VMEM admission budget in bytes; unset = the "
         "kernel planners' scoped budget (pallas_kernels._VMEM_BUDGET),"
         " explicit 0 admits nothing. A query whose projected "
         "worst-case per-step block exceeds it is REJECTED with "
         "AdmissionError (it could never run)"),
    Knob("TEMPO_TPU_SERVICE_HBM_BUDGET", "int", None,
         "tempo_tpu/service/admission",
         "total HBM admission budget in bytes (default 2 GiB; "
         "explicit 0 admits nothing): a query whose projected "
         "footprint exceeds the whole budget is REJECTED; one that "
         "merely exceeds the currently-free share is QUEUED until "
         "running queries release theirs"),
    Knob("TEMPO_TPU_SERVE_DEADLINE_S", "float", None,
         "tempo_tpu/serve/executor",
         "default end-to-end deadline (seconds) for serving tickets: "
         "a tick still queued when its budget dies fails fast with a "
         "stage-named DeadlineExceeded instead of waiting forever; "
         "unset/0 = no default deadline (per-submit deadlines stay "
         "available)"),
    Knob("TEMPO_TPU_SERVICE_DEADLINE_S", "float", None,
         "tempo_tpu/service/service",
         "default end-to-end deadline (seconds) for submitted "
         "queries, carried through quota wait, admission wait and "
         "dispatch; unset/0 = no default deadline"),
    Knob("TEMPO_TPU_BREAKER_THRESHOLD", "int", "3",
         "tempo_tpu/resilience",
         "consecutive failures of one key (plan signature / stream "
         "member) that OPEN its circuit breaker: further work on the "
         "key fails fast with QuarantinedError instead of burning "
         "retry budgets"),
    Knob("TEMPO_TPU_BREAKER_COOLDOWN_S", "float", "5.0",
         "tempo_tpu/resilience",
         "quarantine cooldown: after this many seconds an open "
         "circuit admits ONE half-open probe — success closes it, "
         "failure re-opens it for another cooldown"),
    Knob("TEMPO_TPU_SERVE_DONATE", "bool", None, "tempo_tpu/serve/state",
         "force (1) / forbid (0) donation of the serve/cohort step "
         "programs' retired state buffers; unset = backend-automatic: "
         "ON for accelerators (in-place steady state, pinned by the "
         "serve.step/serve.cohort_step compiled contracts), OFF on "
         "XLA:CPU where the virtual multi-device host platform "
         "corrupts donated serve buffers (use-after-free: garbage "
         "emissions / heap aborts observed on jaxlib 0.4.36)"),
    Knob("TEMPO_TPU_SERVE_COHORT_DIFF", "bool", "0",
         "tempo_tpu/serve/cohort",
         "1 makes automatic cohort snapshots differential: only "
         "bucket groups dirty since the previous snapshot are "
         "written, chained to the last full artifact by CRC'd "
         "manifests (resume walks the chain; bytes per snapshot "
         "scale with dirty state, not fleet size)"),
    Knob("TEMPO_TPU_CKPT_PLACEMENT", "enum(auto|off)", "auto",
         "tempo_tpu/plan/checkpoints",
         "placement of first-class checkpoint barrier nodes on "
         "planned chains run inside plan.checkpoints.checkpointed(): "
         "auto places signed step barriers at materialization/reshard "
         "boundaries (every-th op boundary + the final pre-collect "
         "frame); off disables plan barriers (run_resumable keeps "
         "working)"),
    Knob("TEMPO_TPU_INGEST_DEADLINE_S", "float", None,
         "tempo_tpu/io/ingest",
         "default end-to-end deadline (seconds) for from_parquet: ONE "
         "wall-clock budget across validation, census and every "
         "streaming/placement stage, dying with a stage-named "
         "DeadlineExceeded; unset/0 = no deadline (the per-call "
         "retry-policy deadlines still bound individual IO retries)"),
    Knob("TEMPO_TPU_CHAOS_ROWS", "int", None, "bench.py",
         "row target of bench config 16's batch-plane chaos campaign "
         "(--only-chaos-pipeline) in full mode; unset = 1e9 (the "
         "ROADMAP billion-row out-of-core sweep), smoke mode ignores "
         "it"),
    Knob("TEMPO_TPU_TUNE_PROFILE", "path|off", None, "tempo_tpu/tune",
         "tuned-knob profile source: a path to a harness-produced "
         "profile, 'off' to disable profile loading, unset = the "
         "checked-in per-device-kind profile under tempo_tpu/tune/"
         "profiles/.  Tuned values are PRIORS: an explicitly-set env "
         "knob always wins; a corrupt or foreign-fingerprint profile "
         "is refused by name with fallback to the built-in defaults"),
    Knob("TEMPO_TPU_STORE_SEGMENT_ROWS", "int", "1048576",
         "tempo_tpu/store/engine",
         "target rows per clustered segment of one store generation "
         "(the transactional write-back chunk: each segment commits "
         "with a chained CRC'd sidecar; compaction merges into 8x "
         "this by default)"),
    Knob("TEMPO_TPU_STORE_KEEP_GENERATIONS", "int", "2",
         "tempo_tpu/store/engine",
         "generation retention of store tables (min 1 = current "
         "only); >= 2 keeps the previous generation on disk so "
         "readers opened on it stay bitwise-correct while the next "
         "one commits"),
    Knob("TEMPO_TPU_STORE_COMPACT_MIN_SEGMENTS", "int", "2",
         "tempo_tpu/store/compact",
         "segment count below which store.compact() is a no-op (the "
         "table is already compact)"),
    Knob("TEMPO_TPU_STITCH_MAX_OPS", "int", "8",
         "tempo_tpu/plan/optimizer",
         "longest run of adjacent series-local planned ops stitched "
         "into ONE jitted executable (optimization_barrier pins every "
         "op boundary, so stitched == op-by-op bitwise); 1 or 0 "
         "disables stitching"),
    Knob("TEMPO_TPU_INGEST_RING", "int", "2",
         "tempo_tpu/io/ingest",
         "slab-buffer ring depth of the out-of-core pipelines "
         "(io.ingest.sweep_slabs + the from_parquet shard loop): "
         "decode of slab N+1 and D2H of slab N-1 overlap compute of "
         "slab N behind a bounded ring; 1 = fully serial (identical "
         "loop, same bits by construction)"),
    Knob("TEMPO_TPU_SERVE_COALESCE_S", "float", "0.002",
         "tempo_tpu/serve/executor",
         "dispatch coalescing window (seconds) of the serving "
         "executors: ticks arriving within it batch into one device "
         "dispatch (the batched cohort path scatters the whole window "
         "on-device); per-constructor coalesce_s overrides win"),
    Knob("TEMPO_TPU_SERVE_COHORT_RESIDENT", "int", "0",
         "tempo_tpu/serve/cohort",
         "LRU resident-member budget of a StreamCohort with a "
         "spill_dir: members beyond it spill their slot state to "
         "CRC'd kind=\"cohort_member\" artifacts and fault back in "
         "on their next tick; 0 = unlimited (no spill)"),
)

#: Non-TEMPO_TPU environment variables the package legitimately reads
#: (foreign contracts: jax's platform selection, Databricks runtime
#: detection).  ``env_external`` refuses anything not listed, so new
#: foreign reads are declared here or fail loudly.
EXTERNAL_VARS = (
    "JAX_PLATFORMS",
    "DATABRICKS_RUNTIME_VERSION",
)


def get(name: str, default: Optional[str] = None) -> Optional[str]:
    """Raw string value of a *declared* knob (``KeyError`` on an
    undeclared name — declare it in :data:`KNOBS` first).  ``None``
    when unset and no ``default`` given; owning modules keep their
    historical parsing on top of this."""
    if name not in KNOBS:
        raise KeyError(
            f"undeclared knob {name!r}: add it to tempo_tpu.config.KNOBS "
            f"(and BUILDING.md's knob table) before reading it")
    return os.environ.get(name, default)


def get_bool(name: str, default: bool = False) -> bool:
    """Common falsy-string parse: unset/''/'0'/'false'/'no'/'off' →
    False-ish side of ``default``; anything else → True.  Knobs with
    tri-state semantics (forced on / forced off / auto) read
    :func:`get` and decide themselves."""
    val = get(name)
    if val is None or val.strip().lower() in ("", "0", "false", "no", "off"):
        return False if val is not None else default
    return True


def get_int(name: str, default: Optional[int] = None) -> Optional[int]:
    """Integer knob; unset or empty → ``default``."""
    val = get(name)
    if val is None or not val.strip():
        return default
    return int(val)


def get_float(name: str, default: Optional[float] = None) -> Optional[float]:
    """Float knob (seconds budgets etc.); unset or empty → ``default``."""
    val = get(name)
    if val is None or not val.strip():
        return default
    return float(val)


def child_env(overrides: Optional[Dict[str, Optional[str]]] = None
              ) -> Dict[str, str]:
    """Snapshot of the process environment for CHILD processes (the
    autotuner's probe children, bench subprocesses), with
    ``overrides`` applied: value ``None`` removes the name, anything
    else is stringified.  Lives here so the env-knobs lint keeps its
    single-owner guarantee — ``os.environ`` access stays inside the
    registry module even for subprocess plumbing."""
    env = dict(os.environ)
    for name, value in (overrides or {}).items():
        if value is None:
            env.pop(name, None)
        else:
            env[name] = str(value)
    return env


def env_external(name: str, default: Optional[str] = None) -> Optional[str]:
    """Sanctioned read of a non-``TEMPO_TPU`` environment variable
    (:data:`EXTERNAL_VARS`); the env-knobs lint bans direct
    ``os.environ`` use everywhere else in the package."""
    if name not in EXTERNAL_VARS:
        raise KeyError(
            f"{name!r} is not a declared external env var: add it to "
            f"tempo_tpu.config.EXTERNAL_VARS")
    return os.environ.get(name, default)
