"""Distribution layer: device meshes, sharded ops, halo exchange.

The reference's only distribution model is Spark's hash shuffle over
partition keys plus the overlapping time-bucket trick for skewed keys
(/root/reference/python/tempo/tsdf.py:164-190; SURVEY.md §2.3).  The
TPU-native equivalents here:

* **series axis (data parallel)** — packed ``[K, L]`` arrays sharded
  over a ``('series',)`` mesh axis with ``NamedSharding``; per-series
  kernels are batched over K so XLA partitions them with zero
  collectives (the analog of Spark routing each key to one task).
* **time axis (sequence parallel)** — for series too long for one
  chip, the time axis is sharded and rolling/AS-OF lookback windows
  receive their trailing *halo* from the left neighbor via
  ``lax.ppermute`` over ICI inside ``shard_map`` — the same overlap
  algebra as the reference's ``tsPartitionVal`` fraction-overlap
  brackets, turned into a neighbor exchange.
* both axes compose on a 2-D ``('series', 'time')`` mesh.
"""

from tempo_tpu.parallel.mesh import (
    make_mesh,
    series_sharding,
    shard_series,
    pad_series_axis,
)
from tempo_tpu.parallel.halo import (
    range_stats_time_sharded,
    asof_time_sharded,
    ema_time_sharded,
)
from tempo_tpu.parallel.multihost import (
    distributed_init,
    process_mesh,
    process_series_range,
    shard_series_global,
)
from tempo_tpu.parallel.reshard import (
    reshard,
    all_to_all_series_to_time,
    all_to_all_time_to_series,
)

__all__ = [
    "reshard",
    "all_to_all_series_to_time",
    "all_to_all_time_to_series",
    "make_mesh",
    "series_sharding",
    "shard_series",
    "pad_series_axis",
    "range_stats_time_sharded",
    "asof_time_sharded",
    "ema_time_sharded",
    "distributed_init",
    "process_mesh",
    "process_series_range",
    "shard_series_global",
]
