"""Time-axis sharding with neighbor halo exchange (sequence parallelism).

The reference handles "too many rows per key" with overlapping time
brackets: round ts into ``tsPartitionVal``-second buckets and duplicate
the trailing ``fraction`` of each bucket into the next so windowed
lookbacks see their history, then drop the duplicates
(/root/reference/python/tempo/tsdf.py:164-190, consumed at :549-558;
scala asofJoin.scala:91-116).  That is a blockwise halo scheme executed
through Spark's shuffle.

Here the same algebra becomes a *device* layout: the packed time axis
``[K, L]`` is sharded over a ``'time'`` mesh axis, and each shard
receives a trailing halo of ``H`` rows from its left neighbor over ICI
via ``lax.ppermute`` inside ``shard_map``.  Compute then runs on the
halo-extended block with the ordinary single-device kernels and the
halo region is dropped from outputs — compute-local, communication =
one neighbor exchange of ``H`` rows.

Correctness contract (same as the reference's): the halo must cover the
lookback — ``H`` rows must span at least ``window_secs`` (or the AS-OF
lookback) of history.  Like the reference's missing-value audit
(tsdf.py:141-159), kernels return a ``clipped`` count of rows whose
window may have been truncated at the halo boundary instead of failing.

Key layout fact that makes the halo concatenation sound: a packed row
is non-decreasing along the full time axis (real timestamps ascending,
then ``TS_PAD`` padding), so [left-neighbor's last H columns | local
chunk] is a contiguous slice of that row and stays non-decreasing —
``searchsorted`` remains valid with no re-sort.  The first shard's halo
is synthesized as ``TS_NEG``/invalid ("nothing before the beginning").
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map as _shard_map_raw  # jax >= 0.8

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _shard_map_raw(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw

    def shard_map(f, *, mesh, in_specs, out_specs):
        return _shard_map_raw(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tempo_tpu.ops import asof as asof_ops
from tempo_tpu.ops import rolling as rk

from tempo_tpu.packing import RANGE_STATS, TS_PAD, TS_REAL_MAX

# sentinel smaller than any real ns timestamp, with headroom so
# subtracting a window width cannot underflow int64 (mirror of TS_PAD)
TS_NEG = np.int64(-TS_REAL_MAX)
# right-halo fill on the last shard: larger than any real timestamp so
# the extended row stays sorted and no window ever includes it — the
# same sentinel packed rows already use for padding
TS_POS = TS_PAD


def _specs(mesh: Mesh, ndim: int, time_axis: str, series_axis: str):
    """PartitionSpec for a [..., K, L] array: series axis (if present on
    the mesh) on dim -2, time axis on dim -1."""
    s = series_axis if series_axis in mesh.axis_names else None
    lead = [None] * (ndim - 2)
    return P(*(lead + [s, time_axis]))


def _halo_from_left(
    arr: jnp.ndarray, halo: int, n_shards: int, time_axis: str, fill
) -> jnp.ndarray:
    """Return this shard's left halo: the last ``halo`` columns of the
    left neighbor's block (``fill`` on the first shard)."""
    tail = arr[..., -halo:]
    if n_shards == 1:
        return jnp.full_like(tail, fill)
    perm = [(i, i + 1) for i in range(n_shards - 1)]
    recv = jax.lax.ppermute(tail, time_axis, perm)
    ti = jax.lax.axis_index(time_axis)
    return jnp.where(ti == 0, jnp.full_like(tail, fill), recv)


def _halo_from_right(
    arr: jnp.ndarray, halo: int, n_shards: int, time_axis: str, fill
) -> jnp.ndarray:
    """Return this shard's right halo: the first ``halo`` columns of the
    right neighbor's block (``fill`` on the last shard).  Needed because
    a Spark range window's frame includes *following* rows that share
    the current row's order-key value (see range_window_bounds), and
    such ties can straddle a shard boundary."""
    head = arr[..., :halo]
    if n_shards == 1:
        return jnp.full_like(head, fill)
    perm = [(i + 1, i) for i in range(n_shards - 1)]
    recv = jax.lax.ppermute(head, time_axis, perm)
    ti = jax.lax.axis_index(time_axis)
    return jnp.where(ti == n_shards - 1, jnp.full_like(head, fill), recv)


def _check_halo(mesh: Mesh, L: int, halo: int, time_axis: str) -> int:
    n_time = mesh.shape[time_axis]
    if L % n_time != 0:
        raise ValueError(f"time axis {L} not divisible by mesh axis {n_time}")
    if not (0 < halo <= L // n_time):
        raise ValueError(f"halo {halo} must be in (0, {L // n_time}]")
    return n_time


def range_stats_time_sharded(
    mesh: Mesh,
    ts_long: jnp.ndarray,   # [K, L] int64 seconds (sorted per row)
    x: jnp.ndarray,         # [K, L] float values
    valid: jnp.ndarray,     # [K, L] bool
    window_secs: float,
    halo: int,
    time_axis: str = "time",
    series_axis: str = "series",
) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """``withRangeStats`` (tsdf.py:673-721 semantics) over a time-sharded
    series batch.  Returns (stats dict of [K, L] arrays, clipped count).

    ``clipped`` counts rows whose window start hit the halo boundary on a
    non-first shard — i.e. rows whose true window may extend past the H
    rows of halo (the reference's skew-join warning analog).
    """
    _check_halo(mesh, int(ts_long.shape[-1]), halo, time_axis)
    fn = _build_range_stats(mesh, float(window_secs), int(halo),
                            time_axis, series_axis)
    return fn(ts_long, x, valid)


@functools.lru_cache(maxsize=256)
def _build_range_stats(
    mesh: Mesh, window_secs: float, halo: int,
    time_axis: str, series_axis: str,
):
    """Jitted program builder, cached so chained frame-level pipelines
    compile each (mesh, window, halo) combination once."""
    spec2 = _specs(mesh, 2, time_axis, series_axis)
    n_time = mesh.shape[time_axis]

    def kernel(ts_l, x_l, v_l):
        # left halo (lookback history) + right halo (following rows that
        # tie on the order key - Spark's range frame includes them, see
        # range_window_bounds' upper_bound end)
        h_ts = _halo_from_left(ts_l, halo, n_time, time_axis, TS_NEG)
        h_x = _halo_from_left(x_l, halo, n_time, time_axis, jnp.zeros((), x_l.dtype))
        h_v = _halo_from_left(v_l, halo, n_time, time_axis, False)
        r_ts = _halo_from_right(ts_l, halo, n_time, time_axis, TS_POS)
        r_x = _halo_from_right(x_l, halo, n_time, time_axis, jnp.zeros((), x_l.dtype))
        r_v = _halo_from_right(v_l, halo, n_time, time_axis, False)
        # TS_NEG / TS_POS fills keep the extended row sorted end to end
        ext_ts = jnp.concatenate([h_ts, ts_l, r_ts], axis=-1)
        ext_x = jnp.concatenate([h_x, x_l, r_x], axis=-1)
        ext_v = jnp.concatenate([h_v, v_l, r_v], axis=-1)
        L_ext = ext_ts.shape[-1]
        Ll = ts_l.shape[-1]

        # exact integer window compare for any width — no weak-f64 op
        # under the f32 compute policy (the compiled no-f64-leak
        # contract) and no float rounding at epoch-scale seconds
        start, end = rk.range_window_bounds(
            ext_ts, rk.range_window_width(ext_ts, window_secs))
        stats = rk.windowed_stats(ext_x, ext_v, start, end)
        out = {k: v[..., halo:halo + Ll] for k, v in stats.items()}

        ti = jax.lax.axis_index(time_axis)
        # audit both truncation sides: lookback fell off the left halo,
        # or the tie run continued past the right halo
        s_loc = start[..., halo:halo + Ll]
        e_loc = end[..., halo:halo + Ll]
        local_clip = jnp.sum(
            ((s_loc == 0) & v_l & (ti > 0))
            | ((e_loc == L_ext) & v_l & (ti < n_time - 1)),
            dtype=jnp.int32,
        )
        axes = (time_axis, series_axis) if series_axis in mesh.axis_names else (time_axis,)
        clipped = jax.lax.psum(local_clip, axes)
        return out, clipped

    out_stats_spec = {k: spec2 for k in RANGE_STATS}
    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec2, spec2, spec2),
        out_specs=(out_stats_spec, P()),
    )
    return jax.jit(fn)


def ema_time_sharded(
    mesh: Mesh,
    x: jnp.ndarray,        # [K, L] float
    valid: jnp.ndarray,    # [K, L] bool
    alpha: float,
    time_axis: str = "time",
    series_axis: str = "series",
) -> jnp.ndarray:
    """Exact infinite-horizon EMA across a time-sharded axis.

    The EMA recurrence is an associative (decay, value) monoid, so each
    shard scans locally and the cross-shard carry is an exclusive scan
    of per-shard totals, realised with one small ``all_gather`` over the
    time axis — O(L/n) compute + O(n) stitch, vs the reference's
    truncated-lag approximation that cannot cross partitions at all
    (tsdf.py:615-635).
    """
    if x.shape[-1] % mesh.shape[time_axis] != 0:
        raise ValueError(
            f"time axis {x.shape[-1]} not divisible by {mesh.shape[time_axis]}"
        )
    fn = _build_ema(mesh, float(alpha), time_axis, series_axis)
    return fn(x, valid)


@functools.lru_cache(maxsize=256)
def _build_ema(mesh: Mesh, alpha: float, time_axis: str, series_axis: str):
    spec2 = _specs(mesh, 2, time_axis, series_axis)
    n_time = mesh.shape[time_axis]

    def kernel(x_l, v_l):
        a = jnp.asarray(alpha, x_l.dtype)
        decay = jnp.where(v_l, 1.0 - a, 1.0)
        inp = jnp.where(v_l, a * x_l, 0.0)

        def combine(c1, c2):
            d1, v1 = c1
            d2, v2 = c2
            return d1 * d2, v2 + d2 * v1

        d, y = jax.lax.associative_scan(combine, (decay, inp), axis=-1)
        if n_time > 1:
            d_tot, v_tot = d[..., -1], y[..., -1]                  # [K]
            dg = jax.lax.all_gather(d_tot, time_axis)              # [n, K]
            vg = jax.lax.all_gather(v_tot, time_axis)
            ti = jax.lax.axis_index(time_axis)
            carry_d = jnp.ones_like(d_tot)
            carry_v = jnp.zeros_like(v_tot)
            for j in range(n_time):                                # static
                take = j < ti
                nd, nv = combine((carry_d, carry_v), (dg[j], vg[j]))
                carry_d = jnp.where(take, nd, carry_d)
                carry_v = jnp.where(take, nv, carry_v)
            y = y + d * carry_v[..., None]
        return y

    fn = shard_map(
        kernel, mesh=mesh, in_specs=(spec2, spec2), out_specs=spec2,
    )
    return jax.jit(fn)


def asof_time_sharded(
    mesh: Mesh,
    l_ts: jnp.ndarray,       # [K, Ll] int64, time-sharded
    r_ts: jnp.ndarray,       # [K, Lr] int64, time-sharded
    r_valids: jnp.ndarray,   # [n_cols, K, Lr] bool per-column non-null
                             # (False on padding rows — the carry
                             # relies on that invariant)
    r_values: jnp.ndarray,   # [n_cols, K, Lr] float column values
    halo: int,
    time_axis: str = "time",
    series_axis: str = "series",
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """AS-OF join over time-sharded left/right with *unbounded* lookback.

    Shard-local matching handles rows whose match lives in the same
    time shard; matches any distance further back ride a cross-shard
    **carry**: each shard publishes its last non-null value per column
    (one [n_cols, K] vector), an exclusive combine over an
    ``all_gather`` of those supplies the latest preceding value to rows
    with no local match — the associative-scan form of the reference's
    ``last(col, ignoreNulls) over unboundedPreceding`` (tsdf.py:139),
    so lookback depth is unlimited, unlike the reference's
    ``tsPartitionVal`` bracket which nulls beyond the overlap.  The
    trailing ``halo`` from the *right* neighbor covers Spark's
    equal-timestamp tie rule (a tie run straddling the boundary).

    Precondition (value-aligned shards): for every shard *i*, every
    right row in shards j < i must be at-or-before every left row in
    shard *i* — true when both sides share a time grid (telemetry
    joins, the driver dryrun) or were bracket-packed against common
    boundaries.  For independently-packed sides use the exact
    all-to-all layout-switch join instead
    (``tempo_tpu.dist._asof_a2a``, what ``DistributedTSDF.asofJoin``
    dispatches to); under misalignment this kernel's carry can surface
    a *later* right value than the true as-of match.

    Returns (values [n_cols, K, Ll], found [n_cols, K, Ll] bool,
    clipped count) — ``clipped`` counts left rows whose equal-ts tie run
    may continue past the right halo (audit, tsdf.py:150-159 analog).
    """
    n_time = _check_halo(mesh, int(r_ts.shape[-1]), halo, time_axis)
    if l_ts.shape[-1] % n_time != 0:
        raise ValueError(f"left time axis {l_ts.shape[-1]} not divisible by {n_time}")
    from tempo_tpu.ops.sortmerge import use_sort_kernels

    fn = _build_asof(mesh, int(halo), time_axis, series_axis,
                     use_sort_kernels())
    return fn(l_ts, r_ts, r_valids, r_values)


@functools.lru_cache(maxsize=256)
def _build_asof(mesh: Mesh, halo: int, time_axis: str, series_axis: str,
                sort_kernels: bool = False):
    spec2 = _specs(mesh, 2, time_axis, series_axis)
    spec3 = _specs(mesh, 3, time_axis, series_axis)
    n_time = mesh.shape[time_axis]

    def kernel(lts, rts, rval, rx):
        # right halo only: right rows in the next shard that tie a left
        # row's timestamp are the true AS-OF match (last right row with
        # r_ts <= l_ts — equal ts included, tsdf.py:111-162), and a tie
        # run can straddle the boundary.  History older than this shard
        # arrives via the carry below, not a halo.
        g_ts = _halo_from_right(rts, halo, n_time, time_axis, TS_POS)
        g_val = _halo_from_right(rval, halo, n_time, time_axis, False)
        g_x = _halo_from_right(rx, halo, n_time, time_axis, jnp.zeros((), rx.dtype))
        ext_ts = jnp.concatenate([rts, g_ts], axis=-1)
        ext_val = jnp.concatenate([rval, g_val], axis=-1)
        ext_x = jnp.concatenate([rx, g_x], axis=-1)
        L_ext = ext_ts.shape[-1]

        if sort_kernels:
            # gather-free shard-local join (the value gather below is
            # the single most expensive op on TPU — sortmerge.py).
            # Engine cascade per shard (round 6): single-plan VMEM
            # merge when the halo-extended width fits its plan, the
            # XLA bitonic network past the single-program ceiling —
            # so a time-sharded join whose SHARD width exceeds ~205K
            # merged lanes no longer OOMs the compiler; the time
            # sharding itself is the distributed form of lane
            # chunking (shard = chunk, the cross-shard carry below =
            # the chunked kernel's carried ffill state).
            from tempo_tpu.ops import sortmerge as sm

            vals, found, last_idx = sm.asof_merge_values(
                lts, ext_ts, ext_val, ext_x
            )
        else:
            last_idx, col_idx = asof_ops.asof_indices_searchsorted(
                lts, ext_ts, ext_val
            )
            found = col_idx >= 0
            safe = jnp.maximum(col_idx, 0)
            vals = jnp.take_along_axis(ext_x, safe, axis=-1)

        if n_time > 1:
            # cross-shard carry: this shard's last non-null value per
            # (col, series) — from the LOCAL block only — combined
            # exclusively across the time axis (latest prior shard wins)
            lv = jnp.max(
                jnp.where(rval, jnp.arange(rts.shape[-1], dtype=jnp.int32),
                          -1),
                axis=-1,
            )                                             # [n_cols, K]
            has_local = lv >= 0
            v_local = jnp.take_along_axis(
                rx, jnp.maximum(lv, 0)[..., None], axis=-1
            )[..., 0]
            hg = jax.lax.all_gather(has_local, time_axis)  # [n_t, C, K]
            vg = jax.lax.all_gather(v_local, time_axis)
            ti = jax.lax.axis_index(time_axis)
            carry_has = jnp.zeros_like(has_local)
            carry_val = jnp.zeros_like(v_local)
            for j in range(n_time):                        # static
                take = (j < ti) & hg[j]
                carry_has = jnp.where(take, True, carry_has)
                carry_val = jnp.where(take, vg[j], carry_val)
            vals = jnp.where(found, vals, carry_val[..., None])
            found = found | carry_has[..., None]
        vals = jnp.where(found, vals, jnp.nan)

        # audit: left rows whose equal-ts tie run may continue past the
        # right halo (their match could be an even later tied right row)
        l_real = lts < TS_REAL_MAX  # not TS_PAD padding
        ti2 = jax.lax.axis_index(time_axis)
        local_clip = jnp.sum(
            (last_idx == L_ext - 1) & l_real & (ti2 < n_time - 1),
            dtype=jnp.int32,
        )
        axes = (time_axis, series_axis) if series_axis in mesh.axis_names else (time_axis,)
        clipped = jax.lax.psum(local_clip, axes)
        return vals, found, clipped

    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(spec2, spec2, spec3, spec3),
        out_specs=(spec3, spec3, P()),
    )
    return jax.jit(fn)
