"""Device-mesh construction and series-axis sharding helpers.

Replaces the role of Spark's cluster manager + hash partitioner
(reference: ``Window.partitionBy(partition_cols)`` routes each key's
rows to one task, /root/reference/python/tempo/tsdf.py:121,571).  Here
the routing is static: packed ``[K, L]`` arrays are laid out with the
leading (series) axis sharded across devices, and XLA's SPMD
partitioner splits every batched kernel along it without any
communication.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Build a ``jax.sharding.Mesh``.

    ``axes`` maps axis name -> size, e.g. ``{"series": 4, "time": 2}``.
    Defaults to all local devices on a 1-D ``('series',)`` axis — the
    data-parallel layout that covers the reference's entire distribution
    model (one series per task).  A ``'time'`` axis adds sequence
    parallelism (see tempo_tpu.parallel.halo).
    """
    devs = list(devices if devices is not None else jax.devices())
    if axes is None:
        axes = {"series": len(devs)}
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    n = int(np.prod(shape))
    if n > len(devs):
        raise ValueError(f"mesh needs {n} devices, only {len(devs)} available")
    return Mesh(np.asarray(devs[:n]).reshape(shape), names)


def series_sharding(mesh: Mesh, ndim: int = 2, axis: str = "series") -> NamedSharding:
    """NamedSharding that splits the leading (series) axis only."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def pad_series_axis(arr: np.ndarray, n_shards: int, fill) -> np.ndarray:
    """Pad the leading axis to a multiple of ``n_shards`` so an [K, L]
    batch divides evenly across the mesh.  Padded series are all-padding
    rows; kernels already ignore them via validity masks — the analog of
    Spark simply having some idle tasks."""
    K = arr.shape[0]
    rem = (-K) % n_shards
    if rem == 0:
        return arr
    pad = np.full((rem,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def shard_series(arr, mesh: Mesh, axis: str = "series"):
    """Place an array on the mesh sharded along its leading axis.

    The host->device scatter this performs is the ingest boundary —
    the equivalent of Spark's shuffle-on-partition-cols distributing
    rows to executors.  On multi-host topologies the same call (with a
    process-spanning mesh) rides DCN via
    ``jax.make_array_from_process_local_data``-style placement.
    """
    return jax.device_put(arr, series_sharding(mesh, np.ndim(arr), axis))
