"""Multi-host ingest and process-spanning meshes (DCN boundary).

The reference's multi-node story is Spark's: the driver holds a logical
plan and executors pull shuffled row partitions over the network
(SURVEY.md §5 "Distributed communication backend").  The TPU-native
equivalent splits that into two planes:

* **control/ingest (DCN)** — each host process packs the series it
  owns (``process_series_range``) and assembles a global ``jax.Array``
  with :func:`jax.make_array_from_process_local_data`; XLA moves bytes
  host->device locally, and cross-host traffic only happens if a
  subsequent op reshards.
* **compute (ICI)** — once arrays are global, every collective in
  tempo_tpu.parallel.halo (ppermute halos, psum audits, all_gather EMA
  carries) rides the ICI mesh exactly as in single-host mode; nothing
  in the kernels changes.

Single-process runs (tests, one-chip benches) degrade to plain
``device_put`` so every code path here is exercised by the CPU-mesh
test suite.
"""

from __future__ import annotations

import inspect
import logging
import threading
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tempo_tpu.resilience import FailureKind, classify

logger = logging.getLogger(__name__)


class DistributedInitTimeout(TimeoutError):
    """``distributed_init`` gave up waiting for the coordinator — the
    diagnostic alternative to hanging the process forever."""

    failure_kind = FailureKind.DEADLINE


def _watchdog_call(fn, kwargs: dict, timeout_s: float):
    """Run ``fn(**kwargs)`` in a daemon thread with a join timeout: a
    hung initializer (unreachable coordinator on a jax without native
    ``initialization_timeout``) surfaces as ``TimeoutError`` instead of
    blocking the process.  The stuck thread cannot be killed and leaks,
    but the caller gets a diagnostic and keeps control."""
    result: dict = {}

    def target():
        try:
            result["value"] = fn(**kwargs)
        except BaseException as e:
            result["exc"] = e

    t = threading.Thread(target=target, daemon=True,
                         name="tempo-distributed-init")
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        raise TimeoutError(f"initializer still blocked after {timeout_s}s")
    if "exc" in result:
        raise result["exc"]
    return result.get("value")


def distributed_init(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    timeout_s: Optional[float] = 300.0,
) -> None:
    """Initialise JAX's multi-process runtime (idempotent, no-op when
    single-process).  The moral analog of standing up the Spark cluster
    (scala/.../utils/SparkSessionWrapper.scala:12-37 chooses local vs
    cluster master); here the coordinator bootstraps over DCN.

    ``timeout_s`` bounds the wait for the coordinator (default 300s;
    ``None``/0 restores the old block-forever behaviour).  On expiry a
    :class:`DistributedInitTimeout` names the coordinator address and
    process coordinates instead of hanging the job silently — the
    failure-detection half of the resilience story for the one call
    that previously could block forever.  The bound is plumbed through
    jax's native ``initialization_timeout`` when this jax version has
    it, and enforced by a watchdog thread otherwise."""
    if num_processes is None or num_processes <= 1:
        return
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return
    init = jax.distributed.initialize
    kwargs = dict(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    try:
        native_timeout = (
            "initialization_timeout" in inspect.signature(init).parameters
        )
    except (TypeError, ValueError):
        native_timeout = False

    def _diagnostic(cause: Optional[BaseException]):
        raise DistributedInitTimeout(
            f"distributed_init did not complete (timeout_s={timeout_s}): "
            f"coordinator_address={coordinator_address!r}, "
            f"num_processes={num_processes}, process_id={process_id}. "
            "Check that the coordinator is reachable from this host and "
            "that every process in the job was launched with the same "
            "num_processes."
        ) from cause

    try:
        if timeout_s and native_timeout:
            kwargs["initialization_timeout"] = int(timeout_s)
            init(**kwargs)
        elif timeout_s:
            _watchdog_call(init, kwargs, timeout_s)
        else:
            init(**kwargs)
    except DistributedInitTimeout:
        raise
    except TimeoutError as e:
        _diagnostic(e)
    except RuntimeError as e:
        # older jax has no is_initialized(); a double call raises here
        if "once" in str(e):
            return
        if classify(e) is FailureKind.DEADLINE:
            _diagnostic(e)
        raise


def process_mesh(axes: Optional[dict] = None) -> Mesh:
    """Mesh over ALL devices in the job (every process), leading axis
    'series' by default.  ``make_mesh`` already builds from the global
    ``jax.devices()``; this alias exists so multi-host call sites read
    explicitly."""
    from tempo_tpu.parallel.mesh import make_mesh

    return make_mesh(axes)


def series_range_for_process(
    process_index: int,
    shard_process_ids: np.ndarray,   # [n_shards, replicas] device->process
    n_series: int,
) -> Tuple[int, int]:
    """Pure ingest routing rule: the [start, stop) series rows a process
    must supply, given the device->process grid along the series axis.

    Separated from the live-runtime wrapper below so the multi-process
    branches — partial ownership, zero ownership, the non-contiguous
    layout error — are unit-testable with synthetic process grids in a
    single-process suite (VERDICT r1 weak #6).
    """
    n_shards = int(shard_process_ids.shape[0])
    if n_series % n_shards != 0:
        raise ValueError(
            f"n_series {n_series} not divisible by series axis {n_shards}; "
            "pad with pad_series_axis first"
        )
    block = n_series // n_shards
    mine = [
        i for i in range(n_shards)
        if (shard_process_ids[i] == process_index).any()
    ]
    if not mine:
        return 0, 0
    lo, hi = min(mine), max(mine)
    if mine != list(range(lo, hi + 1)):
        raise ValueError(
            "series axis devices of this process are not contiguous; "
            "use a process-major mesh layout"
        )
    return lo * block, (hi + 1) * block


def mesh_shard_process_ids(mesh: Mesh, axis: str = "series") -> np.ndarray:
    """[n_shards, replicas] process index of each device, series-major.
    A process owns series-shard i if ANY of its devices sits in the mesh
    slice with series-index i: other mesh axes replicate the series
    block (P(axis, None, ...)), so every replica-holding process must
    supply the same local rows to make_array_from_process_local_data."""
    ax = mesh.axis_names.index(axis)
    n_shards = mesh.shape[axis]
    devs = np.moveaxis(np.asarray(mesh.devices), ax, 0).reshape(n_shards, -1)
    return np.vectorize(lambda d: d.process_index)(devs)


def process_series_range(n_series: int, mesh: Mesh, axis: str = "series") -> Tuple[int, int]:
    """[start, stop) of the series rows THIS process must supply for a
    [K, ...] array sharded over ``axis``.

    This is the ingest routing rule — the analog of Spark's hash
    partitioner deciding which executor holds which keys (tsdf.py:121),
    made static: contiguous series blocks per shard, shards laid out in
    mesh order.  Callers pack only their slice and hand it to
    :func:`shard_series_global`.
    """
    return series_range_for_process(
        jax.process_index(), mesh_shard_process_ids(mesh, axis), n_series
    )


def shard_series_global(
    local_rows: np.ndarray, mesh: Mesh, n_series: int, axis: str = "series"
):
    """Assemble a global [n_series, ...] jax.Array from each process's
    local series block (the rows ``process_series_range`` assigned it).

    Single-process: equivalent to ``device_put`` with a series
    NamedSharding.  Multi-process: wraps
    ``jax.make_array_from_process_local_data`` so ingest stays on the
    host-local DCN path — no host ever materialises the full array.
    """
    spec = P(axis, *([None] * (local_rows.ndim - 1)))
    sharding = NamedSharding(mesh, spec)
    global_shape = (n_series,) + tuple(local_rows.shape[1:])
    if jax.process_count() == 1:
        if local_rows.shape[0] != n_series:
            raise ValueError(
                f"single-process ingest expects all {n_series} series, "
                f"got {local_rows.shape[0]}"
            )
        return jax.device_put(local_rows, sharding)
    return jax.make_array_from_process_local_data(
        sharding, local_rows, global_shape
    )
