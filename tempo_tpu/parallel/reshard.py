"""Resharding between parallelism layouts (the shuffle analog).

The reference switches distribution layouts with Spark shuffles: a
``Window.partitionBy(key)`` stage hash-shuffles by key, a skew-bucketed
stage re-shuffles by (key, bracket) (tsdf.py:164-190, 549-558).  The
TPU-native equivalent is moving a packed ``[K, L]`` batch between

* **series layout** ``P('series', None)`` — each device owns whole
  series (the data-parallel layout every per-key op wants), and
* **time layout** ``P(None, 'time')`` or ``P('series', 'time')`` — each
  device owns a time slice (the sequence-parallel layout the halo
  kernels in :mod:`tempo_tpu.parallel.halo` want for series too long
  for one device),

with ICI collectives instead of a network shuffle.  Two entry points:

* :func:`reshard` — declarative: hand XLA the target sharding and let
  it plan the collectives (the normal path; XLA emits an all-to-all).
* :func:`all_to_all_series_to_time` / ``..._time_to_series`` —
  explicit ``lax.all_to_all`` inside ``shard_map``, for composition
  into hand-written distributed kernels where the collective must stay
  inside the same program as the compute it feeds.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tempo_tpu.parallel.halo import shard_map


def reshard(arr: jax.Array, mesh: Mesh, spec: P) -> jax.Array:
    """Move ``arr`` to ``NamedSharding(mesh, spec)``; XLA plans the
    ICI/DCN collectives (all-to-all for a layout switch, all-gather for
    replication)."""
    return jax.device_put(arr, NamedSharding(mesh, spec))


def _axis_sizes(mesh: Mesh, series_axis: str, time_axis: str):
    return mesh.shape[series_axis], mesh.shape[time_axis]


def all_to_all_series_to_time(
    arr: jax.Array,
    mesh: Mesh,
    series_axis: str = "series",
    time_axis: str = "time",
) -> jax.Array:
    """[K, L] sharded P(series, time) -> P(time-major on series dim):
    after the call the ``time`` axis owns contiguous series blocks and
    every device holds full rows for its block — one ``lax.all_to_all``
    over the time axis per series group.

    Use when a time-sharded pipeline stage (halo kernels) feeds a
    per-series stage (resample, FFT) without a host round-trip.
    """
    n_s, n_t = _axis_sizes(mesh, series_axis, time_axis)
    if arr.shape[0] % (n_s * n_t) != 0:
        raise ValueError(
            f"series dim {arr.shape[0]} must divide mesh {n_s}x{n_t}"
        )

    def kernel(block):  # block: [K/n_s, L/n_t] on each device
        # split local series between time-axis peers, exchange, and
        # concatenate the received time slices back into full rows
        return jax.lax.all_to_all(
            block, time_axis, split_axis=0, concat_axis=1, tiled=True
        )

    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(series_axis, time_axis),),
        out_specs=P((series_axis, time_axis), None),
    )
    return jax.jit(fn)(arr)


def all_to_all_time_to_series(
    arr: jax.Array,
    mesh: Mesh,
    series_axis: str = "series",
    time_axis: str = "time",
) -> jax.Array:
    """Inverse of :func:`all_to_all_series_to_time`: full-row blocks
    sharded over (series, time) jointly on dim 0 -> P(series, time)."""
    n_s, n_t = _axis_sizes(mesh, series_axis, time_axis)
    if arr.shape[0] % (n_s * n_t) != 0 or arr.shape[1] % n_t != 0:
        raise ValueError(f"shape {arr.shape} incompatible with {n_s}x{n_t}")

    def kernel(block):  # block: [K/(n_s*n_t), L] on each device
        return jax.lax.all_to_all(
            block, time_axis, split_axis=1, concat_axis=0, tiled=True
        )

    fn = shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P((series_axis, time_axis), None),),
        out_specs=P(series_axis, time_axis),
    )
    return jax.jit(fn)(arr)
