"""Persisted tuned-knob profiles: the autotuner's output artifact.

A profile is one CRC'd JSON document produced by the sweep harness
(:mod:`tempo_tpu.tune.harness`) recording, per (device kind, shape
class), the measured knob winners and their rates, plus the measured
cost-model inputs (the image's real stream rate instead of the BENCH r5
TPU prior).  Consumption is strictly *priors, not laws*:

* an explicitly-set ``TEMPO_TPU_*`` env knob always wins over the
  profile (the knob readers in ``ops/pallas_stream.py``,
  ``ops/pallas_window.py``, ``ops/pallas_merge.py`` and
  ``serve/executor.py`` consult :func:`knob_value` only when their env
  knob is unset);
* the cost model overlays ``measured`` between its hard-coded priors
  and any per-process :func:`tempo_tpu.plan.cost.set_measured` call;
* :func:`stamp` folds the active profile's CRC into
  ``cost.fingerprint()`` and therefore into the executable-cache key —
  swapping profiles re-plans, it never replays an executable built
  under the other profile's knobs.

**Foreign-profile refusal by name** (the PR-14 convention): a profile
is keyed by ``(device_kind, jaxlib)``.  Loading one whose fingerprint
does not match the running process — or whose CRC does not match its
payload — is *refused* with a message naming the path and both
fingerprints, and the process falls back to the built-in defaults.  A
refused profile never half-applies.

``TEMPO_TPU_TUNE_PROFILE`` points at an explicit profile path, or
``off`` disables profile loading entirely; unset resolves to the
checked-in per-device-kind profile under ``tempo_tpu/tune/profiles/``
(the CPU-image profile ships in-tree, produced by the harness itself).
"""

from __future__ import annotations

import json
import logging
import math
import os
import re
import threading
import zlib
from typing import Dict, Optional

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1

#: knobs a profile may tune; anything else in a ``knobs`` section is
#: refused at load (a profile must never smuggle undeclared behaviour)
TUNABLE_KNOBS = (
    "TEMPO_TPU_DMA_BUFFERS",
    "TEMPO_TPU_PACK_COLS",
    "TEMPO_TPU_JOIN_CHUNK_LANES",
    "TEMPO_TPU_STREAM_MAX_ROWS",
    "TEMPO_TPU_MEGACORE",
    "TEMPO_TPU_SERVE_BATCH_ROWS",
    "TEMPO_TPU_INGEST_RING",
    "TEMPO_TPU_STITCH_MAX_OPS",
    "TEMPO_TPU_SERVE_COALESCE_S",
)

#: the few tunable knobs whose values are (finite) floats, not ints —
#: everything else in a profile's ``knobs`` section must be an integer
FLOAT_KNOBS = ("TEMPO_TPU_SERVE_COALESCE_S",)


class TuneProfileError(ValueError):
    """A profile that cannot be applied, with the reason and the path
    in the message (corrupt payload, foreign fingerprint, undeclared
    knob).  The lazy loader downgrades this to a one-shot warning and
    falls back to defaults; ``load(strict=True)`` (the CLI, the tests)
    re-raises."""


def runtime_fingerprint() -> Dict[str, str]:
    """What a profile is keyed by: the device kind the knobs were
    measured on and the jaxlib that compiled the measured kernels (a
    jaxlib upgrade can move every crossover)."""
    import jax
    import jaxlib.version as jaxlib_version

    return {
        "device_kind": str(jax.devices()[0].device_kind),
        "jaxlib": str(jaxlib_version.__version__),
    }


def _slug(device_kind: str) -> str:
    return re.sub(r"[^a-z0-9]+", "-", device_kind.lower()).strip("-")


def default_path(device_kind: Optional[str] = None) -> str:
    """The checked-in profile location for ``device_kind`` (default:
    the running process's device kind)."""
    if device_kind is None:
        device_kind = runtime_fingerprint()["device_kind"]
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "profiles", f"{_slug(device_kind)}.json")


def payload_crc(payload: dict) -> int:
    """CRC-32 of the canonical JSON rendering of ``payload`` (the
    profile document without its own ``crc`` field)."""
    body = {k: v for k, v in payload.items() if k != "crc"}
    return zlib.crc32(
        json.dumps(body, sort_keys=True, separators=(",", ":")).encode())


def write(payload: dict, path: str) -> str:
    """Persist a profile document atomically with its CRC stamped in.
    The payload must already carry ``format_version``/``fingerprint``;
    the harness is the only sanctioned producer."""
    payload = dict(payload)
    payload["crc"] = payload_crc(payload)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


def validate(payload: dict, path: str,
             fingerprint: Optional[Dict[str, str]] = None) -> dict:
    """CRC + fingerprint + schema checks; raises
    :class:`TuneProfileError` naming the path and the mismatch."""
    if not isinstance(payload, dict) or "crc" not in payload:
        raise TuneProfileError(
            f"tuned profile {path!r} refused: no CRC stamp "
            f"(not a harness-produced profile)")
    want = payload_crc(payload)
    if int(payload["crc"]) != want:
        raise TuneProfileError(
            f"tuned profile {path!r} refused: CRC mismatch "
            f"(stamped {payload['crc']}, payload {want}) — the file is "
            f"corrupt or hand-edited; re-run `python -m tempo_tpu.tune`")
    if payload.get("format_version") != FORMAT_VERSION:
        raise TuneProfileError(
            f"tuned profile {path!r} refused: format_version "
            f"{payload.get('format_version')!r} != {FORMAT_VERSION}")
    fp = fingerprint or runtime_fingerprint()
    got = payload.get("fingerprint") or {}
    for key in ("device_kind", "jaxlib"):
        if got.get(key) != fp[key]:
            raise TuneProfileError(
                f"tuned profile {path!r} refused: foreign fingerprint — "
                f"profile {key}={got.get(key)!r}, this process "
                f"{key}={fp[key]!r}; profiles are measured artifacts "
                f"and never apply across {key}s (re-tune here)")
    for section in [payload.get("knobs") or {}] + [
            (c.get("knobs") or {}) for c in
            (payload.get("classes") or {}).values()
            if isinstance(c, dict)]:
        for name, value in section.items():
            if name not in TUNABLE_KNOBS:
                raise TuneProfileError(
                    f"tuned profile {path!r} refused: {name!r} is not a "
                    f"tunable knob ({', '.join(TUNABLE_KNOBS)})")
            # tunable knobs are integer-valued (FLOAT_KNOBS: finite
            # float): refuse malformed values HERE, by name, so a bad
            # profile never half-applies and then crashes inside a
            # knob reader mid-kernel-build
            if name in FLOAT_KNOBS:
                if isinstance(value, bool) \
                        or not isinstance(value, (int, float)) \
                        or not math.isfinite(value):
                    raise TuneProfileError(
                        f"tuned profile {path!r} refused: knob "
                        f"{name!r} has non-finite-float value "
                        f"{value!r} ({type(value).__name__})")
            elif isinstance(value, bool) or not isinstance(value, int):
                raise TuneProfileError(
                    f"tuned profile {path!r} refused: knob {name!r} has "
                    f"non-integer value {value!r} "
                    f"({type(value).__name__}) — tunable knobs are "
                    f"integers")
    from tempo_tpu.plan import cost as plan_cost

    # NOT |{"join_chunk_lanes"} (unlike cost.set_measured, whose
    # overlay applies last and wins): params() recomputes that key
    # from env -> profile KNOBS -> default after the measured overlay,
    # so a measured join_chunk_lanes would validate and then be
    # silently clobbered — the knobs section is its sanctioned channel
    known = set(plan_cost.PRIORS)
    for name, value in (payload.get("measured") or {}).items():
        if name not in known:
            raise TuneProfileError(
                f"tuned profile {path!r} refused: measured input "
                f"{name!r} is not a cost-model input "
                f"({sorted(known)})")
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            raise TuneProfileError(
                f"tuned profile {path!r} refused: measured input "
                f"{name!r} has non-numeric value {value!r} "
                f"({type(value).__name__})")
    return payload


# ----------------------------------------------------------------------
# lazy loader — memoized per TEMPO_TPU_TUNE_PROFILE value
# ----------------------------------------------------------------------

_lock = threading.Lock()
#: {"env": <knob string at load>, "profile": dict|None, "path": str|None}
_cache: Optional[dict] = None  # guarded-by: _lock


def _resolve():
    """(path, explicit) from ``TEMPO_TPU_TUNE_PROFILE``; (None, False)
    when loading is off or no checked-in profile exists."""
    from tempo_tpu import config

    val = (config.get("TEMPO_TPU_TUNE_PROFILE") or "").strip()
    if val.lower() in ("off", "0", "none"):
        return None, False
    if val:
        return val, True
    path = default_path()
    return (path if os.path.exists(path) else None), False


def load(strict: bool = False):
    """The active profile document, or None (loading off, no profile
    for this device kind, or a refused profile).  Memoized per
    ``TEMPO_TPU_TUNE_PROFILE`` value — flipping the knob mid-process
    (the bench's tuned-vs-default flip, the tests) reloads on the next
    read; :func:`reload` drops the memo outright.  Refusals warn ONCE
    per memo generation and fall back to defaults; ``strict=True``
    re-raises them (the CLI and the lifecycle tests)."""
    global _cache
    from tempo_tpu import config

    env_now = config.get("TEMPO_TPU_TUNE_PROFILE") or ""
    with _lock:
        if _cache is not None and _cache["env"] == env_now:
            if strict and _cache.get("error") is not None:
                raise TuneProfileError(_cache["error"])
            return _cache["profile"]
    profile, error, path = None, None, None
    try:
        path, explicit = _resolve()
        if path is not None:
            if not os.path.exists(path):
                raise TuneProfileError(
                    f"tuned profile {path!r} refused: file does not "
                    f"exist (TEMPO_TPU_TUNE_PROFILE points at it "
                    f"explicitly)" if explicit else
                    f"tuned profile {path!r} vanished")
            with open(path) as f:
                raw = json.load(f)
            profile = validate(raw, path)
    except (TuneProfileError, OSError, ValueError) as e:
        error = str(e)
        logger.warning("%s — falling back to built-in knob defaults",
                       error)
        profile = None
    with _lock:
        _cache = {"env": env_now, "profile": profile, "path": path,
                  "error": error}
    if strict and error is not None:
        raise TuneProfileError(error)
    return profile


def reload() -> None:
    """Drop the memoized profile (tests, the bench's in-process
    tuned-vs-default flips)."""
    global _cache
    with _lock:
        _cache = None


def active_path() -> Optional[str]:
    """The path of the currently-loaded profile (None when none)."""
    with _lock:
        snap = _cache
    return snap["path"] if (snap and snap["profile"]) else None


def knob_value(name: str, shape_class: Optional[str] = None):
    """The tuned value for knob ``name`` — the *profile prior* the knob
    readers fall back to when their env knob is unset.  With
    ``shape_class`` the per-class winner is preferred over the merged
    knob set.  None when no profile is loaded or the profile does not
    tune this knob."""
    prof = load()
    if prof is None:
        return None
    if shape_class is not None:
        cls = (prof.get("classes") or {}).get(shape_class) or {}
        if name in (cls.get("knobs") or {}):
            return cls["knobs"][name]
    return (prof.get("knobs") or {}).get(name)


def measured() -> Dict[str, float]:
    """The profile's measured cost-model inputs (``{}`` when none):
    overlaid by ``plan/cost.params()`` between the hard-coded priors
    and any ``set_measured`` call."""
    prof = load()
    if prof is None:
        return {}
    return {k: float(v) for k, v in (prof.get("measured") or {}).items()}


def stamp() -> Optional[float]:
    """The active profile's CRC as a float (exact: CRC-32 < 2**53), or
    None when no profile is loaded — folded into ``cost.fingerprint()``
    / ``cost.params()`` so a profile swap re-plans instead of replaying
    executables built under the other profile's knobs."""
    prof = load()
    return None if prof is None else float(prof["crc"])
