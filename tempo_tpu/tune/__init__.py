"""Self-tuning kernels: the persistent autotuner (ROADMAP item 5).

``python -m tempo_tpu.tune`` sweeps the registered knob space
(:mod:`tempo_tpu.tune.space`) per (device kind, shape class) in child
processes, gates every candidate on a bitwise value audit, and
persists the winners as a CRC'd profile
(:mod:`tempo_tpu.tune.profile`).  The package's read faces below are
what the engine picks consume at run time — an explicitly-set env knob
always wins, the profile is the prior underneath it, and the built-in
default is the floor:

* :func:`knob_value` — tuned knob priors for the readers in
  ``ops/pallas_stream.py`` / ``ops/pallas_window.py`` /
  ``ops/pallas_merge.py`` / ``serve/executor.py``;
* :func:`measured` — measured cost-model inputs, overlaid by
  ``plan/cost.params()`` under any ``cost.set_measured`` call;
* :func:`stamp` — the profile CRC folded into ``cost.fingerprint()``
  and therefore the executable-cache key: a profile swap re-plans,
  never replays.

Import-light on purpose: jax is only touched when a profile is
actually resolved (the fingerprint check needs the device kind).
"""

from tempo_tpu.tune.profile import (   # noqa: F401
    TUNABLE_KNOBS,
    TuneProfileError,
    active_path,
    default_path,
    knob_value,
    load,
    measured,
    reload,
    runtime_fingerprint,
    stamp,
)

__all__ = [
    "TUNABLE_KNOBS", "TuneProfileError", "active_path", "default_path",
    "knob_value", "load", "measured", "reload", "runtime_fingerprint",
    "stamp",
]
