"""The sweep harness: measure the knob space, gate on bitwise audits,
persist the winners as a profile.

Every candidate point runs in a **child process** (``bench.py
--only-tune-probe <probe>`` with the candidate knobs in the child's
environment — the same isolation discipline as ``bench.py``'s
``_config_subprocess``/``bench_pipelined``): a Mosaic OOM, an infeasible
ring depth or a compiler hang kills the child, never the tuner.  Each
probe reports a rate AND a CRC-32 digest of the full kernel outputs on
deterministic data; the **bitwise value-audit gate** compares every
candidate's digest against the all-defaults baseline and rejects any
mismatch — a knob setting that changes result bits is *rejected*, not
just slow.  Mismatches on ``bitwise_neutral`` axes are additionally
recorded as audit FAILURES (a kernel-identity regression; the smoke CLI
exits nonzero on them).

The walk is per-class coordinate descent with **dominated-point
pruning**: axes are swept in declared order from the all-defaults
incumbent; a ladder is abandoned after :data:`PRUNE_AFTER` consecutive
candidates that fail to beat the best point by :data:`MARGIN` (the
ladders are monotone resource knobs — once deeper rings/wider packs
stop paying, the rest of the ladder is dominated).  This keeps the
sweep at O(sum of ladder lengths) probes instead of the cartesian
product.

Child-to-child timing noise is biased AGAINST flapping the profile:
the baseline rate is the MAX of two probes and a would-be winner must
beat it by the margin on the MIN of two probes (its own confirmation
re-probe included), so a knob that is structurally inert on this
backend keeps its default even when scheduler noise hands one child a
lucky run — the defaults stay the incumbent unless the win reproduces.

Classes marked ``requires_tpu`` on a non-TPU backend are recorded
``hardware_gated`` with the reason — runnable unchanged on real
hardware, never faked.
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional, Tuple

from tempo_tpu.tune import profile as tune_profile
from tempo_tpu.tune import space as tune_space

logger = logging.getLogger(__name__)

#: a candidate must beat the incumbent by this fraction to win (noise
#: guard: sub-2% wiggles must not flap the checked-in profile)
MARGIN = 0.02

#: consecutive non-winning candidates before a ladder is pruned
PRUNE_AFTER = 2


def _bench_path() -> str:
    import tempo_tpu

    root = os.path.dirname(os.path.dirname(
        os.path.abspath(tempo_tpu.__file__)))
    return os.path.join(root, "bench.py")


def run_probe(probe: str, knobs: Dict[str, object],
              smoke: bool = False,
              timeout: Optional[float] = None) -> Dict:
    """One measurement child: ``bench.py --only-tune-probe <probe>``
    with exactly ``knobs`` applied (every other tunable knob cleared —
    an inherited env knob must not contaminate the baseline) and
    profile loading off (the sweep measures raw knob values).  Returns
    the probe's JSON record, or ``{"error": ...}`` when the child died
    — the caller treats a dead child as an infeasible point."""
    from tempo_tpu import config

    overrides: Dict[str, Optional[str]] = {
        k: None for k in tune_profile.TUNABLE_KNOBS}
    for k, v in knobs.items():
        if v is not None:
            overrides[k] = str(v)
    overrides["TEMPO_TPU_TUNE_PROFILE"] = "off"
    # set OR clear: an inherited TEMPO_BENCH_SMOKE must not shrink a
    # full sweep's probes to smoke shapes (the profile would be
    # measured on tiny data yet stamped "smoke": false)
    overrides["TEMPO_BENCH_SMOKE"] = "1" if smoke else None
    env = config.child_env(overrides)
    if timeout is None:
        timeout = 300 if smoke else 1200
    try:
        proc = subprocess.run(
            [sys.executable, _bench_path(), "--only-tune-probe", probe],
            capture_output=True, text=True, timeout=timeout, env=env,
        )
    except subprocess.TimeoutExpired:
        return {"error": f"probe {probe} timed out after {timeout}s"}
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        return {"error": f"probe {probe} child rc={proc.returncode}: "
                         f"{' | '.join(tail)}"}
    try:
        return json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError) as e:
        return {"error": f"probe {probe} emitted no JSON record "
                         f"({type(e).__name__}: {e})"}


def _backend() -> str:
    import jax

    return jax.default_backend()


def sweep_class(cls: tune_space.ShapeClass, smoke: bool = False,
                probe_fn=run_probe) -> Tuple[Dict, List[Dict]]:
    """Sweep one shape class; returns (class record, audit failures).
    ``probe_fn`` is injectable for the harness unit tests."""
    if cls.requires_tpu and _backend() != "tpu":
        reason = (f"requires TPU (backend is {_backend()!r}): the "
                  f"Mosaic kernels this class tunes cannot run here — "
                  f"sweep runs unchanged on real hardware")
        logger.info("tune: class %s hardware-gated: %s", cls.name, reason)
        return {"hardware_gated": reason}, []

    t0 = time.time()
    assign: Dict[str, object] = {}
    base = probe_fn(cls.probe, assign, smoke=smoke)
    if "error" in base:
        return {"error": f"baseline probe failed: {base['error']}"}, []
    digest0 = base["digest"]
    # incumbent bias: the baseline rate is the max of TWO probes (an
    # unlucky-slow baseline child must not hand every candidate a
    # fake win); the digest comes from the first, and only the first
    # measures the saxpy stream rate (the marker rides the child env
    # like the knobs do — the re-probe's copy would be discarded)
    base2 = probe_fn(cls.probe, {"TEMPO_BENCH_TUNE_NO_SAXPY": 1},
                     smoke=smoke)
    probes = 2
    if "error" not in base2:
        if base2.get("digest") != digest0:
            # the default-knob kernel itself is nondeterministic: every
            # candidate audit against digest0 would be meaningless (a
            # bits-changing knob could match one baseline run and a
            # legitimate one could miss) — fail the class loudly, never
            # sweep on a baseline the harness has already seen flap
            reason = (f"baseline nondeterminism: two default-knob "
                      f"probes of class {cls.name} disagree (digests "
                      f"{digest0} vs {base2.get('digest')}) — the "
                      f"kernel output is not deterministic and no "
                      f"candidate can be audited against it")
            return {"error": reason}, [
                {"class": cls.name, "knobs": {}, "reason": reason}]
        base["rows_per_sec"] = max(base["rows_per_sec"],
                                   base2["rows_per_sec"])
    best = dict(base)
    best_knobs: Dict[str, object] = {}
    rejected: List[Dict] = []
    failures: List[Dict] = []
    for axis in cls.axes:
        misses = 0
        for v in tune_space.axis_values(axis, smoke)[1:]:
            if misses >= PRUNE_AFTER:
                logger.info(
                    "tune: %s ladder %s pruned after %d dominated "
                    "points", cls.name, axis.knob, misses)
                break
            cand = {k: x for k, x in {**assign, axis.knob: v}.items()
                    if x is not None}
            rec = probe_fn(cls.probe, cand, smoke=smoke)
            probes += 1
            if "error" in rec:
                rejected.append({"knobs": cand, "reason": rec["error"]})
                misses += 1
                continue
            if rec["digest"] != digest0:
                reason = (f"bitwise-audit: output digest {rec['digest']} "
                          f"!= default-knob digest {digest0}")
                rejected.append({"knobs": cand, "reason": reason})
                if axis.bitwise_neutral:
                    # a contract-bitwise knob changed result bits: an
                    # identity regression, not a legitimate rejection
                    failures.append({"class": cls.name, "knobs": cand,
                                     "reason": reason})
                continue
            if rec["rows_per_sec"] > best["rows_per_sec"] * (1 + MARGIN):
                if not axis.bitwise_neutral:
                    # a legality-ceiling axis can never legitimately
                    # win: a same-bits candidate left the engine pick
                    # unchanged, and the ceiling is unread inside the
                    # chosen engine — the measured "win" is child
                    # scheduler noise.  Shipping a changed ceiling
                    # could flip the engine (and the f32 rounding
                    # order) at shapes the probe never ran, so the
                    # default stands; the axis rides the sweep purely
                    # as the audit surface that proves bits-changing
                    # values get rejected.
                    rejected.append({
                        "knobs": cand,
                        "reason": "legality-ceiling axis: same-bits "
                                  "candidate is performance-inert at "
                                  "the probe shape (the measured win "
                                  "is noise) and a changed ceiling "
                                  "could flip the engine at unprobed "
                                  "shapes — the default stands"})
                    misses += 1
                    continue
                # confirmation re-probe: the win must REPRODUCE (min
                # of the two candidate rates still beats by margin) or
                # it is scheduler noise and the incumbent stands
                rec2 = probe_fn(cls.probe, cand, smoke=smoke)
                probes += 1
                confirmed = ("error" not in rec2
                             and rec2.get("digest") == digest0
                             and min(rec["rows_per_sec"],
                                     rec2["rows_per_sec"])
                             > best["rows_per_sec"] * (1 + MARGIN))
                if not confirmed:
                    misses += 1
                    continue
                rec = dict(rec)
                rec["rows_per_sec"] = min(rec["rows_per_sec"],
                                          rec2["rows_per_sec"])
                best = rec
                assign[axis.knob] = v
                best_knobs = {k: x for k, x in assign.items()
                              if x is not None}
                misses = 0
            else:
                misses += 1
    record = {
        "knobs": best_knobs,
        "rows_per_sec": best["rows_per_sec"],
        "default_rows_per_sec": base["rows_per_sec"],
        "speedup": round(best["rows_per_sec"]
                         / max(base["rows_per_sec"], 1e-9), 3),
        "t_iter": best.get("t_iter"),
        "bytes_per_iter": best.get("bytes_per_iter"),
        "probes": probes,
        "rejected": rejected,
        "sweep_seconds": round(time.time() - t0, 1),
        "audit": "bitwise (every kept candidate's output digest == "
                 "the default-knob digest on deterministic data)",
    }
    if base.get("stream_gbps"):
        record["stream_gbps"] = base["stream_gbps"]
    return record, failures


def sweep(class_names=None, smoke: bool = False,
          out_path: Optional[str] = None,
          probe_fn=run_probe) -> Tuple[Dict, List[Dict]]:
    """Run the whole sweep and assemble the profile document.  Returns
    ``(payload, audit_failures)``; the payload is written to
    ``out_path`` when given (CRC stamped by :func:`profile.write`)."""
    classes = tune_space.classes(class_names, smoke=smoke)
    records: Dict[str, Dict] = {}
    failures: List[Dict] = []
    for cls in classes:
        logger.info("tune: sweeping class %s (%s)", cls.name, cls.doc)
        rec, fails = sweep_class(cls, smoke=smoke, probe_fn=probe_fn)
        records[cls.name] = rec
        failures.extend(fails)

    merged: Dict[str, object] = {}
    for cls in classes:
        rec = records.get(cls.name) or {}
        for knob in cls.owns:
            if knob in (rec.get("knobs") or {}):
                merged[knob] = rec["knobs"][knob]

    measured: Dict[str, float] = {}
    for name in ("stream_dense", "stream_medium"):
        gbps = (records.get(name) or {}).get("stream_gbps")
        if gbps:
            # the image's real saxpy stream rate replaces the BENCH r5
            # TPU prior — the cost model's decisions (all bitwise-free)
            # then argmin over what THIS image can actually move
            measured["hbm_stream_rate"] = float(gbps) * 1e9
            break
    jc = records.get("join_chunk") or {}
    if jc.get("t_iter") and jc.get("bytes_per_iter"):
        measured["join_chunked_rate"] = (
            float(jc["bytes_per_iter"]) / float(jc["t_iter"]))

    payload = {
        "format_version": tune_profile.FORMAT_VERSION,
        "fingerprint": tune_profile.runtime_fingerprint(),
        "created_unix": int(time.time()),
        "smoke": bool(smoke),
        "margin": MARGIN,
        "classes": records,
        "knobs": merged,
        "measured": measured,
    }
    if failures:
        payload["audit_failures"] = failures
    if out_path:
        tune_profile.write(payload, out_path)
        logger.info("tune: profile written to %s", out_path)
    return payload, failures
