"""The registered knob space the autotuner sweeps.

Every axis here is a ``TEMPO_TPU_*`` knob declared in
``tempo_tpu/config.py`` whose value is a *performance* choice — the
sweep measures each candidate in a child process and the bitwise audit
gate decides whether the candidate is even admissible:

* ``bitwise_neutral=True`` axes (DMA ring depth, pack width, megacore
  partitioning, serve micro-batch rows, join chunk width) carry a
  kernel-identity CONTRACT: every value must produce bit-identical
  results (pinned by the round-6/3/12 test matrices).  A digest
  mismatch on such an axis is an identity REGRESSION — the sweep
  records it and ``python -m tempo_tpu.tune --smoke`` exits nonzero.
* ``bitwise_neutral=False`` axes (``TEMPO_TPU_STREAM_MAX_ROWS``) gate
  which engine is *legal* for a shape; a candidate that flips the
  engine changes f32 rounding order and is *rejected by the audit* —
  that is the gate working, not a failure.  Such an axis can never
  crown a winner either: a same-bits candidate left the engine pick
  unchanged and the ceiling is unread inside the chosen engine, so any
  measured win is child noise — and a shipped ceiling could flip the
  engine at shapes the probe never ran.  The axis rides the sweep
  purely as the audit surface; the default ceiling always stands.

Shape classes mirror the regimes the bench measures: the dense/medium
streaming stats kernels (configs 2b's densities), the column-packed
streaming kernel, the fused join+stats+EMA chain (configs 1-3's
composite), the lane-chunked AS-OF join (TPU-only: the Mosaic kernel),
the serving micro-batch executor, and the PR 17 dispatch-floor planes
(the slab-pipeline ring depth, the whole-chain stitch length, and the
cohort dispatch-coalescing window).  Each knob has exactly ONE
owning class (``owns``) whose winner feeds the profile's merged knob
set — the other classes sweeping the same knob are cross-checks whose
results are recorded but never merged.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple


class Axis(NamedTuple):
    """One knob ladder, walked in declared order from the default
    (``values[0]`` — the incumbent, measured once as the class
    baseline).  ``None`` as a value means "unset" (the knob's automatic
    choice).  ``smoke_values`` is the clipped ladder of the CI smoke
    sweep."""

    knob: str
    values: Tuple
    smoke_values: Tuple
    bitwise_neutral: bool = True


class ShapeClass(NamedTuple):
    name: str
    probe: str              # bench.py --only-tune-probe <probe>
    axes: Tuple[Axis, ...]
    owns: Tuple[str, ...]   # knobs whose winner feeds profile["knobs"]
    requires_tpu: bool = False
    doc: str = ""


SPACE: Tuple[ShapeClass, ...] = (
    ShapeClass(
        "stream_dense", "stream_dense",
        axes=(
            Axis("TEMPO_TPU_DMA_BUFFERS", (2, 3, 4, 6, 8), (2, 4)),
            Axis("TEMPO_TPU_MEGACORE", (1, 0), (1, 0)),
        ),
        owns=("TEMPO_TPU_DMA_BUFFERS", "TEMPO_TPU_MEGACORE"),
        doc="config 2b's ~50 Hz density: the streaming window engine's "
            "home regime — owns the DMA ring depth + megacore knobs"),
    ShapeClass(
        "stream_medium", "stream_medium",
        axes=(
            Axis("TEMPO_TPU_DMA_BUFFERS", (2, 3, 4, 6, 8), (2, 4)),
            Axis("TEMPO_TPU_STREAM_MAX_ROWS", (16384, 8192, 32768),
                 (16384, 32768), bitwise_neutral=False),
        ),
        owns=("TEMPO_TPU_STREAM_MAX_ROWS",),
        doc="~10 Hz density near the engine crossover — owns the "
            "stream-engine row ceiling (audit-gated: a value that flips "
            "the engine changes bits and is rejected; same-bits values "
            "never win either, so the default ceiling always ships)"),
    ShapeClass(
        "packed_stream", "packed_stream",
        axes=(
            Axis("TEMPO_TPU_PACK_COLS", (None, 8, 4, 2, 1), (None, 2)),
        ),
        owns=("TEMPO_TPU_PACK_COLS",),
        doc="C=4 column-packed streaming stats (one key-plane read per "
            "pack) — owns the pack-width cap"),
    ShapeClass(
        "fused_chain", "fused_chain",
        axes=(
            Axis("TEMPO_TPU_DMA_BUFFERS", (2, 4), (2, 4)),
        ),
        owns=(),
        doc="the fused asof+stats+EMA composite — a cross-check that "
            "the stream-class winners hold on the whole chain (owns "
            "nothing; its sweep is recorded, never merged)"),
    ShapeClass(
        "join_chunk", "join_chunk",
        axes=(
            Axis("TEMPO_TPU_JOIN_CHUNK_LANES",
                 (None, 4096, 8192, 16384, 32768), (None, 4096)),
        ),
        owns=("TEMPO_TPU_JOIN_CHUNK_LANES",),
        requires_tpu=True,
        doc="the lane-chunked streaming AS-OF join (Mosaic kernel) — "
            "TPU-only; on other backends the class is recorded "
            "hardware-gated, not faked"),
    ShapeClass(
        "serve_batch", "serve_batch",
        axes=(
            Axis("TEMPO_TPU_SERVE_BATCH_ROWS", (64, 16, 32, 128, 256),
                 (64, 32)),
        ),
        owns=("TEMPO_TPU_SERVE_BATCH_ROWS",),
        doc="the serving micro-batch executor under a deterministic "
            "tick load — owns the per-series micro-batch row cap"),
    ShapeClass(
        "ingest_sweep", "ingest_sweep",
        axes=(
            Axis("TEMPO_TPU_INGEST_RING", (2, 1, 4, 8), (2, 4)),
        ),
        owns=("TEMPO_TPU_INGEST_RING",),
        doc="the three-stage slab pipeline (io/ingest.sweep_slabs: "
            "decode thread / in-order compute / drain thread) — owns "
            "the slab-buffer ring depth; any depth is bitwise "
            "identical by construction (in-order consumption), so a "
            "digest mismatch is an ordering regression"),
    ShapeClass(
        "stitched_chain", "stitched_chain",
        axes=(
            Axis("TEMPO_TPU_STITCH_MAX_OPS", (8, 1, 4, 16), (8, 1)),
        ),
        owns=("TEMPO_TPU_STITCH_MAX_OPS",),
        doc="the whole-chain program stitcher (plan/stitch.py) on a "
            "resample->EMA->range_stats planned chain — owns the max "
            "stitch run length; every value is bitwise (stitch "
            "boundaries are optimization_barrier-pinned, so stitched "
            "== per-op chain bit-for-bit)"),
    ShapeClass(
        "serve_cohort", "serve_cohort",
        axes=(
            Axis("TEMPO_TPU_SERVE_COALESCE_S",
                 (0.002, 0.0, 0.001, 0.004, 0.008), (0.002, 0.0)),
        ),
        owns=("TEMPO_TPU_SERVE_COALESCE_S",),
        doc="the cohort executor's dispatch-coalescing window under a "
            "deterministic Poisson tick load — owns the only "
            "float-valued knob (profile.FLOAT_KNOBS); the window only "
            "moves the micro-batch split, never per-(slot,row) state "
            "math, so every value is bitwise"),
)


def classes(names=None, smoke: bool = False):
    """The shape classes to sweep: all of them, or the named subset.
    The smoke sweep defaults to one stream class + the serve class —
    the CI gate's 'tiny shape' coverage of both probe families."""
    if names:
        by_name = {c.name: c for c in SPACE}
        unknown = [n for n in names if n not in by_name]
        if unknown:
            raise KeyError(
                f"unknown shape class(es) {unknown}: "
                f"known = {[c.name for c in SPACE]}")
        return tuple(by_name[n] for n in names)
    if smoke:
        return tuple(c for c in SPACE
                     if c.name in ("stream_medium", "serve_batch"))
    return SPACE


def axis_values(axis: Axis, smoke: bool = False) -> Tuple:
    return axis.smoke_values if smoke else axis.values
