"""``python -m tempo_tpu.tune`` — run the autotuner sweep.

Modes:

* (default) full sweep of every shape class, profile written to the
  checked-in per-device-kind location (``--out`` overrides);
* ``--smoke`` — the CI gate: tiny shapes (``TEMPO_BENCH_SMOKE`` in the
  probe children), the clipped smoke ladders, profile written to
  ``--out`` when given (a temp artifact otherwise, never the
  checked-in path).  **Exits nonzero on any bitwise-audit failure** —
  a contract-bitwise knob (DMA depth, pack width, megacore, serve
  batch rows, chunk width) that changed result bits is a kernel
  identity regression, and the gate's whole point;
* ``--show`` — print the profile the current process would load (after
  ``TEMPO_TPU_TUNE_PROFILE`` resolution + refusal checks) and exit.

The summary table and progress go to stderr; stdout carries ONE JSON
line (the sweep record) so drivers can parse it like the bench.
"""

from __future__ import annotations

import argparse
import json
import sys

from tempo_tpu.tune import harness, profile


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tempo_tpu.tune",
        description="sweep the registered knob space and persist a "
                    "tuned profile")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny-shape CI sweep; exit nonzero on any "
                         "bitwise-audit failure")
    ap.add_argument("--out", default=None,
                    help="profile output path (default: the checked-in "
                         "per-device-kind location; --smoke defaults "
                         "to not persisting)")
    ap.add_argument("--classes", default=None,
                    help="comma-separated shape-class subset")
    ap.add_argument("--show", action="store_true",
                    help="print the profile this process would load "
                         "and exit")
    args = ap.parse_args(argv)

    if args.show:
        try:
            prof = profile.load(strict=True)
        except profile.TuneProfileError as e:
            print(str(e), file=sys.stderr)
            return 2
        if prof is None:
            print("no tuned profile (TEMPO_TPU_TUNE_PROFILE="
                  "off/unset and no checked-in profile for this "
                  "device kind)", file=sys.stderr)
            return 0
        print(json.dumps(prof, indent=1, sort_keys=True))
        return 0

    names = ([c.strip() for c in args.classes.split(",") if c.strip()]
             if args.classes else None)
    out_path = args.out
    if out_path is None and not args.smoke:
        out_path = profile.default_path()
    payload, failures = harness.sweep(
        class_names=names, smoke=args.smoke, out_path=out_path)

    for name, rec in payload["classes"].items():
        if "hardware_gated" in rec:
            print(f"[tune] {name}: HARDWARE-GATED — "
                  f"{rec['hardware_gated']}", file=sys.stderr)
        elif "error" in rec:
            print(f"[tune] {name}: ERROR — {rec['error']}",
                  file=sys.stderr)
        else:
            print(f"[tune] {name}: {rec['rows_per_sec']:,.0f} rows/s "
                  f"(default {rec['default_rows_per_sec']:,.0f}, "
                  f"x{rec['speedup']}) knobs={rec['knobs']} "
                  f"[{rec['probes']} probes, "
                  f"{len(rec['rejected'])} rejected]", file=sys.stderr)
    if out_path:
        print(f"[tune] profile written: {out_path}", file=sys.stderr)
    for f in failures:
        print(f"[tune] BITWISE-AUDIT FAILURE: class {f['class']} "
              f"knobs {f['knobs']}: {f['reason']}", file=sys.stderr)
    print(json.dumps(payload, sort_keys=True))
    if failures:
        return 1
    # the CI gate must not pass green on a broken sweep: any errored
    # class fails --smoke (the smoke probes are tiny deterministic
    # shapes — a dead child there is a regression, not flakiness); a
    # full sweep tolerates individual errors (the child-isolation
    # discipline working, recorded in the profile) but fails when NO
    # class measured anything at all
    errored = [n for n, rec in payload["classes"].items()
               if "error" in rec]
    measured_any = any("rows_per_sec" in rec
                       for rec in payload["classes"].values())
    if errored and (args.smoke or not measured_any):
        print(f"[tune] SWEEP BROKEN: class(es) errored: "
              f"{', '.join(errored)}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
