"""Plan IR: deferred op nodes for TSDF / DistributedTSDF chains.

A plan is a small DAG of :class:`Node`\\ s.  Source nodes carry the
actual frame as an execution-only ``payload``; op nodes carry the call
parameters in canonical (hashable, order-stable) form.  The *logical
signature* of a plan hashes only structure + parameters — two plans
recorded over different frames with the same schema and op chain share
a signature, which is exactly what lets the executable cache serve
millions of repeated queries without re-planning (ROADMAP north star).
Anything data-identity-like (shapes, dtypes, the mesh) lives in the
cache key (:func:`state_key`), not the signature.
"""

from __future__ import annotations

import hashlib
import itertools
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

#: Methods of the eager classes that record plan nodes (class name ->
#: method names).  The ``plan-registry`` analyzer rule
#: (tools/analysis/rules/plan_registry.py) keeps this registry and the
#: code in lockstep both ways: every method named here must call
#: ``_plan_record`` in its body, and every other frame-returning op
#: method of these classes must carry an explicit
#: ``# plan-ok: eager-only`` marker on its ``def`` line.
PLANNED_METHODS = {
    "TSDF": (
        "select", "selectExpr", "filter", "withColumn", "asofJoin",
        "withRangeStats", "EMA", "resample", "resampleEMA",
        "interpolate", "on_mesh",
    ),
    "DistributedTSDF": (
        "asofJoin", "withRangeStats", "EMA", "resample", "interpolate",
        "calc_bars", "fourier_transform", "withLookbackFeatures",
    ),
}

#: Ops whose execution forces a device->host materialisation (the
#: optimizer marks these explicitly in the plan; dist.py logs the same
#: barrier at execution time).
BARRIER_OPS = ("collect", "lookback_features")

_opaque_counter = itertools.count()


def canon(value):
    """Canonical hashable form of an op parameter.  Unhashable /
    identity-bearing values (callables, arrays) become unique opaque
    tokens — the node still records and executes, but the plan is
    marked uncacheable (two lambdas with equal source are not provably
    the same query)."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, np.generic) and value.shape == ():
        # numpy scalars (np.int64 window widths out of pandas/numpy
        # arithmetic are routine) collapse to the Python scalar —
        # leaving them opaque would silently mark every such plan
        # uncacheable and re-trace per call
        return canon(value.item())
    if isinstance(value, (list, tuple)):
        return tuple(canon(v) for v in value)
    if isinstance(value, dict):
        return tuple(sorted((str(k), canon(v)) for k, v in value.items()))
    return ("?opaque", next(_opaque_counter))


def is_opaque(cv) -> bool:
    if isinstance(cv, tuple):
        if len(cv) == 2 and cv[0] == "?opaque":
            return True
        return any(is_opaque(v) for v in cv)
    return False


class Node:
    """One deferred op (or source) in a plan DAG."""

    __slots__ = ("op", "params", "inputs", "payload", "objs", "ann")

    def __init__(self, op: str, params: Dict[str, object] = None,
                 inputs: Tuple["Node", ...] = (), payload=None,
                 objs: Dict[str, object] = None):
        self.op = op
        self.params: Tuple[Tuple[str, object], ...] = tuple(
            sorted((k, canon(v)) for k, v in (params or {}).items())
        )
        self.inputs = tuple(inputs)
        self.payload = payload          # source nodes: the actual frame
        self.objs = dict(objs or {})    # execution-only values (mesh, fns)
        self.ann: Dict[str, object] = {}  # optimizer annotations

    # -- structure ------------------------------------------------------

    def param(self, name: str, default=None):
        for k, v in self.params:
            if k == name:
                return v
        return default

    def is_source(self) -> bool:
        return self.op in ("source", "dist_source", "unified_scan")

    def walk(self) -> Iterable["Node"]:
        """Post-order DFS (inputs before the node), each node once."""
        seen = set()

        def rec(n):
            if id(n) in seen:
                return
            seen.add(id(n))
            for c in n.inputs:
                yield from rec(c)
            yield n

        yield from rec(self)

    def sources(self) -> List["Node"]:
        return [n for n in self.walk() if n.is_source()]

    def uncacheable(self) -> bool:
        return any(
            is_opaque(v) for n in self.walk() for _, v in n.params
        )

    def __repr__(self) -> str:
        ps = ", ".join(f"{k}={v!r}" for k, v in self.params)
        return f"Node({self.op}{': ' if ps else ''}{ps})"


def signature(root: Node) -> str:
    """Stable logical-plan signature: structure + canonical params
    (payloads excluded).  Annotated (optimized) plans fold their
    annotations in, so a rewritten plan never collides with its
    un-rewritten twin."""
    h = hashlib.sha1()
    index = {}
    for i, n in enumerate(root.walk()):
        index[id(n)] = i
        h.update(
            f"{i}:{n.op}{n.params!r}"
            f"<{tuple(index[id(c)] for c in n.inputs)}>"
            f"@{tuple(sorted((k, repr(v)) for k, v in n.ann.items()))}"
            .encode()
        )
    return h.hexdigest()[:16]


def _frame_state(frame) -> tuple:
    """Shape/dtype/mesh state of one source frame — the part of the
    cache key that invalidates compiled executables when the packed
    shapes change (shape change -> miss, by design)."""
    from tempo_tpu.dist import DistributedTSDF

    unified = getattr(frame, "_unified_state", None)
    if unified is not None:
        # a unified_scan payload (query/unified.UnifiedSource): its
        # version counter advances on every tail append / store sync,
        # so re-running a standing plan over grown data is a cache
        # MISS by construction while a same-version re-read hits
        return unified()
    if isinstance(frame, DistributedTSDF):
        return ("dist", _mesh_state(frame.mesh), frame.K_dev, frame.L,
                tuple(frame.cols), tuple(frame.host_cols),
                frame.resampled, frame.seq_col,
                # the packed layout: a series-LOCAL (jointly sharded)
                # frame compiles different stage programs than a
                # time-sharded one of the same shapes
                frame.series_axis, frame.time_axis)
    df = frame.df
    return ("host", len(df), tuple(df.columns),
            tuple(str(t) for t in df.dtypes),
            frame.ts_col, tuple(frame.partitionCols),
            frame.sequence_col or "")


def _mesh_state(mesh) -> tuple:
    if mesh is None:
        return ("default-mesh",)
    return (tuple(mesh.axis_names),
            tuple(sorted(mesh.shape.items())),
            tuple(d.id for d in mesh.devices.flat))


def state_key(root: Node) -> Optional[tuple]:
    """Executable-cache key: (logical signature, per-source
    shapes/dtypes, mesh objects referenced by the plan).  None when the
    plan is uncacheable (opaque params)."""
    if root.uncacheable():
        return None
    meshes = tuple(
        _mesh_state(n.objs["mesh"]) for n in root.walk()
        if "mesh" in n.objs
    )
    return (signature(root),
            tuple(_frame_state(n.payload) for n in root.sources()),
            meshes)


# ----------------------------------------------------------------------
# Output-schema inference (drives dead-column pruning and explain())
# ----------------------------------------------------------------------

def _range_stats_names():
    from tempo_tpu import packing

    return packing.RANGE_STATS


def output_columns(node: Node) -> Optional[List[str]]:
    """Column names this node's result exposes, or None when the op's
    output schema cannot be inferred statically (pruning then treats
    everything upstream as live)."""
    if node.op == "source":
        return list(node.payload.df.columns)
    if node.op == "unified_scan":
        return list(node.payload.columns)
    if node.op == "dist_source":
        p = node.payload
        return (list(p.partitionCols) + [p.ts_col] + list(p.cols)
                + list(p.host_cols))
    if not node.inputs:
        return None
    cols = output_columns(node.inputs[0])
    if cols is None:
        return None
    if node.op in ("on_mesh", "reshard", "checkpoint", "sql_filter"):
        return cols
    if node.op == "sql_project":
        return list(node.param("aliases", ()))
    if node.op == "select":
        sel = node.param("cols", ())
        if "*" in sel:
            return cols
        return list(sel)
    if node.op == "with_column":
        name = node.param("colName")
        return cols + ([name] if name not in cols else [])
    if node.op == "range_stats":
        pick = node.param("colsToSummarize")
        picked = list(pick) if pick else None
        if picked is None:
            return None  # "all numeric" needs dtypes; stay conservative
        return cols + [f"{s}_{c}" for c in picked
                       for s in _range_stats_names()]
    if node.op in ("ema", "ema_stream"):
        return cols + [f"EMA_{node.param('colName')}"]
    if node.op == "asof_join":
        right = output_columns(node.inputs[1])
        if right is None:
            return None
        lp = node.param("left_prefix")
        rp = node.param("right_prefix") or "right"
        ren = (lambda c: f"{lp}_{c}") if lp else (lambda c: c)
        # structural cols keep their names on the left; right side is
        # uniformly prefixed (incl. its ts col)
        return [ren(c) for c in cols] + [f"{rp}_{c}" for c in right]
    return None


def consumed_columns(node: Node) -> Optional[List[str]]:
    """Columns an op reads by name (beyond structural), or None for
    "potentially all"."""
    if node.op in ("select",):
        return list(node.param("cols", ()))
    if node.op in ("sql_project", "sql_filter"):
        # sql_compile stores the (compile-time resolved) column refs of
        # the parsed expressions in params, so pruning reads them here
        # without re-walking the ASTs
        return list(node.param("cols", ()))
    if node.op == "with_column":
        return None
    if node.op == "range_stats":
        pick = node.param("colsToSummarize")
        return list(pick) if pick else None
    if node.op == "ema":
        return [node.param("colName")]
    if node.op == "resample_ema":
        return [node.param("colName")]
    if node.op == "resample":
        pick = node.param("metricCols")
        return list(pick) if pick else None
    if node.op == "calc_bars":
        pick = node.param("metricCols")
        return list(pick) if pick else None
    if node.op == "interpolate":
        pick = node.param("target_cols")
        return list(pick) if pick else None
    if node.op == "fourier":
        return [node.param("valueCol")]
    if node.op in ("collect", "count", "on_mesh", "reshard",
                   "checkpoint"):
        return []
    return None
