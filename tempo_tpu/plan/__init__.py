"""Lazy query planner for TSDF / DistributedTSDF chains.

The reference outsources query planning to Spark's Catalyst (SURVEY §1
L0); the rebuild has every op but executed them op-by-op, so a chain
like ``on_mesh().asofJoin().withRangeStats().EMA().collect()`` only
reached fused-kernel rates when a human called the fused entry points
by hand.  This package is the missing layer:

* :mod:`~tempo_tpu.plan.ir` — deferred op nodes.  When planning is on
  (``TEMPO_TPU_PLAN=1``; eager remains the default), the op methods of
  :class:`~tempo_tpu.frame.TSDF` and
  :class:`~tempo_tpu.dist.DistributedTSDF` record a :class:`~ir.Node`
  instead of executing, and return a lazy wrapper
  (:mod:`~tempo_tpu.plan.lazy`).
* :mod:`~tempo_tpu.plan.optimizer` — rewrite passes over the recorded
  plan: adjacent-node fusion onto the already-shipped fused kernels
  (``resampleEMA``; the single-program mesh join→stats→EMA chain),
  plan-time engine selection (``pick_join_engine`` /
  ``pick_range_engine`` hoisted so knob reads happen once), dead-column
  pruning before packing, and explicit host-materialisation barrier
  marking.
* :mod:`~tempo_tpu.plan.cost` — the cost model behind those decisions
  (round 11; ``TEMPO_TPU_COST_MODEL``): estimated-seconds argmins from
  byte models × measured-rate priors, with the legacy thresholds
  demoted to feasibility priors and the argmin restricted to
  bitwise-equal candidates.  The multi-tenant query service
  (``tempo_tpu/service/``) sits on top of this package.
* :mod:`~tempo_tpu.plan.cache` — compiled executables keyed by
  (optimized-plan signature, source shapes/dtypes, mesh, cost
  fingerprint) with an LRU bound (``TEMPO_TPU_PLAN_CACHE_SIZE``),
  single-flight builds, and hit/miss/evict counters (totals,
  per-signature, per-tenant) surfaced through
  :func:`tempo_tpu.profiling.plan_cache_stats`.
* :mod:`~tempo_tpu.plan.render` — ``explain(cost=False)``: the logical
  and optimized plans, per-node engine choices and barriers, and (with
  ``cost=True``) XLA's post-compilation cost analysis — the analog of
  the reference's ``explain cost`` display path.

Recording is suspended inside the executor (and inside eager internals
that planning must not re-enter) via :func:`suspended`, so replaying a
plan through the eager methods never re-records.
"""

from __future__ import annotations

import contextlib
import contextvars

_SUSPENDED: contextvars.ContextVar[bool] = contextvars.ContextVar(
    "tempo_tpu_plan_suspended", default=False)


def planning_enabled() -> bool:
    """``TEMPO_TPU_PLAN`` truthiness (read live — tests and notebooks
    toggle it mid-process)."""
    from tempo_tpu import config

    return config.get_bool("TEMPO_TPU_PLAN")


def recording() -> bool:
    """Should an op method record a plan node right now?  True only
    when planning is enabled AND no executor/eager-internal frame is on
    the stack (replaying a plan must not re-record)."""
    return not _SUSPENDED.get() and planning_enabled()


@contextlib.contextmanager
def suspended():
    """Run a block with plan recording off (the executor replays plans
    through the eager API inside this; eager methods whose bodies call
    other recorded methods wrap themselves too)."""
    token = _SUSPENDED.set(True)
    try:
        yield
    finally:
        _SUSPENDED.reset(token)
