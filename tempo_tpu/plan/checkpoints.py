"""Plan-integrated checkpoint barriers.

PR-1 gave the batch plane ``run_resumable`` — eager-only, invisible to
the planner, and (until this round) happy to restore a checkpoint
written by a different pipeline.  This module makes checkpointing a
*plan* property instead: inside a :func:`checkpointed` context the
optimizer's ``TEMPO_TPU_CKPT_PLACEMENT`` pass
(:func:`tempo_tpu.plan.optimizer._place_checkpoints`) inserts
first-class ``checkpoint`` nodes at the materialization/reshard
boundaries of the chain, ``explain()`` renders them with estimated
checkpoint bytes, and the executor:

* **saves** each barrier as a ``step_NNNNN`` checkpoint whose manifest
  is stamped with the optimized-plan signature and the predecessor
  barrier's manifest CRC-32 (the chained-manifest scheme the cohort
  differential snapshots introduced);
* **resumes** a re-submitted plan from the newest intact,
  chain-consistent barrier — the whole subtree under it is SKIPPED
  (never re-executed, never re-compiled: the executable comes from the
  plan cache) — and REFUSES by name
  (:class:`~tempo_tpu.resilience.CheckpointError`) to restore a
  barrier stamped by a different plan.

``run_resumable`` is the eager wrapper over the same stamping/refusal
machinery (:func:`tempo_tpu.checkpoint.resolve_step`).

The context is a contextvar, so concurrent planned queries (the query
service) only checkpoint the chains explicitly run inside it.  The
placement spec (``every``) is folded into the executable-cache key
(:func:`fingerprint`), the *directory* is read at run time — one cached
executable serves any number of checkpoint directories.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class CheckpointSpec:
    """Active barrier policy: where step checkpoints land, how often a
    boundary gets one, and how many are retained."""

    ckpt_dir: str
    every: int = 1
    keep_last: int = 3
    sharded: bool = False


_ACTIVE: contextvars.ContextVar[Optional[CheckpointSpec]] = \
    contextvars.ContextVar("tempo_tpu_plan_ckpt", default=None)


def active() -> Optional[CheckpointSpec]:
    """The live :class:`CheckpointSpec`, or None outside any
    :func:`checkpointed` context."""
    return _ACTIVE.get()


@contextlib.contextmanager
def checkpointed(ckpt_dir, every: int = 1, keep_last: int = 3,
                 sharded: bool = False):
    """Run planned chains with checkpoint barriers: every ``every``-th
    materialization boundary (and the reshard boundaries / the final
    pre-collect frame) becomes a signed ``step_NNNNN`` checkpoint under
    ``ckpt_dir``; re-running the SAME chain inside the context resumes
    from the newest intact barrier and re-executes only the ops above
    it."""
    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    spec = CheckpointSpec(str(ckpt_dir), int(every), int(keep_last),
                          bool(sharded))
    token = _ACTIVE.set(spec)
    try:
        yield spec
    finally:
        _ACTIVE.reset(token)


def placement_mode() -> str:
    """``TEMPO_TPU_CKPT_PLACEMENT`` — ``auto`` (default: barriers at
    materialization/reshard boundaries of chains run inside a
    :func:`checkpointed` context) or ``off`` (no plan barriers; the
    context then has no effect on planned chains)."""
    from tempo_tpu import config

    mode = (config.get("TEMPO_TPU_CKPT_PLACEMENT") or "auto")
    mode = mode.strip().lower()
    return mode if mode in ("auto", "off") else "auto"


def fingerprint() -> Optional[tuple]:
    """Executable-cache key component: barrier placement changes the
    optimized plan, so a chain planned inside a checkpointed context
    must never replay the barrier-free executable (or vice versa).
    Directory/retention are runtime-only and stay out of the key."""
    spec = active()
    if spec is None or placement_mode() == "off":
        return None
    return ("ckpt", spec.every)


def source_fingerprint(frame) -> str:
    """Content fingerprint of one source frame, folded into the
    stamped barrier signature.  The plan signature alone covers only
    STRUCTURE — without this, re-running the same chain over
    different same-shape data inside the same checkpoint directory
    would silently restore the previous data's barriers (exactly the
    stale-restore hazard the refusal semantics exist for).

    Content-derived (host frames: ``pd.util.hash_pandas_object``;
    distributed frames: CRC over every fetched plane + the layout), so
    it is stable across process restarts — a crash-resumed pipeline
    that re-ingests the same bytes matches its own barriers.  Memoized
    on the frame (frames are immutable), so repeated submissions of a
    live frame pay the O(data) fetch once."""
    cached = getattr(frame, "_plan_ckpt_fp", None)
    if cached is not None:
        return cached
    import hashlib

    import jax
    import numpy as np

    from tempo_tpu.dist import DistributedTSDF

    h = hashlib.sha1()

    def eat(a):
        h.update(np.ascontiguousarray(np.asarray(a)).tobytes())

    if isinstance(frame, DistributedTSDF):
        h.update(repr(("dist", tuple(frame.cols), frame.ts_col,
                       tuple(frame.partitionCols),
                       frame.seq_col or "")).encode())
        if jax.process_count() > 1:
            # multi-process arrays span non-addressable devices — a
            # global fetch is illegal here.  Fall back to the
            # host-resident layout (keys + per-series lengths): weaker
            # (same-layout different-value frames collide) but every
            # process computes the same stamp without a collective.
            h.update(repr(("multiprocess",
                           tuple(int(s) for s in frame.ts.shape))
                          ).encode())
        else:
            eat(frame.ts)
            eat(frame.mask)
            if frame.seq is not None:
                eat(frame.seq)
            for col in frame.cols.values():
                eat(col.values)
                eat(col.valid)
                if col.host_gather is not None:
                    _vals, starts, perm = col.host_gather
                    h.update(repr(len(_vals)).encode())
                    eat(starts)
                    eat(perm)
        eat(frame.layout.starts)
        h.update(frame.layout.key_frame.to_json().encode())
    else:
        import pandas as pd

        h.update(repr(("host", tuple(frame.df.columns), frame.ts_col,
                       tuple(frame.partitionCols),
                       frame.sequence_col or "")).encode())
        eat(pd.util.hash_pandas_object(frame.df, index=False).to_numpy())
    fp = h.hexdigest()[:16]
    try:
        frame._plan_ckpt_fp = fp
    except AttributeError:  # pragma: no cover - slotted frame class
        pass
    return fp

